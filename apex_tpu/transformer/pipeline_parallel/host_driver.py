"""Host-driven (MPMD) pipeline: per-stage jitted programs, 1F1B from
the host — the multi-slice design.

≡ the reference's schedule engine running OUTSIDE the compiled graph:
forward_backward_pipelining_without_interleaving drives per-stage
modules from Python, moving activations with batched isend/irecv
(apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py + p2p_communication.py:
385-690).  The SPMD schedule in schedules.py compiles the WHOLE
pipeline into one program with `ppermute` hops — ideal within an ICI
domain; a DCN-spanning (multi-slice / multi-host) pipeline cannot live
in one program, so this driver is the second design SURVEY §7 names:

  * each stage is its OWN jitted (fwd, bwd) pair, pinned to its device
    (one slice / host in production; distinct devices of the local
    platform here);
  * activations/cotangents cross stages as host-initiated
    `jax.device_put` transfers (≡ the NCCL send/recv pairs; over DCN
    this is where the transfer library plugs in);
  * the host runs a dependency-driven 1F1B: ready backwards first
    (later stages first, so cotangents flow a hop per sweep), then
    ready forwards, with a HARD per-stage in-flight cap of
    n_stage - i saved inputs — the exact 1F1B activation bound (the
    last stage never holds more than one), asserted per stage in
    tests/test_host_pipeline.py;
  * dispatch is async — device k executes microbatch m's forward while
    device k-1 already runs m+1 — so the host loop pipelines for real
    even though it is plain Python.

The backward of a stage is recompute-based: bwd_i(params, x, dy)
re-runs the stage forward under jax.vjp inside ONE jitted program (the
standard remat trade: no cross-program residuals need to move between
fwd and bwd programs beyond the saved stage INPUT).

Gradient accumulation across microbatches happens on each stage's own
device; the final per-stage grads never leave their slice (the
optimizer is expected to be stage-local, ≡ the reference where each
rank's optimizer owns its stage's params).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp


class HostPipelineStage:
    """One pipeline stage: `apply(params, x) -> y` compiled twice —
    forward-only and forward+vjp — and pinned to `device`.  The LAST
    stage's apply must return a scalar loss (positional contract, as in
    the reference's schedule engine)."""

    def __init__(self, apply_fn: Callable, device=None):
        self.apply_fn = apply_fn
        self.device = device

        def fwd(params, x):
            return apply_fn(params, x)

        def bwd(params, x, dy):
            y, vjp = jax.vjp(apply_fn, params, x)
            dparams, dx = vjp(dy)
            return dparams, dx

        def loss_bwd(params, x):
            # last stage: scalar loss; seed cotangent 1.0
            loss, vjp = jax.vjp(apply_fn, params, x)
            dparams, dx = vjp(jnp.ones_like(loss))
            return loss, dparams, dx

        # placement comes from the COMMITTED inputs (put() pins both
        # params and activations to this stage's device), not from the
        # deprecated jit(device=...) argument
        self._fwd = jax.jit(fwd)
        self._bwd = jax.jit(bwd)
        self._loss_bwd = jax.jit(loss_bwd)
        self._accum = jax.jit(
            lambda acc, g: jax.tree_util.tree_map(jnp.add, acc, g))

    def put(self, x):
        """Move an activation/cotangent onto this stage's device —
        the DCN/ICI transfer point (≡ p2p isend/irecv)."""
        if self.device is None:
            return x
        return jax.device_put(x, self.device)


def host_pipeline_train_step(stages: Sequence[HostPipelineStage],
                             params_list: Sequence[Any],
                             microbatches: Sequence[Any],
                             schedule: str = "1f1b",
                             return_stats: bool = False):
    """Run one training step over `microbatches` with per-stage jitted
    programs in 1F1B (or fill-drain "gpipe") order.

    stages[-1].apply_fn must return a SCALAR loss (mean over its
    microbatch).  Returns (mean_loss, [per-stage grad pytrees]).

    ≡ forward_backward_pipelining_without_interleaving
    (schedules/fwd_bwd_pipelining_without_interleaving.py): same
    warmup/steady/drain structure, with device_put as the p2p layer.
    """
    n_stage = len(stages)
    n_mb = len(microbatches)
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if n_stage == 0 or n_mb == 0:
        raise ValueError(
            f"need at least one stage and one microbatch, got "
            f"{n_stage} stage(s), {n_mb} microbatch(es)")
    if len(params_list) != n_stage:
        raise ValueError(
            f"params_list has {len(params_list)} entries for "
            f"{n_stage} stages")
    # commit each stage's params to its device once; every jitted call
    # then runs where its inputs live
    params_list = [st.put(p) for st, p in zip(stages, params_list)]

    # per-stage FIFO of saved inputs (the only cross-program residual)
    saved_x: List[List[Any]] = [[] for _ in range(n_stage)]
    in_q: List[List[Any]] = [[] for _ in range(n_stage)]   # awaiting fwd
    dy_q: List[List[Any]] = [[] for _ in range(n_stage)]   # awaiting bwd
    in_q[0] = list(microbatches)
    grads: List[Optional[Any]] = [None] * n_stage
    losses: List[Any] = []
    fwd_done = [0] * n_stage
    bwd_done = [0] * n_stage
    peaks = [0] * n_stage

    # the 1F1B invariant, PER STAGE: stage i keeps at most
    # n_stage - i saved inputs in flight (its warmup depth + 1); gpipe
    # has no cap and holds all n_mb during fill
    def cap(i):
        return n_mb if schedule == "gpipe" else (n_stage - i)

    def do_fwd(i):
        st = stages[i]
        x = st.put(in_q[i].pop(0))
        saved_x[i].append(x)
        peaks[i] = max(peaks[i], len(saved_x[i]))
        fwd_done[i] += 1
        if i < n_stage - 1:
            in_q[i + 1].append(st._fwd(params_list[i], x))
        # the last stage's fwd is fused into its loss_bwd

    def do_bwd(i):
        st = stages[i]
        x = saved_x[i].pop(0)               # FIFO ≡ 1F1B backward order
        if i == n_stage - 1:
            loss, dparams, dx = st._loss_bwd(params_list[i], x)
            losses.append(loss)
        else:
            dy = st.put(dy_q[i].pop(0))
            dparams, dx = st._bwd(params_list[i], x, dy)
        grads[i] = (dparams if grads[i] is None
                    else st._accum(grads[i], dparams))
        bwd_done[i] += 1
        if i > 0:
            dy_q[i - 1].append(dx)

    # dependency-driven sweeps (async dispatch pipelines the devices):
    # each round, every stage runs its ready backward (later stages
    # first, so cotangents flow a full hop per round) and then its
    # ready forward (earlier stages first) — gated by the in-flight cap.
    # gpipe degenerates to fill-then-drain because backwards only
    # become ready once forwards stop being capped (cap = n_mb).
    while bwd_done[0] < n_mb:
        progressed = False
        for i in range(n_stage - 1, -1, -1):
            bwd_ready = (len(saved_x[i]) > 0
                         and (dy_q[i] if i < n_stage - 1
                              else saved_x[i]))
            if schedule == "gpipe" and fwd_done[0] < n_mb:
                bwd_ready = False       # fill first
            if bwd_ready:
                do_bwd(i)
                progressed = True
        for i in range(n_stage):
            if in_q[i] and len(saved_x[i]) < cap(i):
                do_fwd(i)
                progressed = True
        if not progressed:
            raise RuntimeError(
                "host pipeline stalled — schedule invariant violated "
                f"(fwd_done={fwd_done}, bwd_done={bwd_done})")

    mean_loss = sum(jax.device_get(l) for l in losses) / n_mb
    # grads are per-microbatch sums of per-mb means; normalize to the
    # global-batch mean (each stage on its own device)
    scale = 1.0 / n_mb
    grads_out = [
        jax.tree_util.tree_map(lambda g: g * scale, grads[i])
        for i in range(n_stage)
    ]
    if return_stats:
        return mean_loss, grads_out, {
            "peak_in_flight": max(peaks),
            "peak_in_flight_per_stage": peaks,
        }
    return mean_loss, grads_out
