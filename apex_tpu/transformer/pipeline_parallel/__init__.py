"""apex_tpu.transformer.pipeline_parallel ≡ apex/transformer/pipeline_parallel:
stage-to-stage communication, schedules, microbatch utilities."""

from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline,
)
from apex_tpu.transformer.pipeline_parallel import common  # noqa: F401
from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from apex_tpu.transformer.pipeline_parallel import utils  # noqa: F401
from apex_tpu.transformer.pipeline_parallel.common import (  # noqa: F401
    build_model,
    get_params_for_weight_decay_optimization,
)
from apex_tpu.transformer.pipeline_parallel.host_driver import (  # noqa: F401
    HostPipelineStage,
    host_pipeline_train_step,
)
