"""Stage-to-stage activation transfer.

≡ apex/transformer/pipeline_parallel/p2p_communication.py:48-690: the
reference wraps batched NCCL isend/irecv pairs with shape negotiation.
On TPU, stage transfer inside the SPMD pipeline is a single
`lax.ppermute` over the pp mesh axis riding ICI — shapes are static so
the negotiation protocol (168-383) disappears; "async" is XLA's default.

The 8 public ops (recv_forward … send_forward_backward_recv_forward_backward,
p2p_communication.py:385-690) reduce to forward/backward ring shifts:
a send_forward IS everyone's recv_forward under SPMD.

For DCN-spanning (multi-slice / multi-host) pipelines, where one
compiled program cannot cover all stages, use the HOST-DRIVEN driver
instead: `pipeline_parallel.host_driver` runs per-stage jitted
programs in 1F1B order with `device_put` as the transfer layer — the
full equivalent of the reference's send/recv-driven schedule engine.
"""

from __future__ import annotations

import jax
from jax import lax

from apex_tpu.parallel.mesh import PP_AXIS


def _shift(x, axis_name, delta):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + delta) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def send_forward_recv_forward(x, axis_name: str = PP_AXIS):
    """Shift activations one stage forward (stage i → i+1).
    ≡ send_forward + recv_forward (p2p_communication.py:385-475)."""
    return _shift(x, axis_name, +1)


def send_backward_recv_backward(g, axis_name: str = PP_AXIS):
    """Shift gradients one stage backward (stage i → i-1).
    ≡ send_backward + recv_backward (p2p_communication.py:478-568)."""
    return _shift(g, axis_name, -1)


# aliases matching the reference op names; under SPMD each pair is one op
recv_forward = send_forward = send_forward_recv_forward
recv_backward = send_backward = send_backward_recv_backward


def send_forward_backward_recv_forward_backward(x, g,
                                                axis_name: str = PP_AXIS):
    """≡ p2p_communication.py:571-690 (the fused steady-state 1F1B op)."""
    return _shift(x, axis_name, +1), _shift(g, axis_name, -1)


class FutureTensor:
    """≡ p2p_communication.FutureTensor (p2p_communication.py:34-45): the
    reference pairs a tensor with an outstanding NCCL request to overlap
    communication with compute.  XLA arrays are ALREADY futures (async
    dispatch): `get()` is just a block-until-ready, kept so schedule code
    written against the reference API ports over unchanged."""

    def __init__(self, tensor):
        self.tensor = tensor

    def get(self):
        t = self.tensor
        return t.block_until_ready() if hasattr(t, "block_until_ready") else t
