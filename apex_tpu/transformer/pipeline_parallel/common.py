"""Schedule-independent pipeline helpers.

≡ apex/transformer/pipeline_parallel/schedules/common.py: model-chunk
construction with pre/post-process placement (build_model, common.py:30-149),
the per-microbatch forward/backward steps (253-403), output freeing /
direct-engine backward (199-250), and the weight-decay param split (162).

In the SPMD pipeline (apex_tpu.transformer.pipeline_parallel.schedules)
set_input_tensor / p2p handoff is built into the clocked scan, and XLA's
buffer donation replaces manual output freeing — the helpers here keep
the reference's call shape for drivers written against it.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.parallel import mesh as _mesh

__all__ = [
    "build_model", "forward_step", "backward_step", "free_output_tensor",
    "custom_backward", "get_params_for_weight_decay_optimization",
]


def build_model(model_provider_func: Callable, wrap_with_ddp: bool = True,
                virtual_pipeline_model_parallel_size: Optional[int] = None,
                stage: Optional[int] = None, *args, **kwargs) -> List[Any]:
    """Construct this pipeline stage's model chunk(s).

    ≡ build_model (schedules/common.py:30-149): calls
    `model_provider_func(*args, pre_process=..., post_process=..., **kwargs)`
    once per virtual chunk this stage owns; pre_process is True only for
    the chunk occupying the first pipeline stage (embedding lives there),
    post_process only for the last (LM head / loss).  The encoder/decoder
    split-rank variant applies the same placement rule around
    `pipeline_model_parallel_split_rank`.

    `wrap_with_ddp` has no wrapper object here — data-parallel gradient
    sync is a `psum` inserted by the train-step builder
    (apex_tpu/parallel/ddp.py), so the flag only records intent (the
    reference wraps each chunk in torchDDP, common.py:138-148).

    `stage` is this controller's pipeline stage.  Multi-controller
    drivers pass it explicitly; under the single-controller SPMD
    pipeline one process owns every stage (the schedule stacks stage
    params), so the default builds stage 0's chunks — call once per
    stage to materialize the whole pipe.
    """
    pp = _mesh.get_pipeline_model_parallel_world_size()
    if stage is None:
        stage = 0
    vpp = virtual_pipeline_model_parallel_size
    if vpp is not None and pp <= 2:
        # Reference asserts pp > 2 for interleaving (common.py:49-54).
        raise ValueError(
            "virtual pipeline parallelism requires pipeline_model_parallel_"
            "size > 2 (≡ schedules/common.py assertion)")
    num_chunks = vpp if vpp is not None else 1
    total_stages = pp * num_chunks
    models = []
    for chunk in range(num_chunks):
        _mesh.set_virtual_pipeline_model_parallel_rank(chunk)
        # Global position of this (stage, chunk) in the virtual pipeline:
        # interleaved placement — chunk c of stage s is virtual stage
        # c * pp + s (fwd_bwd_pipelining_with_interleaving.py:221-260).
        virtual_stage = chunk * pp + stage
        pre_process = virtual_stage == 0
        post_process = virtual_stage == total_stages - 1
        models.append(model_provider_func(
            *args, pre_process=pre_process, post_process=post_process,
            **kwargs))
    return models


def forward_step(forward_step_func: Callable, batch, model,
                 input_tensor: Optional[jax.Array],
                 num_microbatches: int = 1):
    """One microbatch forward ≡ forward_step (schedules/common.py:253-322).

    `forward_step_func(batch, model) -> (output, loss_func)` — the
    reference contract.  When `input_tensor` is not None this stage is
    not first (set_input_tensor semantics): the activation replaces
    `batch` as forward_step_func's first argument, and the function
    must skip its embedding path for non-first stages.

    On the last stage the loss_func output is divided by
    num_microbatches (common.py:308) so summing per-microbatch losses
    yields a mean.
    """
    feed = batch if input_tensor is None else input_tensor
    output, loss_func = forward_step_func(feed, model)
    if loss_func is None:
        return output, None
    loss = loss_func(output)
    return output, loss / num_microbatches


def backward_step(forward_fn: Callable, params, inputs,
                  output_grad: Optional[jax.Array] = None,
                  grad_scale: Optional[jax.Array] = None):
    """One microbatch backward ≡ backward_step (schedules/common.py:325-403).

    `forward_fn(params, inputs) -> output` (activation or scalar loss).
    Last stage passes output_grad=None and optionally `grad_scale` — the
    GradScaler multiplication the reference applies to the first
    backward's seed (common.py:378-379).  Returns
    (input_grad, param_grads): input_grad is the activation gradient to
    hand to the previous stage (the reference's p2p send_backward).
    """
    output, vjp = jax.vjp(forward_fn, params, inputs)
    if output_grad is None:
        seed = jnp.ones_like(output)
        if grad_scale is not None:
            seed = seed * jnp.asarray(grad_scale, seed.dtype)
    else:
        seed = output_grad
    param_grads, input_grad = vjp(seed)
    return input_grad, param_grads


def free_output_tensor(output_tensors, deallocate_pipeline_outputs=False):
    """≡ free_output_tensor (schedules/common.py:199-216).  XLA owns
    buffer lifetimes; donation of the activation buffers in the jitted
    step is the mechanism that reclaims them.  Kept as a no-op for
    driver parity."""
    return output_tensors


def custom_backward(output, grad_output):
    """≡ custom_backward (schedules/common.py:219-250) — a direct
    autograd-engine call that skips the freed-buffer sanity check.  JAX
    has no engine object; use jax.vjp (see backward_step)."""
    raise NotImplementedError(
        "custom_backward is a CUDA-engine workaround; use backward_step / "
        "jax.vjp in apex_tpu")


def get_params_for_weight_decay_optimization(params,
                                             no_decay_names: Sequence[str] =
                                             ("bias", "norm", "bn", "scale",
                                              "offset")):
    """≡ _get_params_for_weight_decay_optimization (common.py:162-196):
    biases and norm parameters get no weight decay.

    Returns a boolean pytree mask (True = apply weight decay) usable as
    an optimizer `wd_mask`, instead of the reference's two param-group
    dicts (JAX optimizers mask, they don't group).
    """
    def decide(path, leaf):
        p = "/".join(str(k) for k in path).lower()
        if any(n in p for n in no_decay_names):
            return False
        return hasattr(leaf, "ndim") and leaf.ndim >= 2

    return jax.tree_util.tree_map_with_path(decide, params)
