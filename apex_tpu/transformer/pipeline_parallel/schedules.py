"""Pipeline schedules — microbatched fwd+bwd over pp stages.

≡ apex/transformer/pipeline_parallel/schedules/: the reference drives an
imperative MPMD 1F1B schedule (fwd_bwd_pipelining_without_interleaving.py:241-597)
with explicit warmup/steady/cooldown phases, p2p sends, and grad-sync
gating.  The TPU re-design is SPMD: ONE jitted program per train step in
which every stage (a pp mesh coordinate) runs the same clocked loop —
microbatch m enters stage 0 at clock m, activations shift stage→stage
with `ppermute` each clock, and reverse-mode AD of the clocked scan IS
the backward pipeline (gradient ppermutes run in the transposed
direction automatically).  Phase boundaries (warmup = first pp-1 clocks,
cooldown = last pp-1) fall out of the clock arithmetic instead of being
hand-scheduled; overlap of the backward pipe with forward clocks (the
point of 1F1B) is XLA's scheduling domain.  Activation-memory control —
the other point of 1F1B — is `jax.checkpoint` on the stage function
(pass remat_stage=True), matching the reference's partial-checkpointing
knob (fwd_bwd_pipelining_without_interleaving.py:351-362).

The interleaved (virtual-pp) schedule maps to num_model_chunks > 1:
each device holds several non-adjacent layer chunks and the clocked
loop cycles microbatches through chunk 0 of all stages, then chunk 1,
… (≡ fwd_bwd_pipelining_with_interleaving.py:27-744).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import (
    reduce_from_tensor_model_parallel_region as _bcast_from_last)
from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS


def spmd_pipeline(stage_fn: Callable, stage_params, microbatches, *,
                  axis_name: str = PP_AXIS, num_model_chunks: int = 1,
                  remat_stage: bool = False,
                  checkpoint_window: Optional[int] = None,
                  loss_fn: Optional[Callable] = None, loss_args=None):
    """Run `microbatches` through pp × num_model_chunks sequential stages.

    stage_fn(chunk_params, x, chunk_index) -> y — the layers owned by one
    (stage, chunk); shapes of x and y must match (transformer blocks).
    stage_params: pytree whose leaves are stacked over chunks on dim 0
    (leading dim num_model_chunks; pass chunk dim even when 1).
    microbatches: (m, ...) stacked microbatch inputs (the stage-0 feed).

    Without loss_fn, returns (m, ...) outputs "as if" x was passed
    through all stages in order — replicating the full stacked output
    costs O(m × activation) pp-axis traffic, so prefer loss_fn when the
    caller only needs the loss.  With loss_fn(y, loss_args[k]) -> scalar
    it is evaluated ON THE LAST STAGE inside the clocked scan as each
    microbatch completes (so the head/loss work overlaps later clocks)
    and only the SCALAR loss sum crosses the pp axis (≡ the reference,
    which computes loss on the last stage only — schedules/common.py:
    253-322 — and never ships activations backwards).

    checkpoint_window: the 1F1B activation-memory dial (≡ the partial
    activation-checkpoint window of the reference's 1F1B,
    fwd_bwd_pipelining_without_interleaving.py:351-362).  AD of the
    plain clocked scan saves residuals for EVERY clock — GPipe-shaped
    O(m) per-stage activation memory.  A window of w clocks wraps each
    w-clock slice in `jax.checkpoint`: backward recomputes one slice at
    a time, so in-flight residuals are O(w) plus O(clocks/w) saved
    window-boundary carries (one microbatch activation each).  With
    w = pp the peak is O(pp + m/pp) activations — the 1F1B bound — at
    the cost of one extra forward pass of the windowed clocks.  Applies
    to the scalar-loss mode (with loss_fn); the stacked-output mode
    carries the (m, ...) buffer either way.

    Call inside shard_map; this device holds its pp shard of
    stage_params.  Differentiable: AD yields the reverse pipeline.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    total_stages = pp * num_model_chunks
    clocks = m + total_stages - 1

    def one_stage(params, x, chunk):
        fn = stage_fn
        if remat_stage:
            fn = jax.checkpoint(stage_fn)
        return fn(params, x, chunk)

    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    def finish(acc):
        return _broadcast_from_last(acc, stage, pp, axis_name)

    if loss_fn is None:
        acc0 = jnp.zeros((m,) + mb_shape, dtype)

        def collect(acc, y, k, write):
            return lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(k, 0, m - 1), axis=0),
                lambda o: o, acc)
    else:
        acc0 = jnp.zeros((), jnp.float32)

        def collect(acc, y, k, write):
            kk = jnp.clip(k, 0, m - 1)
            args_k = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, kk, axis=0,
                                                   keepdims=False),
                loss_args)
            return acc + lax.cond(
                write, lambda: loss_fn(y, args_k).astype(jnp.float32),
                lambda: jnp.zeros((), jnp.float32))

    if num_model_chunks == 1:
        def clock1(carry, t):
            x_in, acc = carry
            feed = lax.dynamic_index_in_dim(
                microbatches, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
            x = jnp.where(stage == 0, feed, x_in)
            params0 = jax.tree_util.tree_map(lambda l: l[0], stage_params)
            y = one_stage(params0, x, 0)
            k = t - (pp - 1)  # microbatch index completing at last stage
            write = jnp.logical_and(stage == pp - 1,
                                    jnp.logical_and(k >= 0, k < m))
            acc = collect(acc, y, k, write)
            x_next = _ring_shift(y, axis_name, +1)
            return (x_next, acc), None

        x0 = jnp.zeros(mb_shape, dtype)
        (xf, acc) = _scan_clocks(clock1, (x0, acc0), clocks,
                                 checkpoint_window)
        return finish(acc)

    # interleaved: iterate chunks sequentially per clock with a ring
    # shift after each chunk (chunk boundary stage pp-1 → stage 0)
    def clockN(carry, t):
        xs, acc = carry  # xs: (chunks,) stacked stage inputs
        new_xs = []
        for c in range(num_model_chunks):
            x = xs[c]
            if c == 0:
                feed = lax.dynamic_index_in_dim(
                    microbatches, jnp.clip(t, 0, m - 1), axis=0,
                    keepdims=False)
                x = jnp.where(stage == 0, feed, x)
            params_c = jax.tree_util.tree_map(lambda l: l[c], stage_params)
            y = one_stage(params_c, x, c)
            k = t - c * pp - stage
            valid = jnp.logical_and(k >= 0, k < m)
            y = jnp.where(valid, y, x)
            if c == num_model_chunks - 1:
                kk = t - (pp * num_model_chunks - 1)
                write = jnp.logical_and(stage == pp - 1,
                                        jnp.logical_and(kk >= 0, kk < m))
                acc = collect(acc, y, kk, write)
            shifted = _ring_shift(y, axis_name, +1)
            new_xs.append(shifted)
        # routing for next clock: stage s>0 chunk c reads chunk c's shift
        # from stage s-1; stage 0 chunk c>0 reads chunk c-1's wrap from
        # stage pp-1 (the same ring shift); stage 0 chunk 0 is re-fed.
        nxt = [new_xs[0]]
        for c in range(1, num_model_chunks):
            nxt.append(jnp.where(stage == 0, new_xs[c - 1], new_xs[c]))
        return (jnp.stack(nxt), acc), None

    xs0 = jnp.zeros((num_model_chunks,) + mb_shape, dtype)
    (xsf, acc) = _scan_clocks(clockN, (xs0, acc0), clocks,
                              checkpoint_window)
    return finish(acc)


def _scan_clocks(clock_fn, carry0, clocks, checkpoint_window):
    """Scan the clock loop, optionally in `jax.checkpoint`ed windows.

    Padding clocks past `clocks` are no-ops by construction: the
    feed index is clipped, the k/kk validity windows gate every write,
    and the extra ring shifts rotate ignored buffers."""
    if not checkpoint_window or checkpoint_window >= clocks:
        carry, _ = lax.scan(clock_fn, carry0, jnp.arange(clocks))
        return carry
    w = checkpoint_window
    n_win = -(-clocks // w)

    def window(carry, ts_w):
        carry, _ = lax.scan(clock_fn, carry, ts_w)
        return carry, None

    carry, _ = lax.scan(jax.checkpoint(window), carry0,
                        jnp.arange(n_win * w).reshape(n_win, w))
    return carry


def _broadcast_from_last(out, stage, pp, axis_name):
    """Replicate the last stage's output buffer to every stage with the
    psum-fwd/identity-bwd pair: each stage seeds its own loss cotangent
    in backward, so only the last stage's flows into the pipeline (the
    others hit the zero mask) — no double counting."""
    masked = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
    return _bcast_from_last(masked, axis_name)


def _ring_shift(x, axis_name, delta):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + delta) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# ------------------------- reference-shaped drivers -------------------------

def forward_backward_no_pipelining(forward_step_func, batch, model_params, *,
                                   num_microbatches: int,
                                   grad_fn: Optional[Callable] = None,
                                   main_grad_dtype=None,
                                   metrics=None, tokens_per_step=None,
                                   rank_timing=None,
                                   rank_timing_axis: str = DP_AXIS):
    """≡ fwd_bwd_no_pipelining.py:23-120: loop microbatches, average loss
    and accumulate grads (no_sync semantics are implicit — grads sync
    when the caller psums them once after this returns).

    forward_step_func(params, microbatch) -> scalar loss.
    batch: pytree with leading dim num_microbatches.
    Returns (mean_loss, grads) via value_and_grad.

    metrics: optional `monitor.MetricsState` — when passed, the return
    becomes (mean_loss, grads, new_metrics) with loss, the LOCAL grad
    norm (grads here are this shard's pre-psum accumulation — the
    caller syncs after this returns, so under dp>1 this is NOT the
    global post-sync norm the ddp path records), and token count
    (tokens_per_step, or inferred from the microbatched batch) folded
    in on-device; this path holds no scaler/optimizer state, so those
    fields carry over unchanged — when a downstream
    `FP16_Optimizer.step(metrics=...)` also runs each iteration, give
    it metrics_count_step=False so the step counter advances once.
    When omitted the function is byte-for-byte the old one.

    rank_timing: this rank's (k,) host-measured duration vector (by
    convention `monitor.trace.TIMING_FIELDS` — per-phase durations the
    driver timed around the previous iteration).  The gathered
    (n_ranks, k) matrix is appended as the LAST return value via one
    all_gather over `rank_timing_axis` — the cross-rank plane of the
    numerics flight recorder (feed `trace.StragglerDetector`).  Call
    inside shard_map with that axis bound.  Omitted (default): no
    collective, no extra output.

    main_grad_dtype: None keeps the historical path — AD through the
    microbatch scan, whose cotangent carry (and therefore the
    accumulator) lives in each param's OWN dtype: with bf16 params every
    microbatch add rounds to 8 mantissa bits.  A floating dtype (float32
    is the mode Apex guarantees: the wgrad GEMM accumulates into a
    persistent fp32 `main_grad`, reference
    transformer/tensor_parallel/layers.py:415-428) switches to explicit
    per-microbatch value_and_grad with the running sum held in that
    dtype; the returned grads ARE the main grads (mean over
    microbatches, in main_grad_dtype).  Cost: the per-leaf cast+add
    chain and an fp32 grad buffer — measured step-time numbers in
    docs/PERF.md (round 6).
    """
    def finish(loss, grads):
        out = (loss, grads)
        if metrics is not None:
            from apex_tpu.monitor import metrics as _mon
            tokens = tokens_per_step if tokens_per_step is not None else \
                _mon.infer_tokens_per_step(batch, microbatch_dims=1)
            out = out + (_mon.update_metrics(
                metrics, loss=loss, grads=grads, tokens=tokens),)
        if rank_timing is not None:
            from apex_tpu.monitor.trace import taps as _trc
            out = out + (_trc.gather_rank_timings(rank_timing,
                                                  rank_timing_axis),)
        return out

    if main_grad_dtype is None:
        def total_loss(p):
            acc, _ = lax.scan(
                lambda a, mb: (a + forward_step_func(p, mb), None),
                jnp.zeros((), jnp.float32), batch)
            return acc / num_microbatches

        loss, grads = jax.value_and_grad(total_loss)(model_params)
        return finish(loss, grads)

    dt = jnp.dtype(main_grad_dtype)

    def body(carry, mb):
        loss_acc, g_acc = carry
        loss, g = jax.value_and_grad(
            lambda p: forward_step_func(p, mb))(model_params)
        g_acc = jax.tree_util.tree_map(
            lambda a, gg: a + gg.astype(dt), g_acc, g)
        return (loss_acc + loss.astype(jnp.float32), g_acc), None

    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), dt), model_params)
    (loss, grads), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros), batch)
    inv = 1.0 / num_microbatches
    grads = jax.tree_util.tree_map(lambda g: g * jnp.asarray(inv, dt),
                                   grads)
    return finish(loss * inv, grads)


def forward_backward_pipelining_without_interleaving(
        stage_fn, stage_params, microbatches, loss_fn, *,
        axis_name: str = PP_AXIS, remat_stage: bool = False,
        checkpoint_window: Optional[int] = None):
    """1F1B-equivalent SPMD pipeline ≡
    fwd_bwd_pipelining_without_interleaving.py:241-597.

    Returns mean loss over microbatches; differentiate the whole thing
    for the backward pipeline.  loss_fn(y_microbatch) -> scalar,
    evaluated on the last stage inside the scan (scalar pp traffic
    only).
    """
    total = spmd_pipeline(stage_fn, stage_params, microbatches,
                          axis_name=axis_name, remat_stage=remat_stage,
                          checkpoint_window=checkpoint_window,
                          loss_fn=lambda y, _: loss_fn(y), loss_args=None)
    return total / microbatches.shape[0]


def forward_backward_pipelining_with_interleaving(
        stage_fn, stage_params, microbatches, loss_fn, *,
        num_model_chunks: int, axis_name: str = PP_AXIS,
        remat_stage: bool = False,
        checkpoint_window: Optional[int] = None):
    """Interleaved/virtual-pp schedule ≡
    fwd_bwd_pipelining_with_interleaving.py:27-744."""
    total = spmd_pipeline(stage_fn, stage_params, microbatches,
                          axis_name=axis_name,
                          num_model_chunks=num_model_chunks,
                          remat_stage=remat_stage,
                          checkpoint_window=checkpoint_window,
                          loss_fn=lambda y, _: loss_fn(y), loss_args=None)
    return total / microbatches.shape[0]


def get_forward_backward_func(virtual_pipeline_model_parallel_size,
                              pipeline_model_parallel_size):
    """≡ schedules/__init__.py:22-38 selector."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
