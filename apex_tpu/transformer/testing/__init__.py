"""apex_tpu.transformer.testing ≡ apex/transformer/testing: standalone
models, toy modules, arg parsing, and global state for tests/harnesses."""

from apex_tpu.transformer.testing.commons import (  # noqa: F401
    IdentityLayer,
    MyLayer,
    MyModel,
    ToyParallelMLP,
    set_random_seed,
)
from apex_tpu.transformer.testing.global_vars import (  # noqa: F401
    get_args,
    get_timers,
    set_global_variables,
)

# standalone flagship models live in apex_tpu.models; aliased here for
# layout parity with the reference (standalone_gpt.py / standalone_bert.py)
from apex_tpu.models.gpt import GPT as StandaloneGPT  # noqa: F401
from apex_tpu.models.bert import Bert as StandaloneBert  # noqa: F401
