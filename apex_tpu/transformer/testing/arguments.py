"""Megatron-style argument parser.

≡ apex/transformer/testing/arguments.py:23-43 (parse_args with 14
_add_*_args groups).  Same flag surface (the subset meaningful on TPU;
CUDA-only knobs are accepted and ignored for drop-in script parity).
"""

from __future__ import annotations

import argparse
import os


def parse_args(extra_args_provider=None, defaults={},
               ignore_unknown_args=False):
    """≡ arguments.parse_args (arguments.py:23-103)."""
    parser = argparse.ArgumentParser(
        description="apex_tpu Arguments", allow_abbrev=False)
    parser = _add_network_size_args(parser)
    parser = _add_regularization_args(parser)
    parser = _add_training_args(parser)
    parser = _add_initialization_args(parser)
    parser = _add_learning_rate_args(parser)
    parser = _add_checkpointing_args(parser)
    parser = _add_mixed_precision_args(parser)
    parser = _add_distributed_args(parser)
    parser = _add_validation_args(parser)
    parser = _add_data_args(parser)
    parser = _add_autoresume_args(parser)
    parser = _add_biencoder_args(parser)
    parser = _add_vit_args(parser)
    parser = _add_logging_args(parser)
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)
    if ignore_unknown_args:
        args, _ = parser.parse_known_args()
    else:
        args = parser.parse_args()
    for key, value in defaults.items():
        if getattr(args, key, None) is None:
            setattr(args, key, value)
    args.rank = int(os.getenv("RANK", "0"))
    args.world_size = int(os.getenv("WORLD_SIZE", "1"))
    if args.num_attention_heads and args.hidden_size:
        args.kv_channels = args.hidden_size // args.num_attention_heads
    return args


def _add_network_size_args(parser):
    g = parser.add_argument_group(title="network size")
    g.add_argument("--num-layers", type=int, default=None)
    g.add_argument("--hidden-size", type=int, default=None)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    return parser


def _add_regularization_args(parser):
    g = parser.add_argument_group(title="regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    g = parser.add_argument_group(title="training")
    g.add_argument("--micro-batch-size", type=int, default=None)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--train-iters", type=int, default=None)
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--tensorboard-dir", type=str, default=None)
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd", "lamb", "novograd", "adagrad"])
    g.add_argument("--use-flash-attention", action="store_true")
    return parser


def _add_initialization_args(parser):
    g = parser.add_argument_group(title="initialization")
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    return parser


def _add_learning_rate_args(parser):
    g = parser.add_argument_group(title="learning rate")
    g.add_argument("--lr", type=float, default=None)
    g.add_argument("--lr-decay-style", type=str, default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--min-lr", type=float, default=0.0)
    return parser


def _add_checkpointing_args(parser):
    g = parser.add_argument_group(title="checkpointing")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--no-save-optim", action="store_true", default=None)
    g.add_argument("--no-load-optim", action="store_true", default=None)
    return parser


def _add_mixed_precision_args(parser):
    g = parser.add_argument_group(title="mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    return parser


def _add_distributed_args(parser):
    g = parser.add_argument_group(title="distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--distributed-backend", default="xla",
                   choices=["nccl", "gloo", "ucc", "xla"])
    g.add_argument("--local_rank", type=int, default=None)
    g.add_argument("--use-cpu-initialization", action="store_true",
                   default=None)
    return parser


def _add_validation_args(parser):
    g = parser.add_argument_group(title="validation")
    g.add_argument("--eval-iters", type=int, default=100)
    g.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    g = parser.add_argument_group(title="data and dataloader")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--seq-length", type=int, default=None)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--vocab-size", type=int, default=None)
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
    return parser


def _add_autoresume_args(parser):
    g = parser.add_argument_group(title="autoresume")
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--adlr-autoresume-interval", type=int, default=1000)
    return parser


def _add_biencoder_args(parser):
    g = parser.add_argument_group(title="biencoder")
    g.add_argument("--ict-head-size", type=int, default=None)
    return parser


def _add_vit_args(parser):
    g = parser.add_argument_group(title="vit")
    g.add_argument("--num-classes", type=int, default=1000)
    g.add_argument("--img-dim", type=int, default=224)
    g.add_argument("--patch-dim", type=int, default=16)
    return parser


def _add_logging_args(parser):
    g = parser.add_argument_group(title="logging")
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    return parser
