"""Global args/timers registry.

≡ apex/transformer/testing/global_vars.py:26-60: the Megatron-style
global `args`, timers, and tensorboard-writer singletons.
"""

from __future__ import annotations

from apex_tpu.utils.timers import Timers

_GLOBAL_ARGS = None
_GLOBAL_TIMERS = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_AUTORESUME = None


def get_args():
    """≡ global_vars.get_args."""
    assert _GLOBAL_ARGS is not None, "args is not initialized."
    return _GLOBAL_ARGS


def get_timers():
    assert _GLOBAL_TIMERS is not None, "timers is not initialized."
    return _GLOBAL_TIMERS


def get_tensorboard_writer():
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    return _GLOBAL_AUTORESUME


def set_global_variables(args=None, extra_args_provider=None, defaults={},
                         ignore_unknown_args=False):
    """≡ global_vars.set_global_variables (26-47)."""
    global _GLOBAL_ARGS, _GLOBAL_TIMERS
    if args is None:
        from apex_tpu.transformer.testing.arguments import parse_args
        args = parse_args(extra_args_provider, defaults,
                          ignore_unknown_args)
    _GLOBAL_ARGS = args
    _GLOBAL_TIMERS = Timers()
    return args
