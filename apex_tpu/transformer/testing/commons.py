"""Test-support modules and helpers.

≡ apex/transformer/testing/commons.py:44-291: toy models
(MyLayer/MyModel/ToyParallelMLP), IdentityLayer, deterministic seeding.
The process-spawning DistributedTestBase (distributed_test_base.py:22-126)
has no TPU analogue — the 8-device virtual CPU mesh in tests/conftest.py
replaces multi-process NCCL spawning entirely.
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
)


def set_random_seed(seed: int):
    """≡ commons.set_random_seed (commons.py:242)."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


class IdentityLayer:
    """≡ commons.IdentityLayer (commons.py:233): a learnable tensor."""

    def __init__(self, size, scale=1.0):
        self.size = size
        self.scale = scale

    def init(self, key):
        return {"weight": self.scale * jax.random.normal(key, self.size)}

    def apply(self, params):
        return params["weight"]


class MyLayer:
    """≡ commons.MyLayer: one linear, shape-preserving."""

    def __init__(self, hidden_size):
        self.hidden_size = hidden_size

    def init(self, key):
        return {"w": jax.random.normal(key, (self.hidden_size,
                                             self.hidden_size)) * 0.1,
                "b": jnp.zeros((self.hidden_size,))}

    def apply(self, params, x):
        return x @ params["w"] + params["b"]


class MyModel:
    """≡ commons.MyModel: stacked MyLayers (pipeline test fodder)."""

    def __init__(self, hidden_size, num_layers=1):
        self.layers = [MyLayer(hidden_size) for _ in range(num_layers)]

    def init(self, key):
        ks = jax.random.split(key, len(self.layers))
        return [l.init(k) for l, k in zip(self.layers, ks)]

    def apply(self, params, x):
        for l, p in zip(self.layers, params):
            x = l.apply(p, x)
        return x


class ToyParallelMLP:
    """≡ commons.ToyParallelMLP (commons.py:44-155): col→gelu→row."""

    def __init__(self, hidden_size, sequence_parallel=False):
        self.col = ColumnParallelLinear(hidden_size, 4 * hidden_size,
                                        gather_output=False,
                                        sequence_parallel=sequence_parallel)
        self.row = RowParallelLinear(4 * hidden_size, hidden_size,
                                     input_is_parallel=True,
                                     sequence_parallel=sequence_parallel)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"col": self.col.init(k1), "row": self.row.init(k2)}

    def apply(self, params, x):
        return self.row.apply(params["row"],
                              jax.nn.gelu(self.col.apply(params["col"], x)))
