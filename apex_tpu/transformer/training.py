"""Tensor+data-parallel training step builder for transformer models.

≡ the reference's Megatron training driver shape
(tests/L0/run_transformer/test_gpt_minimal.py:146-220 +
schedules/common.py forward/backward_step): one jitted SPMD program per
step — shard-local forward/backward with TP collectives inside autodiff,
dp-pmean of grads, fused optimizer on the LOCAL param shard (each rank
owns and updates exactly its shard — optimizer state is tp-sharded by
construction, which is also the natural ZeRO-over-tp layout).

Chunked compute/collective overlap (ISSUE 18) rides through here
untouched: `GPTConfig.overlap_chunks` reaches the TP layers at model
construction, so the step this builder jits contains the chunked
ppermute-ring / chunked-reduce pipelines (parallel/overlap.py) in
BOTH directions — the custom_vjp spellings keep the backward chunked
under the value_and_grad below, and at chunks == 1 the traced program
is byte-identical to the pre-overlap step.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import flat as F
from apex_tpu.parallel.mesh import DP_AXIS, PP_AXIS


def init_sharded_optimizer(optimizer, model, params, mesh):
    """Create optimizer state over the LOCAL param shards.

    The flat fp32 buffers come out tp-sharded (concat of per-rank local
    flats ⇒ P("tp") on dim 0), replicated over dp.
    """
    specs = model.partition_specs()

    state_struct = jax.eval_shape(
        lambda p: optimizer.init(p), params)  # sets optimizer.spec? no —
    # eval_shape traces on GLOBAL shapes; re-derive the local spec by
    # tracing inside shard_map below (optimizer.init sets .spec there).

    def local_init(p):
        return optimizer.init(p)

    # buffers sharded over tp (dim 0), step replicated
    out_specs = type(state_struct)(*([P()] + [P(("pp", "tp"))] * (len(state_struct) - 1)))
    init_fn = jax.jit(shard_map(local_init, mesh=mesh, in_specs=(specs,),
                                out_specs=out_specs, check_vma=False))
    return init_fn(params)


def make_tp_dp_train_step(model, optimizer, mesh, *,
                          loss_fn: Optional[Callable] = None,
                          donate: bool = True,
                          pp_partial_grads: Optional[bool] = None):
    """Returns step(opt_state, tokens, labels[, key]) ->
    (opt_state, loss).  `loss_fn(params, tokens, labels)` defaults to
    model.loss.  Batch is sharded over dp; params/optimizer over tp.

    pp_partial_grads: whether pp-replicated leaves carry PARTIAL grads
    that must be psum'd over pp (True for pipelined models, whose
    embedding/head grads land on different stages — ≡ the reference's
    embedding-group allreduce).  A non-pipelined model on a pp>1 mesh
    computes COMPLETE identical grads on every stage, where the psum
    would scale them by pp.  Default: infer from the model's
    `pipeline_parallel_size`/`pp` attribute.
    """
    specs = model.partition_specs()
    lf = loss_fn or (lambda p, t, l: model.loss(p, t, l))
    if pp_partial_grads is None:
        pp_partial_grads = max(
            getattr(model, "pp", 1),
            getattr(model, "pipeline_parallel_size", 1)) > 1

    def local_step(opt_state, tokens, labels):
        # NOTE: differentiating w.r.t. the flat param view (so grads
        # arrive pre-flattened) was tried and is ~40% SLOWER: the
        # unflatten-transpose becomes one full-buffer scatter-add per
        # leaf.  Per-leaf grads + one concatenate is the fast shape.
        params = F.unflatten(opt_state.params, optimizer.spec)

        loss, grads = jax.value_and_grad(lambda p: lf(p, tokens, labels))(
            params)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, DP_AXIS), grads)
        if pp_partial_grads:
            # pp-REPLICATED leaves (tied embedding, position embeddings,
            # final LN) get per-stage PARTIAL grads under the pipeline —
            # embed-side on stage 0, head-side on the last stage — so
            # each stage's optimizer copy would diverge without summing
            # them.  ≡ the reference's embedding-group allreduce
            # (parallel_state.py:319-407).
            def _pp_sync(g, spec):
                names = set()
                for entry in spec:  # P is tuple-like: None | str | tuple
                    (names.update(entry) if isinstance(entry, tuple)
                     else names.add(entry))
                if PP_AXIS in names:
                    return g  # pp-sharded leaf: its grad is stage-local
                return jax.lax.psum(g, PP_AXIS)
            grads = jax.tree_util.tree_map(_pp_sync, grads, specs)
        _, new_state = optimizer.step(opt_state, grads)
        return new_state, jax.lax.pmean(loss, DP_AXIS)

    state_spec_leaves = None

    def _state_specs(state):
        return type(state)(*([P()] + [P(("pp", "tp"))] * (len(state) - 1)))

    def build(opt_state):
        out_specs = (_state_specs(opt_state), P())
        in_specs = (_state_specs(opt_state), P(DP_AXIS), P(DP_AXIS))
        return jax.jit(
            shard_map(local_step, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False),
            donate_argnums=(0,) if donate else ())

    # build() depends only on the state STRUCTURE (out_specs count its
    # fields), so the cache is keyed on that; the jitted fn inside
    # re-specializes per input shape/dtype on its own
    cache = {}

    def _jitted_for(opt_state):
        k = jax.tree_util.tree_structure(opt_state)
        fn = cache.get(k)
        if fn is None:
            fn = cache[k] = build(opt_state)
        return fn

    def step(opt_state, tokens, labels):
        return _jitted_for(opt_state)(opt_state, tokens, labels)

    def lower(opt_state, tokens, labels):
        return _jitted_for(opt_state).lower(opt_state, tokens, labels)

    def _cache_size():
        # aggregate over the per-structure jits so RecompileSentry's
        # cache poll sees EVERY compile — including the donated-layout
        # recompile no argument-signature change announces (without
        # this the sentry falls back to signature-only detection and
        # the bench gate would miss that class entirely)
        return sum(fn._cache_size() for fn in cache.values())

    # compile & HBM observatory handles (monitor.compile.analyze_step
    # / RecompileSentry): AOT-audit the exact program, label the
    # budget table, verify donation — see parallel/ddp.py
    step.lower = lower
    step._cache_size = _cache_size
    step.donate_argnums = (0,) if donate else ()
    step.arg_names = ("opt_state", "tokens", "labels")
    # mesh axes for the static linter's collective-axis check
    # (apex_tpu.lint CL201) and the comms observatory's replica-group
    # mapping (monitor.comms, ISSUE 7) — see parallel/ddp.py
    step.mesh_axis_names = tuple(str(a) for a in mesh.axis_names)
    step.mesh_axis_sizes = tuple(int(s) for s in mesh.devices.shape)
    return step
