"""Parallel RNG management + activation checkpointing.

≡ apex/transformer/tensor_parallel/random.py: CudaRNGStatesTracker
(204-235) and CheckpointFunction (237-306).  The TPU translation:

* CUDA RNG states → `jax.random` keys.  The Megatron rule "TP ranks
  share a default seed but diverge on model-parallel-rng with
  seed = base + 2718 + tp_rank" (random.py:248-261) becomes a fold_in
  of the tp coordinate.
* CheckpointFunction (recompute-in-backward with RNG state restore) →
  `jax.checkpoint`: functional RNG keys make the fork/restore dance
  unnecessary — passing the same key to the recomputation reproduces
  dropout exactly.
* distributed activation storage (split_tensor_into_1d_equal_chunks /
  gather_split_1d_tensor, random.py:64-83) → psum_scatter/all_gather
  helpers below.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import TP_AXIS

_MODEL_PARALLEL_RNG = "model-parallel-rng"


def model_parallel_fold_in(key, axis_name: str = TP_AXIS):
    """Per-tp-rank key ≡ seed + 2718 + tp_rank (random.py:248-261).
    Use inside shard_map for rank-divergent init/dropout (TP linears)."""
    return jax.random.fold_in(key, 2718 + lax.axis_index(axis_name))


class RNGStatesTracker:
    """Named key registry ≡ CudaRNGStatesTracker (random.py:204-235)."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed_or_key):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        if isinstance(seed_or_key, int):
            seed_or_key = jax.random.PRNGKey(seed_or_key)
        self.states_[name] = seed_or_key

    def fork(self, name=_MODEL_PARALLEL_RNG):
        """Split off a fresh key under `name` and return it (functional
        analogue of the `with tracker.fork():` context)."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        return sub


_GLOBAL_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """≡ get_cuda_rng_tracker (random.py:194-201)."""
    return _GLOBAL_TRACKER


def model_parallel_seed(seed: int, tracker: Optional[RNGStatesTracker] = None):
    """≡ model_parallel_cuda_manual_seed (random.py:248-261): install the
    default + model-parallel keys into the tracker."""
    t = tracker or _GLOBAL_TRACKER
    t.reset()
    t.add("default", jax.random.PRNGKey(seed))
    t.add(_MODEL_PARALLEL_RNG, jax.random.PRNGKey(seed + 2718))
    return t


def checkpoint(fn, *args, policy=None, prevent_cse: bool = True, **kw):
    """Activation recomputation ≡ CheckpointFunction (random.py:237-306).
    `policy` is a jax.checkpoint_policies member for selective
    checkpointing (≡ partial/selective recompute, arXiv 2205.05198)."""
    return jax.checkpoint(fn, policy=policy, prevent_cse=prevent_cse)(*args,
                                                                      **kw)


def split_tensor_into_1d_equal_chunks(x, axis_name: str = TP_AXIS):
    """Shard a flattened activation over tp for distributed storage
    ≡ random.py:64-72."""
    n = lax.axis_size(axis_name)
    flat = x.reshape(-1)
    per = flat.shape[0] // n
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice(flat, (idx * per,), (per,))


def gather_split_1d_tensor(chunk, axis_name: str = TP_AXIS):
    """Inverse gather ≡ random.py:75-83."""
    return lax.all_gather(chunk, axis_name, axis=0, tiled=True)


# --- distributed (tp-sharded) checkpointed-activation storage ---------------
#
# ≡ init_checkpointed_activations_memory_buffer + the
# distribute_saved_activations branch of CheckpointFunction
# (random.py:48-83, 237-306): the reference carves recomputation inputs
# into a preallocated buffer sharded over tp.  Functionally in JAX:
# shard the saved residuals over tp between fwd and bwd via a
# split/all-gather custom pair; XLA owns the allocation, so the "memory
# buffer" reduces to the sharding transform itself.

def checkpoint_with_distributed_saved_activations(fn, axis_name: str = TP_AXIS):
    """Returns g(x, *args) ≡ checkpoint(fn)(x, *args) where the stored
    residual is the tp-shard of `x` (1/tp_size the memory); the full `x`
    is all-gathered back only when the backward pass recomputes.

    jax.checkpoint saves exactly the *inputs* of the wrapped function,
    so the shard/gather pair is placed across that boundary: the
    checkpointed function receives the small chunk (saved), and
    reconstructs `x` inside (recomputed in bwd).
    """

    from apex_tpu.parallel.collectives import (
        gather_from_sequence_parallel_region_no_tp_grad,
        scatter_to_sequence_parallel_region,
    )

    def g(x, *args):
        # split fwd / all-gather bwd outside; gather fwd / split bwd
        # inside — the Megatron pair keeps replicated activation grads
        # exact (a raw slice+all_gather would zero or tp-multiply dx)
        chunk = scatter_to_sequence_parallel_region(
            x.reshape(-1, 1), axis_name)
        shape, dtype = x.shape, x.dtype

        def inner(ck, *a):
            full = gather_from_sequence_parallel_region_no_tp_grad(
                ck, axis_name)
            return fn(full.reshape(shape).astype(dtype), *a)

        return jax.checkpoint(inner)(chunk, *args)

    return g


def init_checkpointed_activations_memory_buffer(*_args, **_kw):
    """≡ random.py:48-83.  No-op on TPU: XLA preallocates and reuses
    activation memory; the distributed-storage behavior lives in
    checkpoint_with_distributed_saved_activations."""
    return None
