"""Tensor-parallel layers — Column/Row parallel linear, vocab-parallel
embedding.

≡ apex/transformer/tensor_parallel/layers.py: VocabParallelEmbedding
(174-276), ColumnParallelLinear (460-642), RowParallelLinear (645-813),
and the fused LinearWithGradAccumulationAndAsyncCommunication autograd
(217-430).  TPU re-design: the layers are shard-local pure functions
intended to run inside `shard_map` over the global mesh; the Megatron
collective semantics come from the custom_vjp pairs in
parallel/collectives.py.  The reference's async-communication overlap
(async grad allreduce overlapping wgrad, layers.py:344-375) is XLA's
scheduler's job: collectives inside one jitted program are issued
asynchronously over ICI automatically.

Parameters are GLOBAL arrays with a `partition_spec()` per layer
(tensor_model_parallel attributes ≡ layers.py:70-107 become
PartitionSpecs); shard_map hands each device its shard.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel.collectives import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
)
from apex_tpu.parallel.mesh import TP_AXIS


class ColumnParallelLinear:
    """Y = XA + b with A column-sharded over tp: A = [A_1 .. A_p].

    ≡ ColumnParallelLinear (layers.py:460-642).  gather_output re-gathers
    Y along the last dim; sequence_parallel all-gathers the seq-sharded
    input first (layers.py:311-324) — its backward is the reduce-scatter
    of dgrad (405-413 via the collective's custom_vjp).
    """

    def __init__(self, input_size: int, output_size: int, *, bias: bool = True,
                 gather_output: bool = False, sequence_parallel: bool = False,
                 init_std: Optional[float] = None, axis_name: str = TP_AXIS,
                 overlap_chunks=None):
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.gather_output = gather_output
        self.sequence_parallel = sequence_parallel
        self.init_std = init_std
        self.axis_name = axis_name
        # chunked compute/collective overlap (parallel/overlap.py).
        # None = tuner-owned (`overlap_chunks` op, heuristic 1); an int
        # forces the pipeline depth for A/B sweeps.  chunks == 1 keeps
        # the monolithic spelling below byte-identical to pre-overlap.
        self.overlap_chunks = overlap_chunks

    def init(self, key, dtype=jnp.float32):
        std = self.init_std or (1.0 / jnp.sqrt(self.input_size))
        p = {"weight": jax.random.normal(
            key, (self.input_size, self.output_size), dtype) * std}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_size,), dtype)
        return p

    def partition_spec(self):
        spec = {"weight": P(None, self.axis_name)}
        if self.use_bias:
            spec["bias"] = P(self.axis_name)
        return spec

    def apply(self, params, x):
        """Shard-local: params are the LOCAL shards (out dim / tp)."""
        ax = self.axis_name
        from apex_tpu.parallel import overlap as OV
        path = "tp_col" if self.sequence_parallel else "tp_col_copy"
        chunks = OV.layer_chunks(
            self.overlap_chunks, path, x.shape[0],
            params["weight"].shape[-1], ax, x.dtype,
            divisor_of=x.shape[0])
        if chunks > 1:
            if self.sequence_parallel:
                # gather+GEMM as a chunked ppermute ring: each hop
                # hides behind the previous chunk's partial GEMM
                y = OV.ring_gather_matmul(x, params["weight"], ax, chunks)
            else:
                # no forward collective to hide; the fused primitive
                # chunks the BACKWARD dx psum against the dgrad GEMM
                y = OV.copy_matmul(x, params["weight"], ax, chunks)
            if self.use_bias:
                y = y + params["bias"].astype(y.dtype)
            if self.gather_output:
                y = gather_from_tensor_model_parallel_region(y, ax)
            return y
        if self.sequence_parallel:
            x = gather_from_sequence_parallel_region(x, ax)
        else:
            x = copy_to_tensor_model_parallel_region(x, ax)
        y = jnp.dot(x, params["weight"],
                    preferred_element_type=jnp.float32).astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        if self.gather_output:
            y = gather_from_tensor_model_parallel_region(y, ax)
        return y


class RowParallelLinear:
    """Y = XA + b with A row-sharded over tp; partial results summed.

    ≡ RowParallelLinear (layers.py:645-813).  input_is_parallel skips the
    input scatter; sequence_parallel reduce-scatters the output along
    the sequence dim instead of all-reducing (mappings.py:122-138).
    Bias is added AFTER the reduction (once, not per-rank).
    """

    def __init__(self, input_size: int, output_size: int, *, bias: bool = True,
                 input_is_parallel: bool = True,
                 sequence_parallel: bool = False,
                 init_std: Optional[float] = None, axis_name: str = TP_AXIS,
                 overlap_chunks=None):
        if sequence_parallel and not input_is_parallel:
            raise RuntimeError(
                "To enable sequence_parallel, input_is_parallel must be True")
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.input_is_parallel = input_is_parallel
        self.sequence_parallel = sequence_parallel
        self.init_std = init_std
        self.axis_name = axis_name
        # chunked GEMM+reduce pipeline depth — see ColumnParallelLinear
        self.overlap_chunks = overlap_chunks

    def init(self, key, dtype=jnp.float32):
        std = self.init_std or (1.0 / jnp.sqrt(self.input_size))
        p = {"weight": jax.random.normal(
            key, (self.input_size, self.output_size), dtype) * std}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_size,), dtype)
        return p

    def partition_spec(self):
        spec = {"weight": P(self.axis_name, None)}
        if self.use_bias:
            spec["bias"] = P()
        return spec

    def apply(self, params, x):
        ax = self.axis_name
        if not self.input_is_parallel:
            from apex_tpu.parallel.collectives import (
                scatter_to_tensor_model_parallel_region)
            x = scatter_to_tensor_model_parallel_region(x, ax)
        from apex_tpu.parallel import overlap as OV
        if self.sequence_parallel:
            try:
                p = int(lax.axis_size(ax))
            except NameError:
                p = 1
            # the chunked dim is the OUTPUT rows (S/p): each chunk
            # GEMMs the input rows feeding its scatter slice
            div = x.shape[0] // max(1, p)
            path = "tp_row"
        else:
            div = x.shape[0]
            path = "tp_row_ar"
        chunks = OV.layer_chunks(
            self.overlap_chunks, path, x.shape[0],
            params["weight"].shape[-1], ax, x.dtype, divisor_of=div)
        if chunks > 1:
            if self.sequence_parallel:
                y = OV.matmul_reduce_scatter(x, params["weight"], ax,
                                             chunks)
            else:
                y = OV.matmul_all_reduce(x, params["weight"], ax, chunks)
            if self.use_bias:
                bias = params["bias"]
                if self.sequence_parallel:
                    bias = copy_to_tensor_model_parallel_region(bias, ax)
                y = y + bias.astype(y.dtype)
            return y
        y = jnp.dot(x, params["weight"],
                    preferred_element_type=jnp.float32).astype(x.dtype)
        if self.sequence_parallel:
            y = reduce_scatter_to_sequence_parallel_region(y, ax)
        else:
            y = reduce_from_tensor_model_parallel_region(y, ax)
        if self.use_bias:
            bias = params["bias"]
            if self.sequence_parallel:
                # replicated param consumed in a seq-sharded region: its
                # grad is a partial sum per rank and must be psum'd over
                # tp — ≡ the sequence_parallel_enabled param tagging +
                # external allreduce (apex/transformer/layers/layer_norm.py:26-74)
                bias = copy_to_tensor_model_parallel_region(bias, ax)
            y = y + bias.astype(y.dtype)
        return y


class VocabParallelEmbedding:
    """Embedding with the vocab dim sharded over tp.

    ≡ VocabParallelEmbedding (layers.py:174-276): each rank owns rows
    [rank*V/p, (rank+1)*V/p); out-of-range ids are masked to 0, looked
    up locally, the masked outputs zeroed, and the result psum'd.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int, *,
                 init_std: float = 0.02, axis_name: str = TP_AXIS,
                 sequence_parallel: bool = False):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.init_std = init_std
        self.axis_name = axis_name
        self.sequence_parallel = sequence_parallel

    def init(self, key, dtype=jnp.float32):
        return {"weight": jax.random.normal(
            key, (self.num_embeddings, self.embedding_dim), dtype)
            * self.init_std}

    def partition_spec(self):
        return {"weight": P(self.axis_name, None)}

    def apply(self, params, ids):
        """Shard-local; params["weight"] is the LOCAL (V/p, D) shard.
        ids: integer array (replicated or seq-sharded upstream)."""
        ax = self.axis_name
        w = params["weight"]
        vocab_per = w.shape[0]
        rank = lax.axis_index(ax)
        start = rank * vocab_per
        local_ids = ids - start
        valid = (local_ids >= 0) & (local_ids < vocab_per)
        local_ids = jnp.where(valid, local_ids, 0)
        out = jnp.take(w, local_ids, axis=0)
        out = jnp.where(valid[..., None], out, 0.0)
        out = reduce_from_tensor_model_parallel_region(out, ax)
        if self.sequence_parallel:
            # embedding output scatter along seq (Megatron SP entry point)
            from apex_tpu.parallel.collectives import (
                scatter_to_sequence_parallel_region)
            out = scatter_to_sequence_parallel_region(out, ax)
        return out
