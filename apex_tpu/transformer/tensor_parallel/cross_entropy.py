"""Vocab-parallel cross entropy.

≡ _VocabParallelCrossEntropy (apex/transformer/tensor_parallel/cross_entropy.py:23-129):
logits are sharded over the vocab dim on the tp axis; the loss needs
three collectives — max (pmax), sum-exp (psum), and the target-logit
gather via a vocab-range mask (psum).  Label smoothing matches the
reference (cross_entropy.py:100-118).

Two backward strategies:

* unfused (the original): AD through the collectives.  AD of
  `x = logits.astype(f32)` makes the saved residuals fp32 — at the
  GPT bench shapes the (S, B, V) fp32 residual is the single largest
  activation in the step (50304-wide vocab), and its write+read is
  pure HBM traffic the MXU never touches.
* fused (`custom_vjp`, ≡ the reference's hand-written backward and the
  xentropy_cuda kernel, which consumes HALF logits with fp32 internal
  math): forward saves only the COMPUTE-dtype logits plus the fp32
  log-sum-exp row; backward reconstructs softmax(x) − q in fp32
  on the fly and emits the cotangent directly in the logits dtype.
  With bf16 logits this halves the xent residual memory and its HBM
  round trip — the round-6 per-GEMM roofline showed the LM-head+xent
  row's gap to its GEMM roofline was exactly this epilogue traffic
  (docs/PERF.md).

`fused=None` (default) auto-selects: fused for sub-fp32 logits (the
bf16 hot path), unfused for fp32 (bit-identical to previous rounds).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import (
    reduce_from_tensor_model_parallel_region as _reduce)
from apex_tpu.parallel.mesh import TP_AXIS


def _unfused(local_logits, labels, smoothing, axis_name):
    x = local_logits.astype(jnp.float32)
    vocab_per = x.shape[-1]
    rank = lax.axis_index(axis_name)
    start = rank * vocab_per

    # stable logsumexp across shards; the max shift is stability-only so
    # it is detached (pmax has no transpose rule; gradient is unchanged)
    local_max = jnp.max(jax.lax.stop_gradient(x), axis=-1)
    global_max = lax.pmax(local_max, axis_name)
    # Reductions use the psum-fwd/identity-bwd pair (Megatron's "g" op,
    # mappings.py:159-174): the loss is replicated across tp, so every
    # rank seeds the same cotangent and each rank's backward must touch
    # only its local shard — a raw lax.psum would double-count by tp.
    x_shift = x - global_max[..., None]
    local_sum = jnp.sum(jnp.exp(x_shift), axis=-1)
    global_sum = _reduce(local_sum, axis_name)
    lse = jnp.log(global_sum) + global_max

    # target logit: mask ids outside this rank's range (cross_entropy.py:44-63)
    local_ids = labels - start
    valid = (local_ids >= 0) & (local_ids < vocab_per)
    safe_ids = jnp.where(valid, local_ids, 0)
    picked = jnp.take_along_axis(x, safe_ids[..., None], axis=-1)[..., 0]
    target_logit = _reduce(jnp.where(valid, picked, 0.0), axis_name)

    loss = lse - target_logit
    if smoothing > 0:
        # ≡ cross_entropy.py:100-118: mean log prob over the full vocab
        vocab_size = vocab_per * lax.axis_size(axis_name)
        sum_logits = _reduce(jnp.sum(x, axis=-1), axis_name)
        mean_log_prob = sum_logits / vocab_size - lse
        smooth_loss = -mean_log_prob
        loss = (1.0 - smoothing) * loss + smoothing * smooth_loss
    return loss


# ------------------------------ fused path -----------------------------------

def _fused_forward(local_logits, labels, smoothing, axis_name):
    """Primal forward.  Raw collectives are fine here: AD never sees this
    function (custom_vjp), so no transpose double-counting can occur."""
    x = local_logits.astype(jnp.float32)
    vocab_per = x.shape[-1]
    start = lax.axis_index(axis_name) * vocab_per

    local_max = jnp.max(x, axis=-1)
    global_max = lax.pmax(local_max, axis_name)
    local_sum = jnp.sum(jnp.exp(x - global_max[..., None]), axis=-1)
    global_sum = lax.psum(local_sum, axis_name)
    lse = jnp.log(global_sum) + global_max

    local_ids = labels - start
    valid = (local_ids >= 0) & (local_ids < vocab_per)
    safe_ids = jnp.where(valid, local_ids, 0)
    picked = jnp.take_along_axis(x, safe_ids[..., None], axis=-1)[..., 0]
    target_logit = lax.psum(jnp.where(valid, picked, 0.0), axis_name)

    loss = lse - target_logit
    if smoothing > 0:
        vocab_size = vocab_per * lax.axis_size(axis_name)
        sum_logits = lax.psum(jnp.sum(x, axis=-1), axis_name)
        loss = ((1.0 - smoothing) * loss
                + smoothing * (lse - sum_logits / vocab_size))
    return loss, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_xent(local_logits, labels, smoothing, axis_name):
    loss, _ = _fused_forward(local_logits, labels, smoothing, axis_name)
    return loss


def _fused_xent_fwd(local_logits, labels, smoothing, axis_name):
    loss, lse = _fused_forward(local_logits, labels, smoothing, axis_name)
    # residuals: compute-dtype logits + one fp32 row per token — NOT the
    # fp32 upcast of the logits (the AD path's dominant residual)
    return loss, (local_logits, labels, lse)


def _fused_xent_bwd(smoothing, axis_name, res, g):
    local_logits, labels, lse = res
    x = local_logits.astype(jnp.float32)
    vocab_per = x.shape[-1]
    start = lax.axis_index(axis_name) * vocab_per

    # softmax(x) − q, entirely shard-local given the replicated lse; the
    # loss is replicated over tp so every rank holds the same cotangent g
    # and emits only its own shard's gradient (identity-bwd convention).
    p = jnp.exp(x - lse[..., None])
    local_ids = labels - start
    valid = (local_ids >= 0) & (local_ids < vocab_per)
    cols = lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    onehot = (cols == jnp.where(valid, local_ids, -1)[..., None]
              ).astype(jnp.float32)
    q = (1.0 - smoothing) * onehot
    if smoothing > 0:
        q = q + smoothing / (vocab_per * lax.axis_size(axis_name))
    dx = (g[..., None] * (p - q)).astype(local_logits.dtype)
    return dx, None


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


# ------------------------------ public API -----------------------------------

def vocab_parallel_cross_entropy(local_logits, labels, smoothing: float = 0.0,
                                 axis_name: str = TP_AXIS, fused=None):
    """Per-token loss from vocab-sharded logits.

    local_logits: (..., V/p) this rank's shard; labels: (...) global ids.
    fused: None (auto — fused custom_vjp iff logits are sub-fp32),
    True/False to force.  Both paths compute identical fp32 math; the
    fused one saves compute-dtype residuals only (module docstring).
    """
    if fused is None:
        fused = local_logits.dtype != jnp.float32
    if fused:
        return _fused_xent(local_logits, labels, float(smoothing), axis_name)
    return _unfused(local_logits, labels, smoothing, axis_name)
