"""Vocab-parallel cross entropy.

≡ _VocabParallelCrossEntropy (apex/transformer/tensor_parallel/cross_entropy.py:23-129):
logits are sharded over the vocab dim on the tp axis; the loss needs
three collectives — max (pmax), sum-exp (psum), and the target-logit
gather via a vocab-range mask (psum).  Label smoothing matches the
reference (cross_entropy.py:100-118).  Backward is derived by AD through
the collectives (the reference hand-writes it; XLA produces the same
collective pattern).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import (
    reduce_from_tensor_model_parallel_region as _reduce)
from apex_tpu.parallel.mesh import TP_AXIS


def vocab_parallel_cross_entropy(local_logits, labels, smoothing: float = 0.0,
                                 axis_name: str = TP_AXIS):
    """Per-token loss from vocab-sharded logits.

    local_logits: (..., V/p) this rank's shard; labels: (...) global ids.
    """
    x = local_logits.astype(jnp.float32)
    vocab_per = x.shape[-1]
    rank = lax.axis_index(axis_name)
    start = rank * vocab_per

    # stable logsumexp across shards; the max shift is stability-only so
    # it is detached (pmax has no transpose rule; gradient is unchanged)
    local_max = jnp.max(jax.lax.stop_gradient(x), axis=-1)
    global_max = lax.pmax(local_max, axis_name)
    # Reductions use the psum-fwd/identity-bwd pair (Megatron's "g" op,
    # mappings.py:159-174): the loss is replicated across tp, so every
    # rank seeds the same cotangent and each rank's backward must touch
    # only its local shard — a raw lax.psum would double-count by tp.
    x_shift = x - global_max[..., None]
    local_sum = jnp.sum(jnp.exp(x_shift), axis=-1)
    global_sum = _reduce(local_sum, axis_name)
    lse = jnp.log(global_sum) + global_max

    # target logit: mask ids outside this rank's range (cross_entropy.py:44-63)
    local_ids = labels - start
    valid = (local_ids >= 0) & (local_ids < vocab_per)
    safe_ids = jnp.where(valid, local_ids, 0)
    picked = jnp.take_along_axis(x, safe_ids[..., None], axis=-1)[..., 0]
    target_logit = _reduce(jnp.where(valid, picked, 0.0), axis_name)

    loss = lse - target_logit
    if smoothing > 0:
        # ≡ cross_entropy.py:100-118: mean log prob over the full vocab
        vocab_size = vocab_per * lax.axis_size(axis_name)
        sum_logits = _reduce(jnp.sum(x, axis=-1), axis_name)
        mean_log_prob = sum_logits / vocab_size - lse
        smooth_loss = -mean_log_prob
        loss = (1.0 - smoothing) * loss + smoothing * smooth_loss
    return loss
