"""Batch data distribution across the TP group.

≡ apex/transformer/tensor_parallel/data.py broadcast_data (data.py:80):
the reference torch-broadcasts tokenized batches from tp-rank-0 because
each process loads data independently.  Under JAX's single-program SPMD,
every host feeds the same global arrays and the partitioner distributes
them — a broadcast is definitionally a no-op *within* a process.  What
remains meaningful (and is implemented) is the reference's key/dtype
validation, and a multi-host broadcast helper for when hosts load
distinct data (jax.experimental.multihost_utils).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _check_data_types(keys, data, target_dtype):
    """≡ data.py:17-27."""
    for key in keys:
        if data[key].dtype != target_dtype:
            raise ValueError(
                f"{key} has data type {data[key].dtype} which "
                f"is different than {target_dtype}")


def broadcast_data(keys, data, datatype=jnp.int32):
    """≡ broadcast_data (data.py:80-115).  Validates dtypes and returns
    the selected entries; under multi-host, routes through
    multihost_utils so all hosts agree on rank-0's batch."""
    _check_data_types(keys, data, datatype)
    out = {k: jnp.asarray(data[k]) for k in keys}
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        out = {k: multihost_utils.broadcast_one_to_all(v)
               for k, v in out.items()}
    return out
