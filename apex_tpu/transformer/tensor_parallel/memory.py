"""Preallocated memory buffers for checkpointed activations.

≡ apex/transformer/tensor_parallel/memory.py MemoryBuffer/RingMemBuffer
(37-146).  On TPU, XLA owns allocation: buffer reuse is achieved with
donation + static shapes, so these classes are thin functional
equivalents kept for API parity (chunked allocate-from-arena semantics
without the manual pointer math).
"""

from __future__ import annotations

import jax.numpy as jnp


class MemoryBuffer:
    """≡ MemoryBuffer (memory.py:37-107): fixed-size arena handing out
    tensor views.  Functional version: tracks offsets, returns slices."""

    def __init__(self, name, numel, dtype, track_usage=False):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self.data = jnp.zeros((numel,), dtype)
        self._start = 0
        self.in_use_value = 0
        self.total_value = 0
        self.track_usage = track_usage

    def reset(self):
        self._start = 0

    def is_in_use(self):
        return self._start > 0

    def add(self, shape):
        size = 1
        for s in shape:
            size *= int(s)
        if self._start + size > self.numel:
            raise RuntimeError("MemoryBuffer out of space")
        view = self.data[self._start:self._start + size].reshape(shape)
        self._start += size
        if self.track_usage:
            self.in_use_value += size
            self.total_value += size
        return view

    def get_data(self):
        return self.data


class RingMemBuffer:
    """≡ RingMemBuffer (memory.py:110-146): round-robin buffer pool."""

    def __init__(self, name, num_buffers, numel, dtype, track_usage=False):
        self.num_buffers = num_buffers
        self.buffers = [MemoryBuffer(f"{name} {i}", numel, dtype, track_usage)
                        for i in range(num_buffers)]
        self._index = -1

    def get_next_buffer(self):
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        if buf.is_in_use():
            raise RuntimeError("buffer is already in use")
        return buf
