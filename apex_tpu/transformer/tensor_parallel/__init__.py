"""apex_tpu.transformer.tensor_parallel ≡ apex/transformer/tensor_parallel:
Megatron-style parallel layers, mappings, vocab-parallel cross entropy,
data broadcast, RNG tracking, and activation-checkpoint helpers."""

from apex_tpu.parallel.collectives import (  # noqa: F401  (≡ mappings.py)
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data  # noqa: F401
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RNGStatesTracker,
    checkpoint,
    get_rng_tracker,
    model_parallel_fold_in,
)
