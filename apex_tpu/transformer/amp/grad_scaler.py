"""Model-parallel-aware grad scaler.

≡ apex/transformer/amp/grad_scaler.py:21-79 (GradScaler): a torch
GradScaler subclass whose only change is all-reducing found_inf over the
model-parallel group before the step/update decision — so a tp/pp rank
that overflows makes EVERY rank skip in lockstep.

TPU version: the same rule as a pure function over the functional
scaler state: `found_inf` is psum'd over the tp and pp axes inside the
SPMD region.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.parallel.mesh import PP_AXIS, TP_AXIS


def allreduce_found_inf(found_inf, axis_names=(TP_AXIS, PP_AXIS)):
    """≡ GradScaler._unscale_grads_'s MP-group allreduce
    (grad_scaler.py:44-55).  Call inside shard_map."""
    flag = jnp.asarray(found_inf, jnp.float32)
    for ax in axis_names:
        flag = jax.lax.pmax(flag, ax)
    return flag > 0.5


class GradScaler:
    """Functional facade matching the reference class shape."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000, enabled=True):
        self.enabled = enabled
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self.state = scaler_lib.init("dynamic" if enabled else None,
                                     init_scale=init_scale)

    def scale(self, loss):
        return scaler_lib.scale_loss(self.state, loss) if self.enabled \
            else loss

    def unscale_and_sync(self, grads, axis_names=(TP_AXIS, PP_AXIS)):
        grads, found_inf = scaler_lib.unscale(self.state, grads)
        return grads, allreduce_found_inf(found_inf, axis_names)

    def update(self, found_inf):
        self.state = scaler_lib.update(
            self.state, found_inf, dynamic=self.enabled,
            growth_interval=self.growth_interval,
            growth_factor=self.growth_factor,
            backoff_factor=self.backoff_factor)
        return self.state
