"""Sequence-parallel-aware LayerNorm.

≡ apex/transformer/layers/layer_norm.py:26-74: a LayerNorm whose params
carry `sequence_parallel_enabled` so the trainer all-reduces their grads
over the TP group.  TPU version: instead of tagging + external
allreduce, the params are routed through the identity-fwd/psum-bwd
collective when sequence-parallel, making the grad reduction part of
the autodiff graph.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops.layer_norm import FusedLayerNorm, fused_layer_norm
from apex_tpu.parallel.collectives import (
    copy_to_tensor_model_parallel_region)
from apex_tpu.parallel.mesh import TP_AXIS


class LayerNorm(FusedLayerNorm):
    """≡ apex.transformer.layers.LayerNorm — FusedLayerNorm with the
    sequence_parallel_enabled contract."""

    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True,
                 sequence_parallel_enabled: bool = False,
                 axis_name: str = TP_AXIS):
        super().__init__(normalized_shape, eps, elementwise_affine)
        self.sequence_parallel_enabled = sequence_parallel_enabled
        self.axis_name = axis_name

    def apply(self, params, x, use_pallas_override=None):
        w = params.get("weight") if self.elementwise_affine else None
        b = params.get("bias") if self.elementwise_affine else None
        if self.sequence_parallel_enabled and w is not None:
            w = copy_to_tensor_model_parallel_region(w, self.axis_name)
            if b is not None:
                b = copy_to_tensor_model_parallel_region(b, self.axis_name)
        return fused_layer_norm(x, w, b, self.eps, use_pallas_override)
