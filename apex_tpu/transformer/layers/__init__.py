from apex_tpu.transformer.layers.layer_norm import LayerNorm  # noqa: F401
