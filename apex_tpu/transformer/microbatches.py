"""Microbatch calculators.

≡ apex/transformer/microbatches.py:26-175: ConstantNumMicroBatches and
RampupBatchsizeNumMicroBatches — pure bookkeeping, identical semantics.
"""

from __future__ import annotations

from typing import Optional


def build_num_microbatches_calculator(
        rank: int, rampup_batch_size: Optional[list],
        global_batch_size: int, micro_batch_size: int,
        data_parallel_size: int):
    """≡ microbatches.build_num_microbatches_calculator (26-77)."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
    start, incr, samples = map(int, rampup_batch_size[:3])
    return RampupBatchsizeNumMicroBatches(
        start, incr, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


class ConstantNumMicroBatches:
    """≡ microbatches.ConstantNumMicroBatches (89-116)."""

    def __init__(self, global_batch_size, micro_batch_size,
                 data_parallel_size):
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        assert global_batch_size % micro_batch_times_dp == 0, (
            f"global batch size ({global_batch_size}) is not divisible by "
            f"micro batch size ({micro_batch_size}) times data parallel "
            f"size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches:
    """≡ microbatches.RampupBatchsizeNumMicroBatches (119-175): linear
    batch-size rampup over consumed samples."""

    def __init__(self, start_batch_size, batch_size_increment,
                 ramup_samples, global_batch_size, micro_batch_size,
                 data_parallel_size):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        assert self.micro_batch_times_data_parallel_size > 0
        assert start_batch_size > 0
        self.start_batch_size = start_batch_size
        assert global_batch_size > 0
        self.global_batch_size = global_batch_size
        diff_batch_size = global_batch_size - start_batch_size
        assert diff_batch_size >= 0
        assert batch_size_increment > 0
        self.batch_size_increment = batch_size_increment
        assert diff_batch_size % batch_size_increment == 0, (
            "expected global batch size interval to be divisible by global "
            "batch size increment")
        num_increments = diff_batch_size // batch_size_increment
        self.ramup_samples = ramup_samples
        assert self.ramup_samples >= 0
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments else 0)
        self.num_micro_batches = None
        self.current_global_batch_size = None
        self.update(0, False)

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size)
        if consistency_check:
            assert (self.current_global_batch_size %
                    self.micro_batch_times_data_parallel_size == 0)
        self.num_micro_batches = max(
            1, self.current_global_batch_size //
            self.micro_batch_times_data_parallel_size)
