from apex_tpu.transformer._data._batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)
