"""Collective-backend availability probe.

≡ apex.transformer._ucc_util (apex/transformer/_ucc_util.py:1-9), which
exposes HAS_UCC so callers can select the UCC torch.distributed backend.
JAX has no pluggable collective backend — XLA emits ICI/DCN collectives —
so the analogous runtime question is "which platforms are live and can a
multi-process (multi-controller) run be formed".
"""

from __future__ import annotations

import jax

__all__ = ["HAS_UCC", "backend_available", "default_backend"]

# UCC never applies on TPU; kept for API parity with the reference import
# sites (`from apex.transformer._ucc_util import HAS_UCC`).
HAS_UCC = False


def backend_available(name: str) -> bool:
    """True if a JAX platform with this name is available ('tpu',
    'cpu', 'gpu') — not merely the default one."""
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def default_backend() -> str:
    return jax.default_backend()
