"""Attention-softmax dispatcher.

≡ apex/transformer/functional/fused_softmax.py:166-276
(FusedScaleMaskSoftmax): picks the fused kernel variant (causal /
masked / plain) by attention-mask type and shape, with a plain-jnp
fallback — mirroring is_kernel_available (222-247).  On TPU the "fused
kernel" is the Pallas softmax family (ops/softmax.py); the CUDA
seq-length/batch-per-block constraints disappear.
"""

from __future__ import annotations

import enum
from typing import Optional

import jax.numpy as jnp

from apex_tpu.ops import softmax as S


class AttnMaskType(enum.Enum):
    """≡ apex/transformer/enums.py AttnMaskType."""
    padding = 1
    causal = 2
    no_mask = 3


class FusedScaleMaskSoftmax:
    """≡ FusedScaleMaskSoftmax (fused_softmax.py:166-276)."""

    def __init__(self, attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func=None, softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        self.attn_mask_type = attn_mask_type
        self.fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if self.scale is not None and not softmax_in_fp32:
            raise RuntimeError(
                "softmax should be in fp32 when scaled")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """≡ fused_softmax.py:222-247 — on TPU the blocked Pallas kernel
        covers every shape; only the fusion flag gates it."""
        return self.fusion

    def __call__(self, inputs, mask=None, use_pallas_override=None):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = inputs.shape
            x = inputs.reshape(-1, sq, sk)
            out = S.scaled_upper_triang_masked_softmax(
                x, scale, use_pallas_override)
            return out.reshape(inputs.shape)
        if mask is not None:
            return S.scaled_masked_softmax(inputs, mask, scale,
                                           use_pallas_override)
        return S.scaled_softmax(inputs, scale, use_pallas_override)
