"""apex_tpu.transformer — Megatron-style TP/SP/PP parallelism library.

≡ apex.transformer (apex/transformer/__init__.py): parallel_state (here:
apex_tpu.parallel.mesh), tensor_parallel, pipeline_parallel, amp grad
scaler, fused softmax, batch samplers, and testing models.
"""

from apex_tpu.parallel import mesh as parallel_state  # noqa: F401


def __getattr__(name):
    import importlib
    submods = (
        "tensor_parallel", "pipeline_parallel", "functional", "layers",
        "testing", "microbatches",
    )
    if name in submods:
        return importlib.import_module(f"apex_tpu.transformer.{name}")
    raise AttributeError(name)
