"""apex_tpu.mlp — fused MLP (≡ apex.mlp, apex/mlp/mlp.py:11-87).

Parity shim re-exporting the Pallas/XLA-fused MLP from the ops layer.
"""

from apex_tpu.ops.mlp import MLP, mlp_forward  # noqa: F401

__all__ = ["MLP", "mlp_forward"]
