"""apex_tpu.normalization — fused LayerNorm/RMSNorm (≡ apex.normalization).

Parity shim over the Pallas kernel layer: the reference package
(apex/normalization/__init__.py, fused_layer_norm.py:204-438) exports
module classes and functional forms; both live in
`apex_tpu.ops.layer_norm` and are re-exported here under the reference
names.
"""

from apex_tpu.ops.layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm,
    fused_rms_norm,
    layer_norm_reference,
    rms_norm_reference,
)

# Megatron "mixed dtype" variants (fused_layer_norm.py:398-438) are the
# same kernels with fp32 stats/params over low-precision activations —
# the kernel always computes stats in fp32, so the aliases are exact.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm

__all__ = [
    "FusedLayerNorm", "FusedRMSNorm", "MixedFusedLayerNorm",
    "MixedFusedRMSNorm", "fused_layer_norm", "fused_rms_norm",
    "layer_norm_reference", "rms_norm_reference",
]
