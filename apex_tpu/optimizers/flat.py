"""Pytree <-> flat 1-D buffer mapping.

≡ the reference's `apex_C` extension (csrc/flatten_unflatten.cpp:16-17,
torch's flatten_dense_tensors) plus the dtype-partitioned list building
every fused optimizer does per step (apex/optimizers/fused_adam.py:163-197).
In JAX the flattening happens once at optimizer init; the training step
then moves a single fused buffer through the Pallas optimizer kernels —
no per-step re-bucketing, no 110-tensor launch limits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static description of the pytree layout inside the flat buffer."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int


def make_spec(tree) -> FlatSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    return FlatSpec(treedef=treedef, shapes=shapes, dtypes=dtypes,
                    sizes=sizes, offsets=offsets, total=int(sum(sizes)))


def flatten(tree, dtype=jnp.float32, pad_to: int = 1):
    """Concatenate all leaves into one 1-D buffer (cast to `dtype`).

    `pad_to` rounds the buffer length up to a multiple (zeros appended) so
    downstream Pallas kernels see tile-aligned shapes and update in place
    — without it every optimizer step would re-pad (a full HBM copy that
    also breaks the donation chain).  unflatten ignores the tail.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype)
    flat = jnp.concatenate([l.astype(dtype).reshape(-1) for l in leaves])
    pad = (-flat.shape[0]) % pad_to
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def unflatten(flat, spec: FlatSpec, cast_to_leaf_dtype: bool = True):
    """Rebuild the pytree from a flat buffer (XLA: pure slicing, fused)."""
    leaves = []
    for shape, dt, size, off in zip(spec.shapes, spec.dtypes, spec.sizes,
                                    spec.offsets):
        leaf = jax.lax.dynamic_slice(flat, (off,), (size,)).reshape(shape)
        if cast_to_leaf_dtype:
            leaf = leaf.astype(dt)
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
