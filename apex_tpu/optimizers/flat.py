"""Pytree <-> flat 1-D buffer mapping.

≡ the reference's `apex_C` extension (csrc/flatten_unflatten.cpp:16-17,
torch's flatten_dense_tensors) plus the dtype-partitioned list building
every fused optimizer does per step (apex/optimizers/fused_adam.py:163-197).
In JAX the flattening happens once at optimizer init; the training step
then moves a single fused buffer through the Pallas optimizer kernels —
no per-step re-bucketing, no 110-tensor launch limits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static description of the pytree layout inside the flat buffer.

    With ``align > 1`` every leaf's segment is rounded up to a multiple
    of `align` elements (zero-filled tail).  Aligning to the 128-lane
    TPU vector width makes each tensor span whole (rows, 128) rows of
    the 2-D view, so per-tensor reductions (LAMB trust ratios, NovoGrad
    norms) become row-aligned segment sums — one pass over the buffer —
    instead of hundreds of per-leaf dynamic slices.
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    align: int = 1


def make_spec(tree, align: int = 1) -> FlatSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    padded = [(-(-s // align)) * align for s in sizes]
    offsets = tuple(int(o) for o in np.cumsum([0] + padded[:-1]))
    total = int(offsets[-1] + padded[-1]) if sizes else 0
    return FlatSpec(treedef=treedef, shapes=shapes, dtypes=dtypes,
                    sizes=sizes, offsets=offsets, total=total, align=align)


def flatten(tree, dtype=jnp.float32, pad_to: int = 1, align: int = 1):
    """Concatenate all leaves into one 1-D buffer (cast to `dtype`).

    `pad_to` rounds the buffer length up to a multiple (zeros appended) so
    downstream Pallas kernels see tile-aligned shapes and update in place
    — without it every optimizer step would re-pad (a full HBM copy that
    also breaks the donation chain).  `align` zero-pads every LEAF
    segment to a multiple (must match the spec's align).  unflatten
    ignores all padding.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype)
    parts = []
    for l in leaves:
        v = l.astype(dtype).reshape(-1)
        pad = (-v.shape[0]) % align
        if pad:
            v = jnp.pad(v, (0, pad))
        parts.append(v)
    flat = jnp.concatenate(parts)
    pad = (-flat.shape[0]) % pad_to
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def unflatten(flat, spec: FlatSpec, cast_to_leaf_dtype: bool = True):
    """Rebuild the pytree from a flat buffer (XLA: pure slicing, fused).

    An optimization barrier sits between each slice and its
    convert/reshape: XLA otherwise CSE-hoists the ~hundreds of
    slice→convert/reshape chains into whole-buffer temps —
    * cast case: one 1-D bf16 convert whose [N/2, 2] layout tile-pads
      the minor dim 2 up to 128, a 64x HBM blowup (43 GB at 336M) that
      OOMs compilation;
    * same-dtype case: one whole-buffer RESHAPE per distinct leaf minor
      width (observed at 1.3B: two 2.44 GB bf16 relayout temps,
      [N/8192, 8192] and [N/2048, 2048] views of the master buffer —
      the step OOM'd at batch 8 and the standalone unflatten ran at
      23 GB/s).
    The barrier keeps every convert/reshape leaf-sized.
    """
    leaves = []
    for shape, dt, size, off in zip(spec.shapes, spec.dtypes, spec.sizes,
                                    spec.offsets):
        leaf = jax.lax.dynamic_slice(flat, (off,), (size,))
        leaf = jax.lax.optimization_barrier(leaf)
        if cast_to_leaf_dtype and dt != flat.dtype:
            leaf = leaf.astype(dt)
        leaves.append(leaf.reshape(shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def per_leaf_scalars(tree, params, who: str) -> np.ndarray:
    """Flatten a per-leaf scalar pytree (bools or floats — e.g. the
    wd_mask from get_params_for_weight_decay_optimization, or per-leaf
    lr multipliers) into an (n_leaves,) fp32 vector in param leaf order.
    The tree's STRUCTURE must match params' exactly (a same-count tree
    with different keys would silently assign hyperparameters to the
    wrong tensors).  ≡ the reference's param_groups: each leaf's scalar
    plays the role of its group's hyperparameter
    (apex/optimizers/fused_adam.py:156-303)."""
    want = jax.tree_util.tree_structure(params)
    got = jax.tree_util.tree_structure(tree)
    if got != want:
        raise ValueError(
            f"{who}: per-leaf tree structure/leaves do not match the "
            f"params pytree ({got} vs {want}) — build it with tree_map "
            "over the same params pytree")
    return np.asarray([float(x) for x in jax.tree_util.tree_leaves(tree)],
                      np.float32)


def resolve_per_leaf(wd_mask, lr_scales, weight_decay: float, params,
                     who: str):
    """The ONE definition of per-leaf hyperparameter resolution shared
    by FusedAdam/FusedLAMB and their ZeRO variants: returns
    (seg_wd, seg_lrs) fp32 vectors in leaf order — wd_mask leaves
    multiply `weight_decay` (bool → 0/1), lr_scales leaves multiply the
    learning rate; an absent tree falls back to the uniform value."""
    n = len(jax.tree_util.tree_leaves(params))
    seg_wd = (weight_decay * per_leaf_scalars(wd_mask, params, who)
              if wd_mask is not None
              else np.full((n,), weight_decay, np.float32))
    seg_lrs = (per_leaf_scalars(lr_scales, params, who)
               if lr_scales is not None else np.ones((n,), np.float32))
    return seg_wd, seg_lrs


def layout_dict(spec: FlatSpec) -> dict:
    """Layout fingerprint stored inside optimizer state_dicts so a
    checkpoint written under one flat layout cannot be silently restored
    into another (offsets moved when align was introduced; buffer
    lengths often coincide after FLAT_TILE rounding, so a shape check
    alone cannot catch it)."""
    return {"align": spec.align, "total": spec.total,
            "n_tensors": len(spec.sizes)}


def check_layout(spec: FlatSpec, d: dict, who: str) -> None:
    lay = d.get("flat_layout")
    if lay is None:
        # pre-layout checkpoint: only safe when this spec is unaligned
        if spec.align != 1:
            raise ValueError(
                f"{who}: checkpoint has no flat_layout record but the "
                f"current spec is align={spec.align}; offsets would not "
                "match — re-save the checkpoint with this version")
        # a full (unsharded) buffer must still cover spec.total —
        # catches pre-layout checkpoints whose padding rule changed
        # (shard buffers can't be validated without the shard count;
        # their loaders require a recorded layout instead)
        arr = d.get("params")
        if arr is not None and hasattr(arr, "shape") and len(
                getattr(arr, "shape", ())) == 1:
            if int(arr.shape[0]) < spec.total:
                raise ValueError(
                    f"{who}: pre-layout checkpoint buffer has "
                    f"{int(arr.shape[0])} elements < spec total "
                    f"{spec.total} — wrong layout or truncated")
        return
    want = layout_dict(spec)
    if {k: int(lay[k]) for k in want} != want:
        raise ValueError(
            f"{who}: checkpoint flat layout {lay} does not match the "
            f"current spec {want}")


class FlatCheckpointMixin:
    """Shared checkpoint plumbing for flat-buffer optimizers.

    State is a NamedTuple of arrays (``step`` plus flat buffers);
    subclasses set ``_STATE``.  ``state_dict`` embeds the layout
    fingerprint; ``load_state_dict`` refuses to restore before init()
    (without a spec the layout cannot be validated and a mismatched
    checkpoint would fail later with an opaque shape error)."""

    _STATE = None

    def state_dict(self, state) -> dict:
        d = dict(state._asdict())
        d["flat_layout"] = layout_dict(self.spec)
        return d

    def load_state_dict(self, d: dict):
        if self.spec is None:
            raise ValueError(
                f"{type(self).__name__}.load_state_dict called before "
                "init(); call init(params) first so the checkpoint's "
                "flat layout can be validated")
        check_layout(self.spec, d, type(self).__name__)
        cls = type(self)._STATE
        fields = {k: jnp.asarray(v) for k, v in d.items()
                  if k != "flat_layout"}
        if "step" in fields:
            fields["step"] = jnp.asarray(d["step"], jnp.int32)
        return cls(**fields)
