"""apex_tpu.optimizers — fused optimizers over flat parameter buffers.

≡ apex.optimizers (apex/optimizers/__init__.py): FusedAdam, FusedLAMB,
FusedSGD, FusedNovoGrad, FusedAdagrad, FusedMixedPrecisionLamb — each a
single fused Pallas kernel pass over a flattened dtype-partitioned
buffer, ≡ one multi_tensor_applier launch per dtype group
(apex/optimizers/fused_adam.py:156-303).
"""


def __getattr__(name):
    import importlib
    mods = {
        "FusedAdam": "apex_tpu.optimizers.fused_adam",
        "FusedLAMB": "apex_tpu.optimizers.fused_lamb",
        "FusedSGD": "apex_tpu.optimizers.fused_sgd",
        "FusedNovoGrad": "apex_tpu.optimizers.fused_novograd",
        "FusedAdagrad": "apex_tpu.optimizers.fused_adagrad",
        "FusedMixedPrecisionLamb": "apex_tpu.optimizers.fused_lamb",
        "DistributedFusedAdam": "apex_tpu.optimizers.distributed_fused_adam",
    }
    if name in mods:
        return getattr(importlib.import_module(mods[name]), name)
    if name in ("fused_adam", "fused_lamb", "fused_sgd", "fused_novograd",
                "fused_adagrad", "distributed_fused_adam", "flat"):
        return importlib.import_module(f"apex_tpu.optimizers.{name}")
    raise AttributeError(name)
