"""FusedSGD ≡ apex.optimizers.FusedSGD (apex/optimizers/fused_sgd.py):
momentum/dampening/nesterov/weight-decay SGD as one flat Pallas pass
(amp_C.multi_tensor_sgd), with the reference's `wd_after_momentum` and
`materialize_master_grads` semantics subsumed by the fp32 flat buffer.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedSGDState(NamedTuple):
    step: jnp.ndarray
    params: jnp.ndarray
    momentum_buffer: jnp.ndarray


class FusedSGD:
    def __init__(self, lr=1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False,
                 use_pallas: Optional[bool] = None,
                 master_dtype=jnp.float32):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.master_dtype = master_dtype
        self.use_pallas = use_pallas
        self.spec = None

    def init(self, params) -> FusedSGDState:
        self.spec = F.make_spec(params)
        flat = F.flatten(params, self.master_dtype, pad_to=K.FLAT_TILE)
        return FusedSGDState(step=jnp.zeros((), jnp.int32), params=flat,
                             momentum_buffer=jnp.zeros_like(flat))

    def step(self, state: FusedSGDState, grads, lr=None, inv_scale=1.0,
             found_inf=False):
        gdts = {l.dtype for l in jax.tree_util.tree_leaves(grads)}
        gdt = gdts.pop() if len(gdts) == 1 else jnp.float32
        g_flat = F.flatten(grads, gdt, pad_to=K.FLAT_TILE)
        return self.step_flat(state, g_flat, lr=lr, inv_scale=inv_scale,
                              found_inf=found_inf)

    def step_flat(self, state: FusedSGDState, g_flat, lr=None,
                  inv_scale=1.0, found_inf=False):
        """Step from an already-flat grad buffer (zero-copy hot path)."""
        found = jnp.asarray(found_inf)
        # first-step semantics (buf := g, torch's buf-is-None branch) are
        # a traced scalar select INSIDE the kernel: a host-side transform
        # of the buffer materializes a param-sized copy and breaks the
        # in-place aliasing chain, and lax.cond of two kernel calls does
        # the same — this also keeps a skipped (found_inf) first step
        # from writing any derived value into the buffer.
        first = state.step == 0
        if self.momentum != 0.0:
            p, buf = K.sgd_flat(
                state.params, state.momentum_buffer, g_flat,
                lr=self.lr if lr is None else lr, momentum=self.momentum,
                dampening=self.dampening, nesterov=self.nesterov,
                weight_decay=self.weight_decay,
                wd_after_momentum=self.wd_after_momentum, first_run=False,
                first=first, inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        else:
            p, buf = K.sgd_flat(
                state.params, state.momentum_buffer, g_flat,
                lr=self.lr if lr is None else lr, momentum=0.0,
                dampening=self.dampening, nesterov=False,
                weight_decay=self.weight_decay,
                wd_after_momentum=self.wd_after_momentum, first_run=False,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        new_state = FusedSGDState(step=step_next, params=p,
                                  momentum_buffer=buf)
        return F.unflatten(p, self.spec), new_state
