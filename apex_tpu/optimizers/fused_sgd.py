"""FusedSGD ≡ apex.optimizers.FusedSGD (apex/optimizers/fused_sgd.py):
momentum/dampening/nesterov/weight-decay SGD as one flat Pallas pass
(amp_C.multi_tensor_sgd), with the reference's `wd_after_momentum` and
`materialize_master_grads` semantics subsumed by the fp32 flat buffer.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedSGDState(NamedTuple):
    step: jnp.ndarray
    params: jnp.ndarray
    momentum_buffer: jnp.ndarray


class FusedSGD:
    def __init__(self, lr=1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False,
                 use_pallas: Optional[bool] = None):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.use_pallas = use_pallas
        self.spec = None

    def init(self, params) -> FusedSGDState:
        self.spec = F.make_spec(params)
        flat = F.flatten(params, jnp.float32, pad_to=K.FLAT_TILE)
        return FusedSGDState(step=jnp.zeros((), jnp.int32), params=flat,
                             momentum_buffer=jnp.zeros_like(flat))

    def step(self, state: FusedSGDState, grads, lr=None, inv_scale=1.0,
             found_inf=False):
        g_flat = F.flatten(grads, jnp.float32, pad_to=K.FLAT_TILE)
        found = jnp.asarray(found_inf)
        # first_run initializes the momentum buffer with the raw grad
        # (≡ torch SGD buf-is-None branch); branch-free via buffer math:
        # step==0 → buf := g is equivalent to momentum*0 + (1-damp)*g only
        # when dampening==0, so emulate with a traced select on step.
        first = state.step == 0
        if self.momentum != 0.0:
            # compute both branches, select (cheap: one extra elementwise)
            p1, b1 = K.sgd_flat(
                state.params, state.momentum_buffer, g_flat,
                lr=self.lr if lr is None else lr, momentum=self.momentum,
                dampening=self.dampening, nesterov=self.nesterov,
                weight_decay=self.weight_decay,
                wd_after_momentum=self.wd_after_momentum, first_run=True,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
            p2, b2 = K.sgd_flat(
                state.params, state.momentum_buffer, g_flat,
                lr=self.lr if lr is None else lr, momentum=self.momentum,
                dampening=self.dampening, nesterov=self.nesterov,
                weight_decay=self.weight_decay,
                wd_after_momentum=self.wd_after_momentum, first_run=False,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
            p = jnp.where(first, p1, p2)
            buf = jnp.where(first, b1, b2)
        else:
            p, buf = K.sgd_flat(
                state.params, state.momentum_buffer, g_flat,
                lr=self.lr if lr is None else lr, momentum=0.0,
                dampening=self.dampening, nesterov=False,
                weight_decay=self.weight_decay,
                wd_after_momentum=self.wd_after_momentum, first_run=False,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        new_state = FusedSGDState(step=step_next, params=p,
                                  momentum_buffer=buf)
        return F.unflatten(p, self.spec), new_state
