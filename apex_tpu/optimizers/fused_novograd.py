"""FusedNovoGrad ≡ apex.optimizers.FusedNovoGrad
(apex/optimizers/fused_novograd.py): layer-wise second moment — v is a
per-tensor scalar EMA of the grad norm — with the elementwise moment/
param update as a flat Pallas pass (amp_C.multi_tensor_novograd).
The per-tensor norm reduction is an XLA segmented reduction.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedNovoGradState(NamedTuple):
    step: jnp.ndarray
    params: jnp.ndarray
    exp_avg: jnp.ndarray        # flat m
    exp_avg_sq: jnp.ndarray     # (num_tensors,) per-tensor v


class FusedNovoGrad(F.FlatCheckpointMixin):
    _STATE = FusedNovoGradState

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.95, 0.98),
                 eps=1e-8, weight_decay=0.0, grad_averaging=False,
                 amsgrad=False, reg_inside_moment=False,
                 norm_type=2, init_zero=False,
                 use_pallas: Optional[bool] = None):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type != 2:
            raise ValueError("FusedNovoGrad only supports l2 norm now")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.grad_averaging = grad_averaging
        self.reg_inside_moment = reg_inside_moment
        self.init_zero = init_zero
        self.use_pallas = use_pallas
        self.spec = None

    def init(self, params) -> FusedNovoGradState:
        self.spec = F.make_spec(params, align=K._LANES)
        flat = F.flatten(params, jnp.float32, pad_to=K.FLAT_TILE,
                         align=K._LANES)
        n_tensors = len(self.spec.sizes)
        return FusedNovoGradState(
            step=jnp.zeros((), jnp.int32), params=flat,
            exp_avg=jnp.zeros_like(flat),
            exp_avg_sq=jnp.zeros((n_tensors,), jnp.float32))

    def step(self, state: FusedNovoGradState, grads, lr=None, inv_scale=1.0,
             found_inf=False):
        g_flat = F.flatten(grads, jnp.float32, pad_to=K.FLAT_TILE,
                           align=K._LANES) * jnp.asarray(
            inv_scale, jnp.float32)
        found = jnp.asarray(found_inf)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        lr_val = self.lr if lr is None else lr

        # per-tensor ||g||^2 EMA (fused_novograd.py: v init at first step
        # with the raw norm unless init_zero)
        gn2 = jnp.square(K.per_tensor_l2norm_aligned(
            g_flat, self.spec, use_pallas_override=self.use_pallas))
        first = state.step == 0
        if self.init_zero:
            v_prev = state.exp_avg_sq
            v_new = self.beta2 * v_prev + (1.0 - self.beta2) * gn2
        else:
            v_cont = self.beta2 * state.exp_avg_sq + (1.0 - self.beta2) * gn2
            v_new = jnp.where(first, gn2, v_cont)

        denom = jnp.sqrt(v_new) + self.eps
        denom_elem = K.expand_per_tensor_aligned(denom, self.spec,
                                                 state.params.shape[0])

        p32 = state.params
        gg = g_flat / denom_elem
        if self.weight_decay and self.reg_inside_moment:
            gg = gg + self.weight_decay * p32
        beta1_scale = (1.0 - self.beta1) if self.grad_averaging else 1.0
        m_new = self.beta1 * state.exp_avg + beta1_scale * gg
        upd = m_new
        if self.weight_decay and not self.reg_inside_moment:
            upd = upd + self.weight_decay * p32
        if self.bias_correction:
            bc1 = 1.0 - jnp.power(jnp.float32(self.beta1),
                                  step_next.astype(jnp.float32))
            upd = upd / bc1
        p_new = p32 - lr_val * upd

        p = jnp.where(found, state.params, p_new)
        m = jnp.where(found, state.exp_avg, m_new)
        v = jnp.where(found, state.exp_avg_sq, v_new)
        new_state = FusedNovoGradState(step=step_next, params=p, exp_avg=m,
                                       exp_avg_sq=v)
        return F.unflatten(p, self.spec), new_state

    # checkpoint parity: FlatCheckpointMixin
