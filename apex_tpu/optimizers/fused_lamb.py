"""FusedLAMB ≡ apex.optimizers.FusedLAMB (apex/optimizers/fused_lamb.py):
two-phase LAMB — (1) global-grad-norm computation + clipping and the
Adam-style raw update, (2) per-tensor trust-ratio application — matching
the reference's multi_tensor_l2norm → multi_tensor_lamb launch pair
(fused_lamb.py:124-133, 183-199).  Per-tensor norms are XLA segmented
reductions over the flat buffer; phases are Pallas kernels.

FusedMixedPrecisionLamb (apex/optimizers/fused_mixed_precision_lamb.py)
is the same algorithm with fp32 master state over low-precision model
params — subsumed here since the flat buffer is always the fp32 master.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedLAMBState(NamedTuple):
    step: jnp.ndarray
    params: jnp.ndarray
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray


class FusedLAMB(F.FlatCheckpointMixin):
    _STATE = FusedLAMBState

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, amsgrad=False,
                 adam_w_mode=True, grad_averaging=True,
                 max_grad_norm=1.0, use_nvlamb=False,
                 master_dtype=jnp.float32,
                 use_pallas: Optional[bool] = None,
                 wd_mask=None, lr_scales=None):
        """master_dtype=bf16 keeps p/m/v/u in bf16 — halves the LAMB
        pass's HBM traffic (the dominant cost at BERT-Large scale; all
        in-kernel math stays fp32) at ~8-bit state precision, the same
        dial as FusedAdam's 1.3B bf16-state point (docs/PERF.md).

        wd_mask / lr_scales: optional per-leaf pytrees (same structure
        as init's params) ≡ the reference's param_groups — wd_mask
        leaves multiply `weight_decay` per tensor (pass
        get_params_for_weight_decay_optimization(params) for the BERT
        no-decay-bias/LN recipe); lr_scales folds into the per-tensor
        trust ratio, costing nothing extra."""
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.master_dtype = master_dtype
        self.use_pallas = use_pallas
        self.wd_mask = wd_mask
        self.lr_scales = lr_scales
        self._seg_wd = None
        self._seg_lrs = None
        self.spec = None

    def init(self, params) -> FusedLAMBState:
        self.spec = F.make_spec(params, align=K._LANES)
        flat = F.flatten(params, self.master_dtype, pad_to=K.FLAT_TILE,
                         align=K._LANES)
        if self.wd_mask is not None or self.lr_scales is not None:
            self._seg_wd, self._seg_lrs = F.resolve_per_leaf(
                self.wd_mask, self.lr_scales, self.weight_decay, params,
                type(self).__name__)
        # distinct zero buffers (see fused_adam.init: an aliased pair
        # breaks donating jits fed the fresh state)
        return FusedLAMBState(step=jnp.zeros((), jnp.int32), params=flat,
                              exp_avg=jnp.zeros_like(flat),
                              exp_avg_sq=jnp.zeros_like(flat))

    def step(self, state: FusedLAMBState, grads, lr=None, inv_scale=1.0,
             found_inf=False):
        # native-dtype grad flatten (the kernels upcast per block;
        # halving the bf16 grad traffic beats a pre-cast) and NO
        # inv_scale pass — it folds into phase 1's g_scale scalar
        gdts = {l.dtype for l in jax.tree_util.tree_leaves(grads)}
        gdt = gdts.pop() if len(gdts) == 1 else jnp.float32
        g_flat = F.flatten(grads, gdt, pad_to=K.FLAT_TILE,
                           align=K._LANES)
        found = jnp.asarray(found_inf)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        lr_val = self.lr if lr is None else lr

        # phase 0: global grad norm + clip ratio (fused_lamb.py:124-133,
        # 169-181: clip when norm > max_grad_norm); the norm is
        # homogeneous so unscaling multiplies it
        gnorm = K.l2norm_flat(g_flat) * jnp.asarray(inv_scale, jnp.float32)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip = jnp.where(gnorm > self.max_grad_norm,
                             self.max_grad_norm / gnorm, 1.0)
        else:
            clip = jnp.float32(1.0)
        # overflow skip rides inside the kernels (lr_eff=0 / moment
        # coefficients folded) — no whole-buffer where-masks
        if self._seg_wd is not None:
            m, v, u = K.lamb_phase1_seg(
                state.exp_avg, state.exp_avg_sq, g_flat, state.params,
                clip_ratio=clip, step=step_next.astype(jnp.float32),
                wd_values=self._seg_wd, spec=self.spec,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                bias_correction=self.bias_correction,
                grad_averaging=self.grad_averaging,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        else:
            m, v, u = K.lamb_phase1_flat(
                state.exp_avg, state.exp_avg_sq, g_flat, state.params,
                clip_ratio=clip, step=step_next.astype(jnp.float32),
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                weight_decay=self.weight_decay,
                bias_correction=self.bias_correction,
                grad_averaging=self.grad_averaging,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)

        # per-tensor trust ratios ≡ the lamb kernel's
        # ratio = w_norm / u_norm when both > 0 else 1 — one-hot MXU
        # segment sums (ops/optimizer_kernels.py), not scatter/gather
        wn = K.per_tensor_l2norm_aligned(
            state.params, self.spec, use_pallas_override=self.use_pallas)
        un = K.per_tensor_l2norm_aligned(
            u, self.spec, use_pallas_override=self.use_pallas)
        ratio = jnp.where((wn > 0) & (un > 0), wn / jnp.maximum(un, 1e-12),
                          1.0)
        if self._seg_lrs is not None:
            # per-leaf lr rides the per-tensor ratio — zero extra passes
            ratio = ratio * jnp.asarray(self._seg_lrs)
        lr_eff = jnp.where(found, 0.0, jnp.asarray(lr_val, jnp.float32))
        p = K.lamb_phase2_seg(state.params, u, ratio, self.spec, lr_eff,
                              use_pallas_override=self.use_pallas)
        new_state = FusedLAMBState(step=step_next, params=p, exp_avg=m,
                                   exp_avg_sq=v)
        return F.unflatten(p, self.spec), new_state

    # checkpoint parity: FlatCheckpointMixin


class FusedMixedPrecisionLamb(FusedLAMB):
    """≡ apex.optimizers.FusedMixedPrecisionLamb — identical math; the
    flat fp32 buffer already is the master copy of low-precision params."""
