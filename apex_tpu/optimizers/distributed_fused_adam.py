"""DistributedFusedAdam — ZeRO-2 optimizer-state + gradient sharding.

≡ apex.contrib.optimizers.DistributedFusedAdam
(apex/contrib/optimizers/distributed_fused_adam.py:199-212 docstring;
bucket/fragment dataclasses 302-447; grad hooks 652-712; bucket sync
1274-1571): the reference flattens params into fixed-size buckets,
reduce-scatters gradient buckets over the dp group as backward produces
them, keeps only this rank's optimizer-state fragments, and all-gathers
updated param fragments — all overlapped on side streams.

TPU re-design: the 2.2k LoC of bucket/fragment bookkeeping collapses
into array arithmetic on ONE flat buffer — `psum_scatter` IS the bucketed
reduce-scatter (XLA chunks and overlaps it with backward over ICI), and
`all_gather` restores full params after the sharded Pallas Adam pass.
Each dp rank holds exactly 1/dp of (master params, m, v).

Also subsumes DistributedFusedLAMB
(apex/contrib/optimizers/distributed_fused_lamb.py:24,728-987) via
`DistributedFusedLAMB` below: same sharding with the two-phase LAMB
kernels and psum'd global/per-tensor norms.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F
from apex_tpu.parallel.mesh import DP_AXIS


def _bucket_ranges(sizes, n_buckets):
    """Contiguous leaf ranges with ~equal element counts — the bucket
    boundaries for backward-overlapped grad sync (≡ the reference's
    fixed-size grad buckets, distributed_fused_adam.py:302-447)."""
    n_buckets = max(1, min(n_buckets, len(sizes)))
    total = sum(sizes)
    ranges, start, acc = [], 0, 0
    for i, s in enumerate(sizes):
        acc += s
        if (len(ranges) < n_buckets - 1
                and acc * n_buckets >= total * (len(ranges) + 1)):
            ranges.append((start, i + 1))
            start = i + 1
    ranges.append((start, len(sizes)))
    return [r for r in ranges if r[0] < r[1]]


class DistributedFusedAdamState(NamedTuple):
    step: jnp.ndarray
    params_shard: jnp.ndarray    # fp32 master, this rank's 1/dp slice
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray


class _ShardedFlat(F.FlatCheckpointMixin):
    """Shared flat-buffer plumbing for the ZeRO optimizers: ONE place
    defines the (dtype, align, pad_to) layout so init and step can never
    drift apart.  Checkpoint plumbing (layout fingerprint + loud
    restore-before-init guard) comes from FlatCheckpointMixin."""

    _ALIGN = 1  # subclasses override when they need lane-aligned leaves
    # expert-parallel annotation (apex_tpu.moe): when the flat state
    # shards over the COMBINED ("dp", "ep") axes, ep_shards records the
    # ep factor so the checkpoint layout names the expert sharding and
    # `restore_sharded` can refuse an ep re-shard BY NAME instead of
    # silently concatenating (ISSUE 13 satellite).  1 = dense layout.
    ep_shards = 1

    def _set_ep_shards(self, num_shards: int, ep_shards: int) -> None:
        """The ONE validation both ZeRO constructors run — the invariant
        (and its message, which tests match on) lives here."""
        if ep_shards < 1 or num_shards % ep_shards:
            raise ValueError(
                f"ep_shards={ep_shards} must be >= 1 and divide "
                f"num_shards={num_shards} (num_shards = dp * ep)")
        self.ep_shards = ep_shards

    def _make_spec(self, params):
        self.spec = F.make_spec(params, align=self._ALIGN)

    def _flatten(self, tree, dtype=jnp.float32):
        return F.flatten(tree, dtype, align=self._ALIGN,
                         pad_to=self.num_shards * K.FLAT_TILE)

    def _flatten_grads(self, grads):
        """Grad flatten in `grad_sync_dtype` (≡ the reference's
        grad_sync_dtype option, distributed_fused_adam.py:199-212 —
        bf16 halves reduce-scatter traffic; the update kernels upcast
        per block)."""
        return self._flatten(grads, self.grad_sync_dtype)

    def _gather_full(self, shard):
        """All-gather a flat shard into the full (trimmed) pytree —
        the single definition of the gather/trim/unflatten sequence
        used by full_params and both steps.

        The gather runs in `param_sync_dtype` (≡ the reference's
        param_sync_dtype, distributed_fused_adam.py:199-212): defaulting
        to the models' uniform leaf dtype, so a bf16 model with an fp32
        master gathers HALF the bytes and never materializes a
        full-model fp32 buffer (at 1.3B that is 5.25 GB of traffic and
        temps per step saved)."""
        sync_dt = getattr(self, "param_sync_dtype", None)
        if sync_dt is None:
            dts = set(self.spec.dtypes)
            sync_dt = dts.pop() if len(dts) == 1 else shard.dtype
        full = lax.all_gather(shard.astype(sync_dt), self.axis_name,
                              axis=0, tiled=True)
        return F.unflatten(full[: self.spec.total], self.spec)

    def full_params(self, state):
        """All-gather this rank's shard into the full params pytree
        (≡ the reference's bucketed param all-gather, the fwd-side half
        of ZeRO-2).  Shard-local: call inside shard_map."""
        return self._gather_full(state.params_shard)

    def state_partition_specs(self):
        """PartitionSpec pytree for this optimizer's state NamedTuple:
        `step` replicated, every flat shard buffer split over the dp
        axis.  Feed to shard_map in/out_specs — ddp.make_train_step
        detects this method and shards the optimizer state instead of
        replicating it (the ZeRO-2 hot-path wiring)."""
        from jax.sharding import PartitionSpec as P

        return self._STATE(*[
            P() if f == "step" else P(self.axis_name)
            for f in self._STATE._fields])

    def shard_layout(self) -> dict:
        """Static description of THIS optimizer's flat shard layout —
        the re-layout contract `apex_tpu.checkpoint`'s manifests record
        (ISSUE 9): enough to reassemble the canonical align-padded flat
        content from per-rank shard files written at ANY
        (num_shards, n_buckets) and re-slice it for this one.
        Subclasses with bucketed layouts override the bucket rows."""
        import jax.numpy as jnp
        if self.spec is None:
            raise RuntimeError(
                f"{type(self).__name__}.shard_layout() before init(); "
                "call init(params) first so the flat layout is fixed")
        d = {"align": int(self.spec.align),
             "total": int(self.spec.total),
             "n_tensors": len(self.spec.sizes),
             "num_shards": int(self.num_shards),
             "n_buckets": 1,
             "bucket_totals": [int(self.spec.total)],
             "bucket_padded": [int(self.padded_total)],
             "master_dtype": str(jnp.dtype(self.master_dtype))}
        if int(getattr(self, "ep_shards", 1)) > 1:
            # expert-sharded layout: num_shards = dp * ep with the ep
            # factor named, so a restore at a different ep topology is
            # refused by name (checkpoint/sharded._check_layouts)
            # rather than silently re-laid; dense manifests omit the
            # key (old checkpoints keep restoring unchanged)
            d["ep_shards"] = int(self.ep_shards)
        return d


class DistributedFusedAdam(_ShardedFlat):
    """ZeRO-2 Adam.  Shard-local: init/step run inside shard_map with the
    dp axis unmapped.  `num_shards` = dp world size (static)."""

    _STATE = DistributedFusedAdamState

    def __init__(self, num_shards: int, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, axis_name=DP_AXIS,
                 grad_sync_dtype=jnp.float32, param_sync_dtype=None,
                 n_buckets: int = 1, master_dtype=jnp.float32,
                 use_pallas: Optional[bool] = None,
                 wd_mask=None, lr_scales=None, ep_shards: int = 1):
        """master_dtype=bf16 shards bf16 p/m/v state (in-kernel math
        stays fp32) — the ZeRO counterpart of FusedAdam's bf16-state
        dial; halves per-rank state memory AND the update-pass HBM
        traffic.

        n_buckets > 1 splits the flat buffer into contiguous
        leaf-group buckets, each reduce-scattered INDEPENDENTLY: a
        bucket's collective depends only on its own leaves' grads, so
        XLA's scheduler can start it while backward still computes the
        other buckets (≡ the reference's per-bucket grad hooks,
        distributed_fused_adam.py:652-712 + bucket sync 1274-1571 —
        one fused psum_scatter cannot start before the LAST grad
        exists).  The shard layout becomes bucket-major; init/step/
        gather and the checkpoint fingerprint all agree on it.

        wd_mask / lr_scales: optional per-leaf pytrees (same structure
        as init's params) ≡ the reference's param_groups — see
        FusedAdam; applied per bucket shard with the shard's global row
        offset, so every rank updates its fragment with the right
        per-tensor hyperparameters.

        axis_name may be a TUPLE of mesh axes (the MoE wiring shards
        over the combined ("dp", "ep") axes with num_shards = dp*ep —
        every collective here takes the tuple natively); ep_shards
        then records the ep factor for the checkpoint layout, see
        _ShardedFlat.ep_shards."""
        self.num_shards = num_shards
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.grad_sync_dtype = grad_sync_dtype
        self.param_sync_dtype = param_sync_dtype
        self.n_buckets = n_buckets
        self.master_dtype = master_dtype
        self.use_pallas = use_pallas
        self.wd_mask = wd_mask
        self.lr_scales = lr_scales
        self._set_ep_shards(num_shards, ep_shards)
        self._seg_wd = None
        self._seg_lrs = None
        if wd_mask is not None or lr_scales is not None:
            # per-leaf hyperparameters need lane-aligned leaf segments
            self._ALIGN = K._LANES
        self.spec: Optional[F.FlatSpec] = None
        self.padded_total = None

    def _bucket_flats(self, tree, dtype):
        leaves = jax.tree_util.tree_leaves(tree)
        # align must match _make_spec/_flatten (the ONE-layout rule) —
        # a lane-aligned subclass would otherwise shift bucket offsets
        return [F.flatten(leaves[a:b], dtype, align=self._ALIGN,
                          pad_to=self.num_shards * K.FLAT_TILE)
                for a, b in self._ranges]

    def init(self, params) -> DistributedFusedAdamState:
        self._make_spec(params)
        leaves = jax.tree_util.tree_leaves(params)
        sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in leaves]
        self._ranges = _bucket_ranges(sizes, self.n_buckets)
        self.bucket_specs = [F.make_spec(leaves[a:b], align=self._ALIGN)
                             for a, b in self._ranges]
        flats = self._bucket_flats(params, self.master_dtype)
        self._bucket_padded = [f.shape[0] for f in flats]
        self.padded_total = sum(self._bucket_padded)
        if self.wd_mask is not None or self.lr_scales is not None:
            self._seg_wd, self._seg_lrs = F.resolve_per_leaf(
                self.wd_mask, self.lr_scales, self.weight_decay, params,
                type(self).__name__)
        rank = lax.axis_index(self.axis_name)
        shard = jnp.concatenate([
            lax.dynamic_slice(f, (rank * (n // self.num_shards),),
                              (n // self.num_shards,))
            for f, n in zip(flats, self._bucket_padded)])
        zeros = jnp.zeros_like(shard)
        return DistributedFusedAdamState(
            step=jnp.zeros((), jnp.int32), params_shard=shard,
            exp_avg=zeros, exp_avg_sq=zeros)

    def _gather_full(self, shard):
        """Bucket-aware param all-gather (one gather per bucket; the
        single-bucket case is the base layout exactly)."""
        sync_dt = self._param_sync_dt()
        pieces, off = [], 0
        for spec_i, padded_i in zip(self.bucket_specs,
                                    self._bucket_padded):
            sz = padded_i // self.num_shards
            piece = lax.dynamic_slice(shard, (off,), (sz,))
            full = lax.all_gather(piece.astype(sync_dt), self.axis_name,
                                  axis=0, tiled=True)
            pieces += jax.tree_util.tree_leaves(
                F.unflatten(full[: spec_i.total], spec_i))
            off += sz
        return jax.tree_util.tree_unflatten(self.spec.treedef, pieces)

    def state_dict(self, state) -> dict:
        d = super().state_dict(state)
        d["flat_layout"]["n_buckets"] = self.n_buckets
        return d

    def shard_layout(self) -> dict:
        """The bucket-major layout (see _ShardedFlat.shard_layout): a
        rank's shard is the concat over buckets of its 1/num_shards
        chunk, so the checkpoint re-layout needs every bucket's
        (total, padded) pair."""
        d = super().shard_layout()
        d["n_buckets"] = len(self._ranges)
        d["bucket_totals"] = [int(s.total) for s in self.bucket_specs]
        d["bucket_padded"] = [int(p) for p in self._bucket_padded]
        return d

    def load_state_dict(self, d: dict):
        lay = d.get("flat_layout") or {}
        if int(lay.get("n_buckets", 1)) != self.n_buckets:
            raise ValueError(
                f"DistributedFusedAdam: checkpoint n_buckets "
                f"{lay.get('n_buckets', 1)} != configured "
                f"{self.n_buckets} — the bucket-major shard layouts "
                "differ")
        return super().load_state_dict(d)

    def _param_sync_dt(self):
        sync_dt = self.param_sync_dtype
        if sync_dt is None:
            dts = set(self.spec.dtypes)
            sync_dt = dts.pop() if len(dts) == 1 else self.master_dtype
        return sync_dt

    def step(self, state: DistributedFusedAdamState, grads, lr=None,
             inv_scale=1.0, found_inf=False, gather_params=True):
        """grads: full (unsynced, per-dp-shard-of-batch) grad pytree.
        Returns (full params pytree, new state).  The reduce-scatter
        averages over dp (≡ the reference's grad sync divide).

        The whole step runs PER BUCKET — reduce-scatter k, Adam k,
        all-gather k — so XLA's scheduler can overlap bucket k's param
        all-gather with bucket k+1's update math, ≡ the reference's
        side-stream bucket pipeline (distributed_fused_adam.py:
        1274-1571); with n_buckets=1 it degenerates to the fused form.

        gather_params=False skips the all-gather and returns
        (None, state): the caller reconstructs params at the NEXT
        forward via `full_params(state)`, which lets XLA overlap the
        gather with the start of forward compute instead of the tail of
        the optimizer (the reference's param-sync-on-first-use mode)."""
        ax = self.axis_name
        rank = lax.axis_index(ax)
        found = jnp.asarray(found_inf)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        common = dict(
            lr=self.lr if lr is None else lr,
            step=step_next.astype(jnp.float32),
            beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, inv_scale=inv_scale,
            found_inf=found, use_pallas_override=self.use_pallas)
        grad_buckets = self._bucket_flats(grads, self.grad_sync_dtype)
        sync_dt = self._param_sync_dt()
        ps, ms, vs = [], [], []
        full_leaves = []
        off = 0
        for (a, b), spec_i, padded_i, gb in zip(
                self._ranges, self.bucket_specs, self._bucket_padded,
                grad_buckets):
            sz = padded_i // self.num_shards
            # ZeRO-2 core: per-bucket reduce-scatter — starts as soon
            # as THIS bucket's leaves' grads exist
            g_b = lax.psum_scatter(gb, ax, scatter_dimension=0,
                                   tiled=True) / jnp.asarray(
                self.num_shards, gb.dtype)

            def sl(arr):
                return lax.dynamic_slice(arr, (off,), (sz,))

            if self._seg_wd is not None:
                pi, mi, vi = K.adam_flat_seg(
                    sl(state.params_shard), sl(state.exp_avg),
                    sl(state.exp_avg_sq), g_b,
                    wd_values=self._seg_wd[a:b],
                    lr_scale_values=self._seg_lrs[a:b],
                    spec=spec_i, row_offset=rank * (sz // K._LANES),
                    padded_total=padded_i, **common)
            else:
                pi, mi, vi = K.adam_flat(
                    sl(state.params_shard), sl(state.exp_avg),
                    sl(state.exp_avg_sq), g_b,
                    weight_decay=self.weight_decay, **common)
            ps.append(pi)
            ms.append(mi)
            vs.append(vi)
            if gather_params:
                # bucket k's param all-gather depends only on ITS adam
                # output → schedulable under bucket k+1's compute
                full = lax.all_gather(pi.astype(sync_dt), ax, axis=0,
                                      tiled=True)
                full_leaves += jax.tree_util.tree_leaves(
                    F.unflatten(full[: spec_i.total], spec_i))
            off += sz
        new_state = DistributedFusedAdamState(
            step=step_next, params_shard=jnp.concatenate(ps),
            exp_avg=jnp.concatenate(ms), exp_avg_sq=jnp.concatenate(vs))
        if not gather_params:
            return None, new_state
        return jax.tree_util.tree_unflatten(self.spec.treedef,
                                            full_leaves), new_state

    # ---- reshardable (gathered) checkpoints --------------------------------

    def gather_state_dict(self, state) -> dict:
        """Layout-independent checkpoint: all-gather every shard buffer
        and unflatten to MODEL-TREE form, so state written at one
        (num_shards, n_buckets) restores at any other.  Shard-local —
        call inside shard_map.  ≡ the reference's state gather for
        save (distributed_fused_adam.py:1274-1571 sharded_state_dict /
        gather paths)."""
        def tree_of(shard):
            off = 0
            out = []
            for spec_i, padded_i in zip(self.bucket_specs,
                                        self._bucket_padded):
                sz = padded_i // self.num_shards
                piece = lax.dynamic_slice(shard, (off,), (sz,))
                full = lax.all_gather(piece, self.axis_name, axis=0,
                                      tiled=True)
                out += jax.tree_util.tree_leaves(
                    F.unflatten(full[: spec_i.total], spec_i,
                                cast_to_leaf_dtype=False))
                off += sz
            return jax.tree_util.tree_unflatten(self.spec.treedef, out)

        return {"step": state.step,
                "params": tree_of(state.params_shard),
                "exp_avg": tree_of(state.exp_avg),
                "exp_avg_sq": tree_of(state.exp_avg_sq)}

    def load_gathered_state_dict(self, d: dict):
        """Inverse of gather_state_dict under THIS optimizer's layout
        (any num_shards / n_buckets / align).  Shard-local — call
        inside shard_map after init() has fixed the layout."""
        # gathered checkpoints carry model-tree "params"; layout-exact
        # shard checkpoints carry "params_shard" (no string marker —
        # the dict must be traceable through shard_map)
        if "params" not in d or "params_shard" in d:
            raise ValueError(
                "not a gathered checkpoint — use load_state_dict for "
                "layout-exact shard checkpoints")
        if self.spec is None:
            raise RuntimeError("call init(params) before "
                               "load_gathered_state_dict()")
        rank = lax.axis_index(self.axis_name)

        def shard_of(tree):
            flats = self._bucket_flats(tree, self.master_dtype)
            return jnp.concatenate([
                lax.dynamic_slice(f, (rank * (n // self.num_shards),),
                                  (n // self.num_shards,))
                for f, n in zip(flats, self._bucket_padded)])

        return DistributedFusedAdamState(
            step=jnp.asarray(d["step"], jnp.int32),
            params_shard=shard_of(d["params"]),
            exp_avg=shard_of(d["exp_avg"]),
            exp_avg_sq=shard_of(d["exp_avg_sq"]))


class DistributedFusedLAMBState(NamedTuple):
    step: jnp.ndarray
    params_shard: jnp.ndarray
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray


class DistributedFusedLAMB(_ShardedFlat):
    """ZeRO-sharded LAMB ≡ DistributedFusedLAMB
    (distributed_fused_lamb.py:24): reduce-scattered grads, sharded
    moments, psum'd global grad norm, per-tensor trust ratios computed
    on gathered norms, sharded phase-2 update, all-gather params."""

    _STATE = DistributedFusedLAMBState
    _ALIGN = K._LANES  # lane-aligned leaves -> one-pass per-tensor norms

    def __init__(self, num_shards: int, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 max_grad_norm=1.0, axis_name=DP_AXIS,
                 grad_sync_dtype=jnp.float32, param_sync_dtype=None,
                 master_dtype=jnp.float32,
                 use_pallas: Optional[bool] = None,
                 wd_mask=None, lr_scales=None, ep_shards: int = 1):
        self.num_shards = num_shards
        # expert-sharded (dp, ep) layouts record their ep factor in the
        # checkpoint manifest — see DistributedFusedAdam
        self._set_ep_shards(num_shards, ep_shards)
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.axis_name = axis_name
        self.grad_sync_dtype = grad_sync_dtype
        self.param_sync_dtype = param_sync_dtype
        self.master_dtype = master_dtype
        self.use_pallas = use_pallas
        self.wd_mask = wd_mask
        self.lr_scales = lr_scales
        self._seg_wd = None
        self._seg_lrs = None
        self.spec = None
        self.padded_total = None

    def init(self, params):
        self._make_spec(params)
        flat = self._flatten(params, self.master_dtype)
        self.padded_total = flat.shape[0]
        if self.wd_mask is not None or self.lr_scales is not None:
            self._seg_wd, self._seg_lrs = F.resolve_per_leaf(
                self.wd_mask, self.lr_scales, self.weight_decay, params,
                type(self).__name__)
        shard_size = self.padded_total // self.num_shards
        rank = lax.axis_index(self.axis_name)
        shard = lax.dynamic_slice(flat, (rank * shard_size,), (shard_size,))
        zeros = jnp.zeros_like(shard)
        return DistributedFusedLAMBState(
            step=jnp.zeros((), jnp.int32), params_shard=shard,
            exp_avg=zeros, exp_avg_sq=zeros)

    def step(self, state, grads, lr=None, inv_scale=1.0, found_inf=False):
        ax = self.axis_name
        g_flat = self._flatten_grads(grads)
        g_shard = (lax.psum_scatter(g_flat, ax, scatter_dimension=0,
                                    tiled=True)
                   / jnp.asarray(self.num_shards, g_flat.dtype))
        found = jnp.asarray(found_inf)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        lr_val = self.lr if lr is None else lr

        # global grad norm over ALL shards (pipelined block reductions in
        # the reference, distributed_fused_lamb.py:728-987 → one psum);
        # inv_scale multiplies the homogeneous norm and otherwise rides
        # inside phase 1's g_scale scalar — no whole-buffer unscale pass
        gnorm = jnp.sqrt(lax.psum(jnp.sum(
            jnp.square(g_shard.astype(jnp.float32))), ax)
        ) * jnp.asarray(inv_scale, jnp.float32)
        clip = jnp.where(
            (self.max_grad_norm > 0) & (gnorm > self.max_grad_norm),
            self.max_grad_norm / gnorm, 1.0)

        # overflow skip folded into the kernels (≡ FusedLAMB.step)
        shard_rows = state.params_shard.shape[0] // K._LANES
        if self._seg_wd is not None:
            m, v, u = K.lamb_phase1_seg(
                state.exp_avg, state.exp_avg_sq, g_shard,
                state.params_shard,
                clip_ratio=clip, step=step_next.astype(jnp.float32),
                wd_values=self._seg_wd, spec=self.spec,
                row_offset=lax.axis_index(ax) * shard_rows,
                padded_total=self.padded_total,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                bias_correction=self.bias_correction,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        else:
            m, v, u = K.lamb_phase1_flat(
                state.exp_avg, state.exp_avg_sq, g_shard,
                state.params_shard,
                clip_ratio=clip, step=step_next.astype(jnp.float32),
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                weight_decay=self.weight_decay,
                bias_correction=self.bias_correction,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)

        # per-tensor norms WITHOUT materializing the full buffers: each
        # rank computes partial per-tensor sums of squares over its own
        # contiguous shard (segment boundaries are static from FlatSpec;
        # the shard start is rank*shard_size) and ONE small psum of the
        # 2*n_tensors partials yields exact norms — ≡ the reference's
        # pipelined block reductions (distributed_fused_lamb.py:728-987),
        # which likewise never gather the model onto one rank.  The only
        # full-size all-gather left in the step is the final param sync.
        shard_size = self.padded_total // self.num_shards
        rank = lax.axis_index(ax)
        pn_part = K.per_tensor_sumsq_shard(
            state.params_shard, self.spec, rank, self.padded_total,
            use_pallas_override=self.use_pallas)
        un_part = K.per_tensor_sumsq_shard(
            u, self.spec, rank, self.padded_total,
            use_pallas_override=self.use_pallas)
        sums = lax.psum(jnp.concatenate([pn_part, un_part]), ax)
        n_t = len(self.spec.sizes)
        wn = jnp.sqrt(sums[:n_t])
        un = jnp.sqrt(sums[n_t:])
        ratio = jnp.where((wn > 0) & (un > 0), wn / jnp.maximum(un, 1e-12),
                          1.0)
        if self._seg_lrs is not None:
            ratio = ratio * jnp.asarray(self._seg_lrs)

        lr_eff = jnp.where(found, 0.0, jnp.asarray(lr_val, jnp.float32))
        p = K.lamb_phase2_seg(state.params_shard, u, ratio, self.spec,
                              lr_eff,
                              row_offset=rank * (shard_size // K._LANES),
                              padded_total=self.padded_total,
                              use_pallas_override=self.use_pallas)
        new_state = DistributedFusedLAMBState(
            step=step_next, params_shard=p, exp_avg=m, exp_avg_sq=v)
        return self._gather_full(p), new_state

    def gather_state_dict(self, state) -> dict:
        """Layout-independent checkpoint in model-tree form (see
        DistributedFusedAdam.gather_state_dict); restores at any
        num_shards.  Shard-local."""
        def tree_of(shard):
            full = lax.all_gather(shard, self.axis_name, axis=0,
                                  tiled=True)
            return F.unflatten(full[: self.spec.total], self.spec,
                               cast_to_leaf_dtype=False)

        return {"step": state.step,
                "params": tree_of(state.params_shard),
                "exp_avg": tree_of(state.exp_avg),
                "exp_avg_sq": tree_of(state.exp_avg_sq)}

    def load_gathered_state_dict(self, d: dict):
        # gathered checkpoints carry model-tree "params"; layout-exact
        # shard checkpoints carry "params_shard" (no string marker —
        # the dict must be traceable through shard_map)
        if "params" not in d or "params_shard" in d:
            raise ValueError(
                "not a gathered checkpoint — use load_state_dict for "
                "layout-exact shard checkpoints")
        if self.spec is None:
            raise RuntimeError("call init(params) before "
                               "load_gathered_state_dict()")
        rank = lax.axis_index(self.axis_name)
        shard_size = self.padded_total // self.num_shards

        def shard_of(tree):
            flat = self._flatten(tree, self.master_dtype)
            return lax.dynamic_slice(flat, (rank * shard_size,),
                                     (shard_size,))

        return DistributedFusedLAMBState(
            step=jnp.asarray(d["step"], jnp.int32),
            params_shard=shard_of(d["params"]),
            exp_avg=shard_of(d["exp_avg"]),
            exp_avg_sq=shard_of(d["exp_avg_sq"]))
