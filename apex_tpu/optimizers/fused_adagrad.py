"""FusedAdagrad ≡ apex.optimizers.FusedAdagrad
(apex/optimizers/fused_adagrad.py): one flat Pallas pass
(amp_C.multi_tensor_adagrad) with optional decoupled ("adagrad_w_mode")
weight decay.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedAdagradState(NamedTuple):
    step: jnp.ndarray
    params: jnp.ndarray
    sum_sq: jnp.ndarray


class FusedAdagrad:
    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 adagrad_w_mode=False, use_pallas: Optional[bool] = None):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode
        self.use_pallas = use_pallas
        self.spec = None

    def init(self, params) -> FusedAdagradState:
        self.spec = F.make_spec(params)
        flat = F.flatten(params, jnp.float32, pad_to=K.FLAT_TILE)
        return FusedAdagradState(step=jnp.zeros((), jnp.int32), params=flat,
                                 sum_sq=jnp.zeros_like(flat))

    def step(self, state: FusedAdagradState, grads, lr=None):
        g_flat = F.flatten(grads, jnp.float32, pad_to=K.FLAT_TILE)
        p, h = K.adagrad_flat(
            state.params, state.sum_sq, g_flat,
            lr=self.lr if lr is None else lr, eps=self.eps,
            weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode,
            use_pallas_override=self.use_pallas)
        new_state = FusedAdagradState(step=state.step + 1, params=p, sum_sq=h)
        return F.unflatten(p, self.spec), new_state
