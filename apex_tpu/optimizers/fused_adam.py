"""FusedAdam — single-kernel Adam/AdamW over a flat buffer.

≡ apex.optimizers.FusedAdam (apex/optimizers/fused_adam.py:4,127-305):
the reference partitions params by dtype and issues one
multi_tensor_adam launch per group; here all params live in one flat
fp32 buffer and one Pallas pass applies the whole update.  The
"capturable" CUDA-graph variant (fused_adam.py:199-263) is the *default*
semantics in JAX: lr/step/inv_scale/found_inf are on-device scalars and
the overflow-skip is a masked update inside the kernel — no host sync.

Master weights: when `master_weights=True` (≡ FusedMixedPrecisionLamb /
amp O2 master params), the fp32 flat buffer IS the master copy and
`step()` returns params cast back to their storage dtype.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


class FusedAdamState(NamedTuple):
    step: jnp.ndarray      # i32 scalar
    params: jnp.ndarray    # flat fp32 (master) param buffer
    exp_avg: jnp.ndarray   # flat fp32 m
    exp_avg_sq: jnp.ndarray  # flat fp32 v


class FusedAdam(F.FlatCheckpointMixin):
    """API shape: opt = FusedAdam(lr=...); state = opt.init(params);
    params, state = opt.step(state, grads[, lr=, inv_scale=, found_inf=]).
    """

    _STATE = FusedAdamState

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0,
                 amsgrad=False, use_pallas: Optional[bool] = None,
                 master_dtype=jnp.float32, wd_mask=None, lr_scales=None):
        """wd_mask / lr_scales: optional per-leaf pytrees (same structure
        as the params passed to init).  wd_mask leaves (bool or float)
        multiply `weight_decay` per tensor — pass
        get_params_for_weight_decay_optimization(params) for the
        standard no-decay-for-bias/LN groups; lr_scales leaves multiply
        `lr` per tensor.  ≡ the reference's param_groups with distinct
        lr/weight_decay (apex/optimizers/fused_adam.py:156-303), applied
        in ONE kernel pass via in-kernel segment expansion."""
        if amsgrad:
            # ≡ reference raise (apex/optimizers/fused_adam.py:121-122)
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.use_pallas = use_pallas
        # fp32 is the O2-style master copy; bf16 gives O3-style pure-half
        # state (p+m+v at 6 bytes/param instead of 12) for chips where a
        # billion-param model must fit a single HBM
        self.master_dtype = master_dtype
        self.wd_mask = wd_mask
        self.lr_scales = lr_scales
        self._seg_wd = None     # (n_leaves,) fp32, set by init
        self._seg_lrs = None
        self.spec: Optional[F.FlatSpec] = None

    @property
    def _per_leaf(self) -> bool:
        return self.wd_mask is not None or self.lr_scales is not None

    def init(self, params) -> FusedAdamState:
        # per-leaf hyperparameters need lane-aligned leaf segments so
        # the kernel's row-bounds expansion is exact
        align = K._LANES if self._per_leaf else 1
        self.spec = F.make_spec(params, align=align)
        flat = F.flatten(params, self.master_dtype, pad_to=K.FLAT_TILE,
                         align=align)
        if self._per_leaf:
            self._seg_wd, self._seg_lrs = F.resolve_per_leaf(
                self.wd_mask, self.lr_scales, self.weight_decay, params,
                type(self).__name__)
        # two DISTINCT zero buffers: aliasing one array as both moments
        # makes any later donating jit fail with "donate the same
        # buffer twice" when the state is passed in un-resharded
        return FusedAdamState(step=jnp.zeros((), jnp.int32), params=flat,
                              exp_avg=jnp.zeros_like(flat),
                              exp_avg_sq=jnp.zeros_like(flat))

    def step(self, state: FusedAdamState, grads, lr=None, inv_scale=1.0,
             found_inf=False):
        """One fused step.  Returns (params_pytree, new_state)."""
        if self.spec is None:
            raise RuntimeError("call init(params) before step()")
        # keep the grad buffer in its native (bf16) dtype: the kernel
        # upcasts per block, and halving the flatten+read traffic beats a
        # pre-cast (the unscale/moment math still runs in fp32 in-kernel)
        gdts = {l.dtype for l in jax.tree_util.tree_leaves(grads)}
        gdt = gdts.pop() if len(gdts) == 1 else jnp.float32
        g_flat = F.flatten(grads, gdt, pad_to=K.FLAT_TILE,
                           align=self.spec.align)
        p_tree, new_state = self.step_flat(state, g_flat, lr=lr,
                                           inv_scale=inv_scale,
                                           found_inf=found_inf)
        return p_tree, new_state

    def step_flat(self, state: FusedAdamState, g_flat, lr=None,
                  inv_scale=1.0, found_inf=False):
        """Step from an already-flat grad buffer (any float dtype, padded
        to state.params length).  This is the zero-copy hot path: a train
        step that differentiates w.r.t. the flat param view gets its grad
        here directly, skipping the per-leaf flatten entirely."""
        found = jnp.asarray(found_inf)
        step_next = state.step + jnp.where(found, 0, 1).astype(jnp.int32)
        if self._per_leaf:
            p, m, v = K.adam_flat_seg(
                state.params, state.exp_avg, state.exp_avg_sq, g_flat,
                lr=self.lr if lr is None else lr,
                step=step_next.astype(jnp.float32),
                wd_values=self._seg_wd, lr_scale_values=self._seg_lrs,
                spec=self.spec,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                adam_w_mode=self.adam_w_mode,
                bias_correction=self.bias_correction,
                inv_scale=inv_scale, found_inf=found,
                use_pallas_override=self.use_pallas)
        else:
            p, m, v = K.adam_flat(
                state.params, state.exp_avg, state.exp_avg_sq, g_flat,
                lr=self.lr if lr is None else lr,
                step=step_next.astype(jnp.float32),
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                weight_decay=self.weight_decay,
                adam_w_mode=self.adam_w_mode,
                bias_correction=self.bias_correction, inv_scale=inv_scale,
                found_inf=found, use_pallas_override=self.use_pallas)
        new_state = FusedAdamState(step=step_next, params=p, exp_avg=m,
                                   exp_avg_sq=v)
        return F.unflatten(p, self.spec), new_state

    # checkpoint parity ≡ torch optimizer state_dict: FlatCheckpointMixin
