from apex_tpu.utils.timers import Timers, _Timer  # noqa: F401
from apex_tpu.utils.log_util import get_transformer_logger, set_logging_level  # noqa: F401
