"""Phase timers + profiler hooks.

≡ apex/transformer/pipeline_parallel/_timers.py:6-51 (_Timer/_Timers
on CUDA events) — TPU version uses wall clock around block_until_ready
plus `jax.profiler` trace annotations (the reference's NVTX ranges,
apex/parallel/distributed.py:363-407).
"""

from __future__ import annotations

import time

import jax


class _Timer:
    """≡ _timers._Timer: start/stop/elapsed/reset."""

    def __init__(self, name):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()

    def start(self):
        assert not self.started_, "timer has already been started"
        self._trace = jax.profiler.TraceAnnotation(self.name_)
        self._trace.__enter__()
        self.start_time = time.time()
        self.started_ = True

    def stop(self, block: bool = False):
        """block=True drains device execution before reading the clock
        (≡ the reference's torch.cuda.synchronize, _timers.py:25-29) —
        without it the wall clock measures dispatch, not execution."""
        assert self.started_, "timer is not started"
        if block:
            for d in jax.live_arrays():
                d.block_until_ready()
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False
        self._trace.__exit__(None, None, None)

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop()
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e


class Timers:
    """≡ _timers._Timers: registry + log."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def _get(self, name):
        try:
            return self.timers[name]
        except KeyError:
            raise KeyError(
                f"unknown timer {name!r}; registered timers: "
                f"{sorted(self.timers) or '(none)'}") from None

    def write(self, names, writer, iteration, normalizer=1.0, reset=False):
        """Emit `<name>-time` scalars to a SummaryWriter-compatible
        `writer` (anything with add_scalar — e.g. a real TensorBoard
        writer, or `monitor.MetricsLogger.writer` to land timer scalars
        in the metrics JSONL stream)."""
        for name in names:
            value = self._get(name).elapsed(reset=reset) / normalizer
            writer.add_scalar(name + "-time", value, iteration)

    def log(self, names=None, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        names = names or list(self.timers)
        string = "time (ms)"
        for name in names:
            t = self._get(name).elapsed(reset=reset) * 1000.0 / normalizer
            string += f" | {name}: {t:.2f}"
        return string
