"""Per-module loggers with env-var verbosity.

≡ apex/transformer/log_util.py:5-20 (get_transformer_logger,
set_logging_level) + the rank-info formatter in apex/__init__.py:31-43.
"""

from __future__ import annotations

import logging
import os


def get_transformer_logger(name: str) -> logging.Logger:
    name_wo_ext = os.path.splitext(name)[0]
    return logging.getLogger(name_wo_ext)


def set_logging_level(verbosity) -> None:
    """≡ log_util.set_logging_level: APEX_TPU_VERBOSITY env or explicit."""
    from apex_tpu import RankInfoFormatter
    logger = logging.getLogger("apex_tpu")
    logger.setLevel(verbosity)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(RankInfoFormatter(
            "%(asctime)s [%(rank_info)s] %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(handler)


_env = os.environ.get("APEX_TPU_VERBOSITY")
if _env:
    set_logging_level(_env)
