"""`MetricsState` — the on-device telemetry pytree.

The design constraint (ROADMAP: observability must not perturb the
compiled program, ≡ veScale's non-intrusive tracking, PAPERS arxiv
2509.07003) is that collection happens INSIDE the jitted train step:
every field is a scalar computed from values the step already holds
(loss, synced grads, the flat master buffer, the loss-scaler state), so
enabling metrics adds a handful of fused scalar reductions and ZERO
host syncs.  The host only touches the pytree when `MetricsLogger`
device_gets it at log time.

All fields are f32/i32 scalars so the pytree jits, shards (replicated,
`P()`), donates, and checkpoints like any other state.  `tokens_seen`
is f32: exact up to 2**24, then rounds to the nearest representable —
fine for rate math, documented in docs/observability.md.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class MetricsState(NamedTuple):
    """Per-step telemetry riding inside the jitted step (one scalar
    leaf each — the whole pytree is < 50 bytes)."""

    step: jnp.ndarray            # i32, steps attempted (incl. skipped)
    loss: jnp.ndarray            # f32, last UNSCALED loss
    grad_norm: jnp.ndarray       # f32, global L2 of unscaled synced grads
    param_norm: jnp.ndarray      # f32, global L2 of master params
    update_norm: jnp.ndarray     # f32, global L2 of the applied update
    loss_scale: jnp.ndarray      # f32, current loss scale (1.0 if none)
    overflow_count: jnp.ndarray  # i32, cumulative non-finite-grad steps
    # today every overflow is skipped and nothing else is, so the two
    # counters track together; they are separate fields (per ISSUE 2's
    # schema) so future skip policies (nan-loss skip, clip-based skip)
    # can diverge without a schema bump
    skipped_steps: jnp.ndarray   # i32, cumulative optimizer-skip steps
    tokens_seen: jnp.ndarray     # f32, cumulative tokens (or samples)


@dataclasses.dataclass(frozen=True)
class MetricsConfig:
    """Static knobs for in-step collection.

    tokens_per_step: global tokens consumed per optimizer step.  None
    infers from the batch at trace time: integer-dtyped (B, S, ...)
    leaves count B*S (LM token batches), float leaves count B samples
    (image batches) — times the dp axis size inside make_train_step.
    param_norms: the param/update norms read the optimizer's flat
    master buffer (two extra full-buffer reductions per step); disable
    for memory-bound steps where 2 passes over the master buffer show
    up.
    """

    tokens_per_step: Optional[int] = None
    param_norms: bool = True


def init_metrics() -> MetricsState:
    z32 = jnp.zeros((), jnp.float32)
    zi = jnp.zeros((), jnp.int32)
    return MetricsState(step=zi, loss=z32, grad_norm=z32, param_norm=z32,
                        update_norm=z32, loss_scale=jnp.ones((), jnp.float32),
                        overflow_count=zi, skipped_steps=zi, tokens_seen=z32)


def global_norm(tree) -> jnp.ndarray:
    """Global L2 norm over a pytree, accumulated in f32 (bf16 leaves
    upcast per-leaf before squaring).  XLA fuses the per-leaf partial
    sums into the surrounding step.  Deliberately NOT
    K.l2norm_flat(F.flatten(...)) (clip_grad's path): flatten
    materializes a full concatenated grad copy per step, which is
    exactly the overhead telemetry must not add — at the cost that this
    norm may differ from the clip norm in the last few ULPs
    (accumulation order)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


def infer_tokens_per_step(batch, microbatch_dims: int = 0) -> int:
    """Trace-time token accounting for one step's LOCAL batch (callers
    multiply by the dp axis size).  Heuristic on the FIRST leaf:
    integer-dtyped leaves with a sequence dim are LM token ids and count
    every element; float leaves (images etc.) count samples.
    `microbatch_dims=1` for batches stacked (num_microbatches, mb, ...).
    Pass an explicit tokens_per_step when the heuristic is wrong (e.g.
    a dict whose first leaf is a 1-D label vector)."""
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return 0
    lead = leaves[0]
    if (jnp.issubdtype(lead.dtype, jnp.integer)
            and lead.ndim >= 2 + microbatch_dims):
        n = 1
        for d in lead.shape:
            n *= int(d)
        return n
    n = 1
    for d in lead.shape[:1 + microbatch_dims]:
        n *= int(d)
    return n


def update_metrics(state: MetricsState, *, loss=None, grads=None,
                   inv_scale=1.0, params_flat=None, new_params_flat=None,
                   param_norm=None, update_norm=None,
                   loss_scale=None, found_inf=None,
                   tokens: int = 0,
                   count_step: bool = True) -> MetricsState:
    """Fold one step's signals into the pytree — call INSIDE the jitted
    step.  Every argument is optional: paths that don't hold a signal
    (e.g. `forward_backward_no_pipelining` has no optimizer state) leave
    that field at its previous value.

    count_step=False updates fields WITHOUT advancing `step` — for the
    second hook when two hooks fire per training iteration (e.g.
    forward_backward_no_pipelining for loss/grad-norm, then
    FP16_Optimizer.step(metrics_count_step=False) for scale/norms);
    double-counting halves every derived rate downstream.

    grads are the step's (possibly still loss-scaled) gradients;
    `inv_scale` unscales the recorded norm.  params_flat /
    new_params_flat are the optimizer's flat master buffers before and
    after the update (`FusedAdamState.params` etc.) — the update norm is
    computed as their difference, no per-leaf tree needed.
    param_norm / update_norm pass PRECOMPUTED norms instead (they win
    over the flat buffers): the ZeRO-2 path in ddp.make_train_step uses
    them because its state buffers are rank shards whose global norms
    need a psum the caller owns.
    """
    if not isinstance(state, MetricsState):
        raise TypeError(
            f"update_metrics needs a MetricsState, got "
            f"{type(state).__name__}; build one with init_metrics() "
            "(make_train_step's build-time metrics= flag is the one "
            "place that takes True/MetricsConfig instead)")
    step = state.step + (1 if count_step else 0)
    loss_v = state.loss if loss is None else \
        jnp.asarray(loss, jnp.float32).reshape(())
    if grads is not None:
        gn = global_norm(grads) * jnp.asarray(inv_scale, jnp.float32)
    else:
        gn = state.grad_norm
    if param_norm is not None:
        pn = jnp.asarray(param_norm, jnp.float32).reshape(())
    elif params_flat is not None:
        pn = jnp.linalg.norm(params_flat.astype(jnp.float32))
    else:
        pn = state.param_norm
    if update_norm is not None:
        un = jnp.asarray(update_norm, jnp.float32).reshape(())
    elif new_params_flat is not None and params_flat is not None:
        un = jnp.linalg.norm(
            (new_params_flat.astype(jnp.float32)
             - params_flat.astype(jnp.float32)))
    else:
        un = state.update_norm
    scale_v = state.loss_scale if loss_scale is None else \
        jnp.asarray(loss_scale, jnp.float32).reshape(())
    if found_inf is not None:
        inc = jnp.asarray(found_inf).astype(jnp.int32).reshape(())
        overflow = state.overflow_count + inc
        skipped = state.skipped_steps + inc
    else:
        overflow = state.overflow_count
        skipped = state.skipped_steps
    return MetricsState(
        step=step, loss=loss_v, grad_norm=gn, param_norm=pn,
        update_norm=un, loss_scale=scale_v, overflow_count=overflow,
        skipped_steps=skipped,
        tokens_seen=state.tokens_seen + jnp.asarray(tokens, jnp.float32))
