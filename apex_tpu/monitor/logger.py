"""`MetricsLogger` — the host side of the telemetry loop.

The jitted step accumulates a `MetricsState`; the logger device_gets it
(the ONLY host sync, and only at log time), derives the host-side rates
the device cannot know — step time, tokens/sec, MFU — and fans a flat,
schema-versioned record out to sinks.

Schema: every record is a flat JSON object carrying
`monitor_schema_version`; `validate_record`/`validate_records` are the
single source of truth used by the tests, the example, and bench.py.
Bump SCHEMA_VERSION whenever a field is added/renamed so BENCH/JSONL
trajectories across rounds stay comparable (ISSUE 2 satellite).
"""

from __future__ import annotations

import math
import time
from typing import Optional, Sequence

import jax

from apex_tpu.monitor import flops as flops_lib
from apex_tpu.monitor.metrics import MetricsState
from apex_tpu.monitor.sinks import MetricSink, ScalarWriter

# v2 (ISSUE 4): JSONLSink serializes non-finite floats as null + a
# "<key>_nonfinite" marker (valid JSON, enforced with allow_nan=False)
# and tap-enabled loggers stamp the tap_* summary fields — same
# required fields as v1, but v1 readers would mis-parse an overflow
# record, so the version moves.
# v3 (ISSUE 5): the compile & HBM observatory fields — `n_compiles`
# (RecompileSentry), `hbm_bytes_in_use` / `hbm_peak_bytes_in_use` /
# `hbm_bytes_limit` (device watermarks; null on backends that don't
# report) — all OPTIONAL, type-checked by validate_record only when
# present (OPTIONAL_SCHEMA).
# v4 (ISSUE 7): the comms observatory fields — `comms_n_collectives` /
# `comms_bytes` (inventory totals), `comms_predicted_comm_s` (ICI
# roofline table price — always computed, table fallback included),
# `comms_comm_fraction` (null where the backend withholds cost
# analysis), `comms_overlap_ok` (null when the backend emits no async
# collectives — CPU) — all OPTIONAL under the same prefix-scalar rule
# as `hbm_*` (the `comms_` prefix is reserved).
# v5 (ISSUE 8): the serving fields — `serve_streams` (concurrency of
# the stamped measurement), `serve_decode_tokens_per_sec` (continuous-
# batching decode throughput over tokens ACTUALLY emitted),
# `serve_p50_ms` / `serve_p99_ms` (per-token latency percentiles over
# pure decode steps — admission/retirement churn steps carry prefill
# work and are excluded), `serve_recompile_ok`
# (the RecompileSentry verdict over the decode step: False means the
# scheduler retraced under churn, the correctness gate of
# apex_tpu.serve) — all OPTIONAL, never-null when present (a serve
# measurement that ran has all five), `serve_` prefix reserved for
# JSON scalars like `comms_`.
# v6 (ISSUE 9): the checkpointing fields — `ckpt_blocking_s` (what the
# hot path paid for the newest save: wait-for-previous-write +
# device→host snapshot), `ckpt_save_s` (the background writer's wall
# clock for the same save), `ckpt_last_step` (the newest COMMITTED
# step — the resume point), `ckpt_bytes` (committed payload size) —
# all OPTIONAL, never-null when present (a logger without a
# CheckpointManager attached, or one attached before the first save,
# simply doesn't stamp them), `ckpt_` prefix reserved for JSON
# scalars like `comms_`/`serve_`.
# v7 (ISSUE 10): the LIVE serving-observatory fields, stamped by
# `MetricsLogger(serve=engine)` from the engine's request-lifecycle
# ledger and gauges (serve/telemetry.py) — where the v5 fields quote
# a finished `measure_decode` run, these quote the engine NOW.
# Gauges (`serve_queue_depth` / `serve_slots_live` /
# `serve_pages_free` / `serve_pool_util` / `serve_requests_retired` /
# `serve_tokens_emitted`) stamp on every record; ledger percentiles
# (`serve_ttft_p50_ms` / `serve_ttft_p99_ms` / `serve_token_p50_ms` /
# `serve_token_p99_ms` / `serve_queue_wait_p99_ms` /
# `serve_queue_wait_max_ms`) stamp once a request has retired;
# `serve_slo_ok` stamps when the engine carries a ServeSLO AND the
# verdict is grounded — a breach, or a green with every configured
# axis measured (an idle engine's all-skipped "ok" is unmeasured and
# is NOT stamped: a vacuous green would paint an outage window).  All
# OPTIONAL, never-null when present (the v4 rule: no samples → no
# field, never a null), same reserved `serve_` scalar prefix as v5.
# v9 (ISSUE 13): the Mixture-of-Experts fields — `moe_aux_loss`
# (load-balancing loss, 1.0 = perfectly balanced), `moe_drop_fraction`
# (capacity-dropped assignment fraction), `moe_gate_entropy` (mean
# per-token gate entropy — falling toward 0 = router collapse),
# `moe_z_loss`, and bench's `moe_tokens_per_sec` — all OPTIONAL,
# never-null when present (a logger without an attached MoERecorder,
# or one attached before the first step, simply doesn't stamp them);
# `moe_` joins the reserved scalar prefixes.
# v8 (ISSUE 11): the fleet fault-tolerance fields —
# `ckpt_commit_barrier_s` (how long process 0's multi-host commit
# barrier waited on the slowest host's sub-manifest; stamped only by a
# multi-host CheckpointManager on process 0), `fleet_resumes`
# (completed lost-rank recovery cycles of the ElasticOrchestrator,
# stamped by `MetricsLogger(fleet=orch)`), `fleet_dp` (the topology
# currently training — shrinks at each elastic resume),
# `fleet_resume_ok` (bench's kill→resume cycle verdict).  All
# OPTIONAL, never-null when present; `fleet_` joins the reserved
# scalar prefixes.
# v10 (ISSUE 14): the serving-resilience fields — terminal-state
# lifetime counters stamped by `MetricsLogger(serve=engine)` whenever
# telemetry is attached (`serve_shed_total` / `serve_expired_total` /
# `serve_cancelled_total` — 0 is a real count for a healthy engine),
# watchdog counters stamped once an `EngineWatchdog` is attached
# (`serve_watchdog_stalls` / `serve_watchdog_restarts`), and bench's
# overload-leg stamps (`serve_shed_fraction` — shed+expired fraction
# of submissions under the 4× storm; `serve_goodput_tokens_per_sec` —
# tokens of requests that completed OK per second, the number overload
# control exists to protect).  All OPTIONAL, never-null when present;
# same reserved `serve_` scalar prefix.
# v11 (ISSUE 15): the runtime-timeline fields, stamped by
# `MetricsLogger(timeline=report)` from a measured `TimelineReport`
# (monitor.timeline over a ProfileCapture trace) —
# `timeline_device_busy_fraction` (union of device-event intervals
# over step wall time), `timeline_host_gap_ms` (mean per-step device
# idle: wall − busy), `timeline_collective_fraction` (collective share
# of device wall time), `timeline_measured_overlap_ok` (no collective
# span measured serialized — stamped ONLY where the schedule is
# measurable, i.e. TPU traces; a CPU capture simply doesn't stamp it,
# never a null).  All OPTIONAL, never-null when present; `timeline_`
# joins the reserved scalar prefixes.
SCHEMA_VERSION = 11

# field -> (python type, finite_required).  loss_scale may legitimately
# be large but is finite; grad/update norms are inf/nan ON overflow
# steps, so they are only finite-required when the step didn't overflow
# (validate_record handles the conditional).
SCHEMA = {
    "monitor_schema_version": (int, True),
    "step": (int, True),
    "loss": (float, True),
    "grad_norm": (float, False),      # finite unless overflow_delta > 0
    "param_norm": (float, True),
    "update_norm": (float, True),
    "loss_scale": (float, True),
    "overflow_count": (int, True),
    "skipped_steps": (int, True),
    "tokens_seen": (float, True),
    "step_time_ms": (float, True),
    "tokens_per_sec": (float, True),
    "mfu": (float, True),
}

# optional v3 fields (ISSUE 5) — validated only when present.  The
# bool flag is none_ok: watermark fields are null on backends whose
# allocator doesn't report (CPU), while a present n_compiles must be a
# real count.  Any other `compile_*`/`hbm_*` key must be a JSON scalar
# or null (the prefix is reserved for the observatory).
OPTIONAL_SCHEMA = {
    "n_compiles": (int, False),
    "steady_recompiles": (int, False),
    "hbm_bytes_in_use": (int, True),
    "hbm_peak_bytes_in_use": (int, True),
    "hbm_bytes_limit": (int, True),
    # v4 (ISSUE 7): comms observatory stamps.  A present count/bytes is
    # a real inventory total (never null) and the predicted comm
    # seconds is always a table price; fraction and overlap are
    # null-legal — CPU backends withhold cost analysis (fraction) and
    # emit no async collectives (overlap).
    "comms_n_collectives": (int, False),
    "comms_bytes": (int, False),
    "comms_predicted_comm_s": (float, True),
    "comms_comm_fraction": (float, True),
    "comms_overlap_ok": (bool, True),
    # v5 (ISSUE 8): serving stamps.  A serve measurement that ran
    # carries real values for all of these (no null-legal fields — on
    # a backend where serving can't run, bench simply doesn't stamp
    # them, per the try/except-per-metric convention).
    "serve_streams": (int, False),
    "serve_decode_tokens_per_sec": (float, False),
    "serve_p50_ms": (float, False),
    "serve_p99_ms": (float, False),
    "serve_recompile_ok": (bool, False),
    # v6 (ISSUE 9): checkpoint-cadence pricing.  Present only once a
    # CheckpointManager has committed a save; never null (the blocking
    # and writer costs of a save that happened are real numbers).
    "ckpt_blocking_s": (float, False),
    "ckpt_save_s": (float, False),
    "ckpt_last_step": (int, False),
    "ckpt_bytes": (int, False),
    # v7 (ISSUE 10): the live serving observatory.  Gauges are always
    # real values (a serving engine always has a queue depth);
    # percentile fields appear only once the ledger has samples, and
    # serve_slo_ok only when a ServeSLO is attached — never null.
    "serve_queue_depth": (int, False),
    "serve_slots_live": (int, False),
    "serve_pages_free": (int, False),
    "serve_pool_util": (float, False),       # instantaneous gauge
    "serve_pool_util_peak": (float, False),  # run peak (bench stamp)
    "serve_requests_retired": (int, False),
    "serve_tokens_emitted": (int, False),
    "serve_ttft_p50_ms": (float, False),
    "serve_ttft_p99_ms": (float, False),
    "serve_token_p50_ms": (float, False),
    "serve_token_p99_ms": (float, False),
    "serve_queue_wait_p99_ms": (float, False),
    "serve_queue_wait_max_ms": (float, False),
    "serve_slo_ok": (bool, False),
    # v8 (ISSUE 11): fleet fault tolerance.  Barrier seconds appear
    # only on a multi-host process 0 that committed; fleet_* appear
    # only when an ElasticOrchestrator is attached (fleet=) or bench's
    # resume cycle ran — never null.
    "ckpt_commit_barrier_s": (float, False),
    "fleet_resumes": (int, False),
    "fleet_dp": (int, False),
    "fleet_resume_ok": (bool, False),
    # v9 (ISSUE 13): the MoE plane.  Aux scalars appear once an
    # MoERecorder is attached (moe=) and fed a step's aux;
    # moe_tokens_per_sec is bench's stamp — never null.
    "moe_tokens_per_sec": (float, False),
    "moe_aux_loss": (float, False),
    "moe_z_loss": (float, False),
    "moe_drop_fraction": (float, False),
    "moe_gate_entropy": (float, False),
    # v10 (ISSUE 14): serving resilience.  Terminal counters stamp
    # with the rest of the live serve plane; watchdog counters only
    # when an EngineWatchdog is attached; shed fraction / goodput are
    # bench's overload-leg stamps — never null.
    "serve_shed_total": (int, False),
    "serve_expired_total": (int, False),
    "serve_cancelled_total": (int, False),
    "serve_watchdog_stalls": (int, False),
    "serve_watchdog_restarts": (int, False),
    "serve_shed_fraction": (float, False),
    "serve_goodput_tokens_per_sec": (float, False),
    # v11 (ISSUE 15): the measured runtime timeline.  Fractions/gap
    # stamp whenever a TimelineReport is attached; the overlap verdict
    # stamps only from a trace whose schedule is measurable (TPU) —
    # never null.
    "timeline_device_busy_fraction": (float, False),
    "timeline_host_gap_ms": (float, False),
    "timeline_collective_fraction": (float, False),
    "timeline_measured_overlap_ok": (bool, False),
}
_OPTIONAL_PREFIXES = ("compile_", "hbm_", "comms_", "serve_", "ckpt_",
                      "fleet_", "moe_", "timeline_")


def validate_record(record: dict, prev_step: Optional[int] = None) -> None:
    """Raise ValueError unless `record` matches SCHEMA: all fields
    present, right types, finite where finiteness is expected, and
    step > prev_step when given.  Extra keys are allowed (bench.py adds
    its own)."""
    if record.get("monitor_schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"monitor_schema_version {record.get('monitor_schema_version')!r}"
            f" != {SCHEMA_VERSION}")
    overflowed = record.get("overflowed_this_window", False)
    for name, (typ, finite) in SCHEMA.items():
        if name not in record:
            raise ValueError(f"missing field {name!r}")
        v = record[name]
        if (v is None and typ is float
                and isinstance(record.get(f"{name}_nonfinite"), str)):
            # JSONLSink round-trip of a non-finite float: null + marker
            # (sinks.sanitize_json_floats).  Reconstruct the value so
            # the finiteness rules below still apply — a null grad_norm
            # on a non-overflow window must keep failing.
            v = float(record[f"{name}_nonfinite"])
        if typ is float and isinstance(v, int) and not isinstance(v, bool):
            v = float(v)  # JSON round-trips 1.0 as 1
        if not isinstance(v, typ) or isinstance(v, bool):
            raise ValueError(f"field {name!r} is {type(record[name]).__name__},"
                             f" want {typ.__name__}")
        if typ is float and finite and not math.isfinite(v):
            raise ValueError(f"field {name!r} non-finite: {v}")
        if name == "grad_norm" and not overflowed and not math.isfinite(v):
            raise ValueError(f"grad_norm non-finite ({v}) on a step that "
                             "did not overflow")
    for name, (typ, none_ok) in OPTIONAL_SCHEMA.items():
        if name not in record:
            continue
        v = record[name]
        if v is None:
            if not none_ok:
                raise ValueError(f"optional field {name!r} is null but "
                                 "must carry a value when present")
            continue
        if typ is float and isinstance(v, int) and not isinstance(v, bool):
            v = float(v)  # JSON round-trips 0.0 as 0
        if not isinstance(v, typ) or (typ is not bool
                                      and isinstance(v, bool)):
            raise ValueError(f"optional field {name!r} is "
                             f"{type(v).__name__}, want {typ.__name__}")
    for k, v in record.items():
        if (k.startswith(_OPTIONAL_PREFIXES) and k not in OPTIONAL_SCHEMA
                and not k.endswith("_nonfinite")
                and not isinstance(v, (int, float, str, type(None)))):
            raise ValueError(
                f"observatory field {k!r} must be a JSON scalar or "
                f"null, got {type(v).__name__}")
    if record["step"] < 0:
        raise ValueError(f"negative step {record['step']}")
    if prev_step is not None and record["step"] <= prev_step:
        raise ValueError(
            f"non-monotonic step: {record['step']} after {prev_step}")


def validate_records(records: Sequence[dict]) -> None:
    """validate_record over a trajectory, enforcing monotonic steps."""
    prev = None
    for r in records:
        validate_record(r, prev_step=prev)
        prev = r["step"]


class MetricsLogger:
    """Derive rates + write records.

    flops_per_step enables MFU (use `monitor.flops.gpt_step_flops` et
    al.); peak_flops=None resolves the per-chip peak from the device
    kind (`flops.device_peak_flops`), falling back to the v5e bf16
    peak that scripts/gpt_anatomy.py scores against.  `.writer` is a
    SummaryWriter-compatible `ScalarWriter` over the SAME sinks, so
    `Timers.write(names, logger.writer, iteration)` interleaves timer
    scalars into the same stream.
    """

    def __init__(self, sinks: Sequence[MetricSink], *,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 log_tuner: bool = True,
                 taps: bool = False,
                 sentry=None,
                 memory: bool = False,
                 memory_device=None,
                 ckpt=None,
                 serve=None,
                 fleet=None,
                 moe=None,
                 timeline=None):
        self.sinks = list(sinks)
        self.flops_per_step = flops_per_step
        # None resolves the per-chip peak from the device kind (ISSUE 5
        # satellite) LAZILY — device_peak_flops() touches jax.devices()
        # and would force backend init as a constructor side effect;
        # the resolution happens on the first log_step that actually
        # computes MFU.  Unknown kinds fall back to V5E_BF16_PEAK so
        # pre-table numbers don't move; multi-chip runs still pass the
        # aggregate peak explicitly.
        self._peak_flops = peak_flops
        # sentry: a compile.RecompileSentry — every record gains
        # `n_compiles` (+ `steady_recompiles` once any happened), so a
        # silent retrace is visible in the same JSONL stream as the
        # step-time it inflated.  memory: stamp the hbm_* device
        # watermarks per record (None on backends that don't report —
        # the fields stay, null; schema-legal by OPTIONAL_SCHEMA).
        self.sentry = sentry
        self.memory = memory
        self.memory_device = memory_device
        # ckpt: a checkpoint.CheckpointManager — every record gains the
        # ckpt_* cadence-pricing scalars of the newest committed save
        # (ISSUE 9; nothing is stamped before the first save), so the
        # JSONL stream shows what checkpointing cost next to the
        # step-time it may have inflated.
        self.ckpt = ckpt
        # serve: a serve.DecodeEngine (anything with .serve_record())
        # — every record gains the v7 `serve_*` live gauges and ledger
        # percentiles (ISSUE 10), so "what is my TTFT p99 right now"
        # reads out of the same JSONL stream as the training metrics.
        # All host-side state the scheduler already owns: stamping
        # adds zero device syncs.
        self.serve = serve
        # fleet: a checkpoint.ElasticOrchestrator (anything with a
        # .stats() of fleet_* scalars) — every record gains the v8
        # `fleet_resumes` / `fleet_dp` fields, so an elastic topology
        # shrink is visible in the same stream as the step-times it
        # changed.
        self.fleet = fleet
        # moe: a moe.MoERecorder (anything with .moe_record()) — every
        # record gains the v9 `moe_*` aux scalars of the newest step
        # the trainer fed it (ISSUE 13), so router collapse and
        # capacity dropping are visible in the same stream as the
        # loss they degrade.  Host-side only: the trainer updates the
        # recorder with the aux pytree the step already returns.
        self.moe = moe
        # timeline: a monitor.timeline.TimelineReport (anything with
        # .timeline_record()) — every record gains the v11 timeline_*
        # measured-anatomy scalars (ISSUE 15): a run that captured a
        # profiler window stamps what the schedule actually did next
        # to the step-times it explains.  Assignable after
        # construction (`logger.timeline = analyze_trace(path)`), the
        # natural order — the trace only exists once the capture
        # window closed mid-run.
        self.timeline = timeline
        # taps=True: log_step(…, taps=tap_state) folds the flight
        # recorder's per-layer stat planes into each record as compact
        # summary fields (tap_fwd_absmax / tap_grad_absmax /
        # tap_nonfinite / tap_first_bad) — extra keys, schema-legal —
        # so divergence onset is visible in the SAME JSONL stream the
        # run already ships (ISSUE 4)
        self.taps = taps
        # stamp the active kernel-autotuner config fingerprint into
        # every record (ISSUE 3): two trajectories with different
        # fingerprints ran different tuned kernels.  Extra keys are
        # schema-legal (validate_record allows them).
        self.log_tuner = log_tuner
        self.writer = ScalarWriter(*self.sinks)
        self._last_t = time.perf_counter()
        self._last_step = 0
        self._last_tokens = 0.0
        self._last_overflows = 0

    @property
    def peak_flops(self) -> float:
        if self._peak_flops is None:
            self._peak_flops = flops_lib.device_peak_flops()
        return self._peak_flops

    @peak_flops.setter
    def peak_flops(self, value) -> None:
        self._peak_flops = value

    def reset_timer(self, metrics: Optional[MetricsState] = None) -> None:
        """Restart the rate window (call after warmup/compile so the
        first logged step_time is not dominated by compilation).  Pass
        the current MetricsState when warmup steps were COUNTED in the
        pytree: the step/token/overflow baselines resync to it —
        otherwise the first window divides by the warmup's extra steps
        and under-reports step time / inflates tokens-per-sec."""
        self._last_t = time.perf_counter()
        if metrics is not None:
            m = jax.device_get(metrics)
            self._last_step = int(m.step)
            self._last_tokens = float(m.tokens_seen)
            self._last_overflows = int(m.overflow_count)

    def log_step(self, metrics: MetricsState, extra: Optional[dict] = None,
                 taps=None, tap_names: Optional[Sequence[str]] = None,
                 ) -> dict:
        """device_get the pytree, derive rates over the window since the
        previous log_step, write to all sinks, return the record.

        taps / tap_names (with `MetricsLogger(taps=True)`): the step's
        `monitor.trace.TapState` + ordered labels; the record gains the
        tap_* summary fields (worst forward/gradient absmax across all
        taps, total non-finite element count, and the first-bad tap
        name — "" when clean)."""
        m = jax.device_get(metrics)
        now = time.perf_counter()
        step = int(m.step)
        d_steps = max(1, step - self._last_step)
        dt = max(now - self._last_t, 1e-12)
        d_tokens = float(m.tokens_seen) - self._last_tokens
        overflows = int(m.overflow_count)
        record = {
            "monitor_schema_version": SCHEMA_VERSION,
            "step": step,
            "loss": float(m.loss),
            "grad_norm": float(m.grad_norm),
            "param_norm": float(m.param_norm),
            "update_norm": float(m.update_norm),
            "loss_scale": float(m.loss_scale),
            "overflow_count": overflows,
            "skipped_steps": int(m.skipped_steps),
            "tokens_seen": float(m.tokens_seen),
            "step_time_ms": dt / d_steps * 1e3,
            "tokens_per_sec": d_tokens / dt,
            "mfu": (flops_lib.mfu(self.flops_per_step, dt / d_steps,
                                  self.peak_flops)
                    if self.flops_per_step else 0.0),
            "overflowed_this_window": overflows > self._last_overflows,
        }
        if self.log_tuner:
            try:
                from apex_tpu import tune
                t = tune.stats()
                record["tuner_fingerprint"] = t["fingerprint"]
                record["tuner_hits"] = t["hits"]
                record["tuner_misses"] = t["misses"]
            except Exception:  # pragma: no cover — never break logging
                pass
        if self.taps and taps is not None:
            record.update(self._tap_summary(taps, tap_names))
        if self.sentry is not None:
            record["n_compiles"] = int(self.sentry.n_compiles)
            if self.sentry.steady_recompiles:
                record["steady_recompiles"] = int(
                    self.sentry.steady_recompiles)
        if self.memory:
            import apex_tpu.monitor.compile.watermarks as _wm
            record.update(_wm.hbm_watermarks(self.memory_device))
        if self.ckpt is not None:
            record.update(self.ckpt.stats())
        if self.serve is not None:
            record.update(self.serve.serve_record())
        if self.fleet is not None:
            record.update(self.fleet.stats())
        if self.moe is not None:
            record.update(self.moe.moe_record())
        if self.timeline is not None:
            record.update(self.timeline.timeline_record())
        if extra:
            record.update(extra)
        for s in self.sinks:
            s.write(record)
        self._last_t = now
        self._last_step = step
        self._last_tokens = float(m.tokens_seen)
        self._last_overflows = overflows
        return record

    @staticmethod
    def _tap_summary(taps, tap_names: Optional[Sequence[str]]) -> dict:
        """Compress a TapState into flat record fields: the worst
        per-plane absmax over all taps and the non-finite provenance.
        One device_get of a (2n, 4)-ish pytree — same cost class as
        the metrics fetch this call already pays."""
        st = jax.device_get(taps)
        names = list(tap_names or [])

        def worst(plane):
            vals = [float(v) for v in plane[:, 0]]
            finite = [v for v in vals if math.isfinite(v)]
            # a non-finite absmax IS the signal — report inf, not the
            # max of the surviving finite taps
            return max(vals, default=0.0) if len(finite) == len(vals) \
                else float("inf")

        n_bad = float(st.fwd[:, 3].sum() + st.grad[:, 3].sum()) \
            if st.fwd.size else 0.0
        first_bad = ""
        for idx in (int(st.first_bad_fwd), int(st.first_bad_grad)):
            if 0 <= idx < len(names):
                first_bad = names[idx]
                break
        return {
            "tap_fwd_absmax": worst(st.fwd) if st.fwd.size else 0.0,
            "tap_grad_absmax": worst(st.grad) if st.grad.size else 0.0,
            "tap_nonfinite": n_bad,
            "tap_first_bad": first_bad,
        }

    def close(self) -> None:
        for s in self.sinks:
            s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
