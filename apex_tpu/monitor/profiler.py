"""Profiler capture scoped to a step window.

`profile_capture(range(10, 13), logdir=...)` arms a `jax.profiler`
trace that starts when the first step of the window begins and stops
after its last step — the usual "skip compile, grab 3 steady-state
steps" workflow, without littering the training loop with
start/stop_trace calls:

    cap = monitor.profile_capture(range(3, 6), logdir="/tmp/trace")
    for i in range(steps):
        with cap.step(i):
            state, ... = train_step(...)
    cap.close()   # safety net if the loop exits early
    report = monitor.analyze_trace(cap.trace_path())  # ISSUE 15

Each captured step is wrapped in a trace annotation (default name
"train-step"); phase timers used inside the step already emit
`TraceAnnotation`s with their own `_Timer` names (utils/timers.py), so
the profile shows the same names `Timers.log` prints.  After the
window closed, `trace_path()` resolves the `trace.json.gz` the
profiler wrote so `monitor.timeline.analyze_trace` can turn the
capture into a measured step anatomy without the caller spelunking
`logdir/plugins/profile/…` by hand.
"""

from __future__ import annotations

import contextlib
from typing import Iterable, Optional

import jax


class ProfileStepReentryError(RuntimeError):
    """`ProfileCapture.step(i)` was entered while a previous `step()`
    context was still open.  Nested step scopes would nest the trace
    annotations and make every "step" in the resulting trace the hull
    of its children — the capture contract is one scope per training
    step, entered sequentially."""


class ProfileCapture:
    def __init__(self, step_range: Iterable[int], *,
                 logdir: str = "/tmp/apex_tpu_trace",
                 annotation: str = "train-step"):
        steps = sorted(set(int(s) for s in step_range))
        # one capture = ONE contiguous trace window [first, last] —
        # start_trace fires entering `first`, stop_trace after `last`.
        # A gapped range (e.g. {3, 10}) used to be silently treated as
        # its hull, capturing steps the caller never asked for; honor
        # the contract by refusing it instead (two windows = two
        # ProfileCapture objects)
        if steps and steps[-1] - steps[0] != len(steps) - 1:
            raise ValueError(
                f"profile step_range must be contiguous, got {steps}; "
                "a capture arms a single [first, last] trace window — "
                "use one ProfileCapture per window")
        self._first = steps[0] if steps else None
        self._last = steps[-1] if steps else None
        self.logdir = logdir
        self.annotation = annotation
        self._active = False
        self._step_depth = 0    # open step() scopes (re-entry guard)
        self._fired = False     # did a trace window ever open?

    @property
    def active(self) -> bool:
        return self._active

    @contextlib.contextmanager
    def step(self, i: int):
        """Wrap one training step; starts/stops the trace at the window
        edges and annotates the step body."""
        if self._step_depth > 0 and self._active:
            # re-entering while a trace window is OPEN (a nested `with
            # cap.step(...)`, or a generator/except path that never
            # unwound the previous scope) — a NAMED error, because the
            # silent alternative is a trace whose "steps" are hulls of
            # their children; outside a window the nesting is inert
            # (no annotation emitted) and stays permitted
            raise ProfileStepReentryError(
                f"ProfileCapture.step({i}) entered while a previous "
                "step scope's trace window is still open — one scope "
                "per training step, sequentially")
        # the depth (not a bool) keeps inert nesting from opening the
        # window nested or resetting the guard for its outer scope:
        # only a TOP-LEVEL step entry may arm the trace
        if (self._step_depth == 0
                and not self._active and not self._fired
                and self._first is not None
                and self._first <= i <= self._last):
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._fired = True
        if self._active:
            # StepTraceAnnotation groups the step in the trace viewer's
            # step axis; older jax falls back to a plain annotation
            mk = getattr(jax.profiler, "StepTraceAnnotation", None)
            ann = (mk(self.annotation, step_num=i) if mk is not None
                   else jax.profiler.TraceAnnotation(self.annotation))
        else:
            ann = contextlib.nullcontext()
        self._step_depth += 1
        try:
            with ann:
                yield self
        finally:
            self._step_depth -= 1
            if self._active and i >= self._last \
                    and self._step_depth == 0:
                self.close()

    def close(self) -> None:
        """Stop the trace if armed (idempotent)."""
        if self._active:
            self._active = False
            jax.profiler.stop_trace()

    def trace_path(self) -> Optional[str]:
        """Path of the newest `trace.json.gz` the capture wrote under
        `logdir` — what `monitor.timeline.analyze_trace` consumes.
        None when no window ever fired (the loop never reached
        `first`) or the profiler produced no trace file.  Resolved at
        call time: the profiler writes the file on `stop_trace`, so
        call this after the window closed (`close()` or the last
        step's exit)."""
        if not self._fired:
            return None
        from apex_tpu.monitor.timeline import events as _ev
        return _ev.newest_trace(self.logdir)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def profile_capture(step_range: Iterable[int], *,
                    logdir: str = "/tmp/apex_tpu_trace",
                    annotation: str = "train-step") -> ProfileCapture:
    """Build a `ProfileCapture` for the given step window (see module
    docstring for the loop idiom)."""
    return ProfileCapture(step_range, logdir=logdir, annotation=annotation)
