"""Live device-memory watermarks + OOM classification.

TPU runtimes expose an allocator snapshot via
`device.memory_stats()` — `bytes_in_use`, `peak_bytes_in_use`,
`bytes_limit`, ...  Sampling it is a host-side dict read (no device
sync, no effect on the compiled program), so the logger can stamp the
two watermark fields into every record at log interval.  CPU backends
return None; every helper here degrades to None fields rather than
raising — the JSONL schema treats `hbm_*` as optional-null.

`is_oom(exc)` classifies the exception the flight-recorder guard just
caught: a RESOURCE_EXHAUSTED (or allocator "out of memory") death gets
the full forensics treatment — the dump attaches the last
`CompileReport` and a fresh memory snapshot, so the run dies with a
budget table instead of a bare stack trace.
"""

from __future__ import annotations

import re
from typing import Optional

import jax

# the known watermark fields (the TPU runtime's canonical names);
# hbm_watermarks() always emits these three — None when the runtime
# withholds one or reports a value that does not coerce to an int —
# and passes any EXTRA integer-valued stats keys through under the
# same hbm_ prefix (a future allocator reporting more must not lose
# fields to this tuple being stale; the JSONL schema treats every
# hbm_* as an optional null-legal scalar).  Extend this tuple when a
# real runtime's names are verified.
WATERMARK_FIELDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "Out of memory", "out of memory")
# bare "OOM" must match as a word — "BLOOM"/"ZOOM" in an error message
# is not an allocator death, and a wrongly-classified crash dump
# renders actively misleading forensics
_OOM_WORD = re.compile(r"\bOOM\b")


def device_memory_stats(device=None) -> Optional[dict]:
    """One device's allocator snapshot, or None when the backend does
    not report (CPU, older runtimes).  device defaults to
    `jax.devices()[0]` — the addressable chip this process feeds."""
    try:
        if device is None:
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not isinstance(stats, dict):
        return None
    return stats


def _as_int(value) -> Optional[int]:
    """Coerce one allocator stat to an int, or None — a runtime that
    reports a float, a numpy scalar, or garbage for a field must cost
    that FIELD, never the record (bools are not byte counts)."""
    if isinstance(value, bool):
        return None
    try:
        return int(value)
    except (TypeError, ValueError):
        return None


def hbm_watermarks(device=None, stats: Optional[dict] = None) -> dict:
    """The per-record watermark fields: always the three
    WATERMARK_FIELDS (`hbm_bytes_in_use` / `hbm_peak_bytes_in_use` /
    `hbm_bytes_limit`, None when the backend withholds or mangles
    one), plus an `hbm_<key>` passthrough for every EXTRA
    integer-valued key the runtime reports — unknown allocator fields
    ride along instead of vanishing.  `stats` overrides the device
    read (tests feed fake dicts)."""
    if stats is None:
        stats = device_memory_stats(device) or {}
    out = {f"hbm_{k}": _as_int(stats.get(k)) for k in WATERMARK_FIELDS}
    for k, v in stats.items():
        if k in WATERMARK_FIELDS or not isinstance(k, str):
            continue
        iv = _as_int(v)
        if iv is not None:
            out[f"hbm_{k}"] = iv
    return out


def all_device_memory_stats() -> Optional[dict]:
    """{device_id: memory_stats dict} over local devices, or None when
    no device reports — the crash-dump form (an OOM on chip 3 of 4
    should name chip 3)."""
    out = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return None
    for d in devices:
        s = device_memory_stats(d)
        if s is not None:
            out[str(getattr(d, "id", len(out)))] = s
    return out or None


def is_oom(exc: BaseException) -> bool:
    """True when the exception is an allocator death worth full
    forensics (RESOURCE_EXHAUSTED / out-of-memory / the word "OOM"),
    matched on the repr so it works across jaxlib's exception-type
    renames."""
    msg = repr(exc)
    return (any(m in msg for m in _OOM_MARKERS)
            or _OOM_WORD.search(msg) is not None)
