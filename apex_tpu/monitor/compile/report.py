"""AOT compile audit: `analyze_step(step_fn, args) -> CompileReport`.

XLA already computes everything an operator needs to pick a batch size
— per-program argument/output/temp/alias bytes and generated-code size
(`compiled.memory_analysis()`), flops and bytes-accessed
(`compiled.cost_analysis()`) — at compile time, before a single step
executes.  This module lowers and compiles WITHOUT executing and folds
those numbers into one `CompileReport` that also answers the two
questions the raw analyses don't:

  * did donation actually take?  A donated input whose bytes do NOT
    show up as output aliasing means XLA kept a second copy alive —
    the "three fp32 state copies per step" failure bench.py's baseline
    works around by hand.  `donated_bytes` vs `alias_bytes` makes that
    a boolean (`donation_ok`), checked per program, not per anecdote.
  * does XLA's flop count agree with `monitor.flops`' analytic
    accounting?  Every MFU number the telemetry stack publishes divides
    by the analytic count; `flops_divergence` > `flops_tol` (default
    10%) flags the accounting before a wrong MFU lands in a table.

Everything degrades gracefully under `JAX_PLATFORMS=cpu` or an XLA
build that withholds an analysis: optional fields become None, nothing
raises.  The audit is pure AOT — it never touches the step's compiled
program or its numerics (the step is byte-identical whether or not it
was analyzed; tests/test_compile_report.py holds that line).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import numpy as np

# donated bytes may legitimately not alias in full: tiny non-donatable
# leaves (an i32 step counter whose output layout differs, scalar
# flags) ride inside big donated pytrees.  5% covers those without
# masking a real failure — a lost fp32 master copy is 1/3 of the state.
DONATION_TOL = 0.05


@dataclasses.dataclass
class CompileReport:
    """One compiled program's memory/cost anatomy (host-side, JSON-able
    via `to_dict`).  Fields from a backend analysis that is unavailable
    (CPU, older runtimes) are None — never fabricated.

    Bytes fields are per-device (what one chip's HBM sees).  `flops` /
    `bytes_accessed` are XLA cost-analysis totals; `analytic_flops` is
    the caller's `monitor.flops` accounting when given.  `budget` is
    the HBM budget table: traced per-argument bytes classified into
    params / optimizer_state / inputs (see `analyze_step`), plus the
    compiled program's output/temp/code terms.
    """

    backend: str
    device_kind: Optional[str]
    # memory_analysis()
    argument_bytes: Optional[int]
    output_bytes: Optional[int]
    temp_bytes: Optional[int]
    alias_bytes: Optional[int]
    generated_code_bytes: Optional[int]
    # cost_analysis()
    flops: Optional[float]
    bytes_accessed: Optional[float]
    # per top-level argument traced bytes, keyed by arg name
    arg_bytes: dict
    # donation verification
    donated_bytes: int
    undonated_bytes: Optional[int]
    donation_ok: Optional[bool]
    # flops cross-check vs monitor.flops analytic accounting
    analytic_flops: Optional[float]
    flops_divergence: Optional[float]
    flops_ok: Optional[bool]
    # HBM budget classification (params / optimizer_state / inputs /
    # activations_temps / outputs / generated_code)
    budget: dict
    # static-analysis attachment (ISSUE 6): analyze_step(..., lint=True)
    # runs apex_tpu.lint's program passes over the SAME step/args and
    # stores {"ok": bool, "findings": [Finding.to_dict(), ...]} here —
    # so the flight-recorder crash dump (which carries this report)
    # dies with the lint verdict alongside the HBM budget.  None when
    # linting was not requested.
    lint: Optional[dict] = None
    # comms attachment (ISSUE 7): analyze_step(..., comms=True) runs
    # monitor.comms' collective inventory + overlap analysis + ICI
    # roofline over the SAME compiled executable (no second compile)
    # and stores the CommsReport.to_dict() here — the crash dump then
    # carries the communication anatomy alongside the HBM budget, with
    # no recorder schema change (the field rides inside this report,
    # exactly like `lint`).  None when comms was not requested.
    comms: Optional[dict] = None

    def to_dict(self) -> dict:
        """Flat JSON-able dict (what the flight recorder attaches)."""
        return dataclasses.asdict(self)


def _leaf_bytes(leaf) -> int:
    """Traced size of one abstract/concrete array leaf."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays / ShapeDtypeStructs."""
    return sum(_leaf_bytes(l) for l in jax.tree_util.tree_leaves(tree))


def _classify_budget(args: Sequence[Any], names: Sequence[str]) -> dict:
    """Split the traced argument bytes into the budget classes an
    operator reasons in.  Convention (the `make_train_step` arg order):
    an arg named `opt_state` with NamedTuple fields contributes its
    master buffer (`params`/`params_shard` fields) to "params" and the
    rest (moments, step counter) to "optimizer_state"; an arg whose
    name contains `kv_cache` or `page` is the serving path's paged KV
    pool (ISSUE 8 — the thing a serve report must price separately
    from weights: its size scales with CONCURRENT USERS, not model
    size); an arg named `params` is a bare weight pytree (the serve
    decode step passes weights without an optimizer wrapper); every
    other arg counts as "inputs" (batch, scaler, metrics pytree,
    timing rows)."""
    params = opt_state = inputs = kv_cache = 0
    for name, arg in zip(names, args):
        if name == "opt_state" and hasattr(arg, "_fields"):
            for field in arg._fields:
                b = tree_bytes(getattr(arg, field))
                if field in ("params", "params_shard"):
                    params += b
                else:
                    opt_state += b
        elif "kv_cache" in name or "page" in name:
            kv_cache += tree_bytes(arg)
        elif name == "params":
            params += tree_bytes(arg)
        else:
            inputs += tree_bytes(arg)
    return {"params": params, "optimizer_state": opt_state,
            "inputs": inputs, "kv_cache": kv_cache}


def _cost_entry(compiled) -> Optional[dict]:
    """cost_analysis() is a list of per-program dicts on jax 0.4.x and
    a single dict on newer releases; normalize to the first program's
    dict (the train step is one program)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def analyze_step(step_fn, args: Sequence[Any], *,
                 donated: Optional[Sequence[int]] = None,
                 arg_names: Optional[Sequence[str]] = None,
                 analytic_flops: Optional[float] = None,
                 flops_tol: float = 0.10,
                 donation_tol: float = DONATION_TOL,
                 lint: bool = False,
                 comms: bool = False) -> CompileReport:
    """Lower + compile `step_fn(*args)` WITHOUT executing and return
    the `CompileReport`.

    step_fn: anything with `.lower(*args)` — a jitted function, or the
    step `ddp.make_train_step` / `make_tp_dp_train_step` return (they
    attach a `.lower` that applies the same argument mapping as the
    call path).  args may be real arrays OR `jax.ShapeDtypeStruct`s —
    the audit never needs device buffers.

    donated: indices into `args` whose buffers the step donates.  None
    reads `step_fn.donate_argnums` (the builders attach it); pass ()
    to skip the donation check.  arg_names labels the budget table
    (None reads `step_fn.arg_names`, falling back to `arg{i}`).
    analytic_flops: the `monitor.flops` count for one step — the
    cross-check that validates every published MFU number.
    lint: also run `apex_tpu.lint`'s static program passes
    (dtype-policy, collectives, donation incl. the DN302 cross-check
    against THIS report's donation_ok) over the same step/args and
    attach the result as `report.lint` — so a crash dump carrying the
    report carries the lint verdict too.
    comms: also run `monitor.comms`' collective inventory + overlap
    analysis + ICI roofline over the SAME compiled executable (reused
    — no second XLA compile) and attach `CommsReport.to_dict()` as
    `report.comms` (ISSUE 7); replica groups map back to the step's
    `mesh_axis_names`/`mesh_axis_sizes` when the builder attached them.
    """
    lower = getattr(step_fn, "lower", None)
    if lower is None:
        raise TypeError(
            f"{type(step_fn).__name__} has no .lower — pass a jitted "
            "function or a step built by ddp.make_train_step / "
            "make_tp_dp_train_step")
    if donated is None:
        donated = getattr(step_fn, "donate_argnums", ())
    if arg_names is None:
        arg_names = getattr(step_fn, "arg_names", None)
    names = list(arg_names) if arg_names is not None else []
    names += [f"arg{i}" for i in range(len(names), len(args))]
    names = names[:len(args)]

    compiled = lower(*args).compile()

    dev = jax.devices()[0]
    backend = jax.default_backend()
    device_kind = getattr(dev, "device_kind", None)

    arg_b = op_b = tmp_b = ali_b = code_b = None
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        arg_b = getattr(mem, "argument_size_in_bytes", None)
        op_b = getattr(mem, "output_size_in_bytes", None)
        tmp_b = getattr(mem, "temp_size_in_bytes", None)
        ali_b = getattr(mem, "alias_size_in_bytes", None)
        code_b = getattr(mem, "generated_code_size_in_bytes", None)

    cost = _cost_entry(compiled)
    xla_flops = cost.get("flops") if cost else None
    bytes_accessed = cost.get("bytes accessed") if cost else None

    per_arg = {nm: tree_bytes(a) for nm, a in zip(names, args)}
    donated_bytes = sum(tree_bytes(args[i]) for i in donated
                        if 0 <= i < len(args))
    undonated = donation_ok = None
    if donated_bytes and ali_b is not None:
        undonated = max(0, donated_bytes - int(ali_b))
        donation_ok = undonated <= donated_bytes * donation_tol
    elif not donated_bytes:
        undonated, donation_ok = 0, True

    divergence = flops_ok = None
    if analytic_flops and xla_flops:
        divergence = abs(float(xla_flops) - float(analytic_flops)) \
            / max(float(analytic_flops), 1.0)
        flops_ok = divergence <= flops_tol

    budget = _classify_budget(args, names)
    budget["activations_temps"] = tmp_b
    budget["outputs"] = op_b
    budget["generated_code"] = code_b

    report = CompileReport(
        backend=backend, device_kind=device_kind,
        argument_bytes=None if arg_b is None else int(arg_b),
        output_bytes=None if op_b is None else int(op_b),
        temp_bytes=None if tmp_b is None else int(tmp_b),
        alias_bytes=None if ali_b is None else int(ali_b),
        generated_code_bytes=None if code_b is None else int(code_b),
        flops=None if xla_flops is None else float(xla_flops),
        bytes_accessed=(None if bytes_accessed is None
                        else float(bytes_accessed)),
        arg_bytes=per_arg,
        donated_bytes=int(donated_bytes),
        undonated_bytes=undonated,
        donation_ok=donation_ok,
        analytic_flops=(None if analytic_flops is None
                        else float(analytic_flops)),
        flops_divergence=divergence,
        flops_ok=flops_ok,
        budget=budget,
    )
    if lint:
        # advisory, never fatal (the observatory's degradation
        # contract): a lint-side crash must not void the audit that
        # already succeeded — it becomes {"ok": None, "error": ...}
        try:
            from apex_tpu import lint as lint_lib
            findings = lint_lib.lint_step(
                step_fn, args, program="analyze_step",
                arg_names=names, donate_argnums=donated,
                compile_report=report)
            report.lint = {"ok": not findings,
                           "findings": [f.to_dict() for f in findings]}
        except Exception as e:
            report.lint = {"ok": None, "findings": [],
                           "error": repr(e)[:200]}
    if comms:
        # same degradation contract as lint: the comms plane is
        # advisory here — a parser-side surprise must not void the
        # memory/donation audit that already succeeded
        try:
            from apex_tpu.monitor import comms as comms_lib
            report.comms = comms_lib.comms_report(
                step_fn, args, compiled=compiled).to_dict()
        except Exception as e:
            report.comms = {"ok": None, "error": repr(e)[:200]}
    return report


def _human_bytes(b) -> str:
    if b is None:
        return "n/a"
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if b >= div:
            return f"{b / div:.2f} {unit}"
    return f"{int(b)} B"


def render_budget_table(report) -> str:
    """The HBM budget table, the thing an operator reads before picking
    a batch size.  Accepts a CompileReport or its to_dict() (the crash
    dump attaches the dict form)."""
    r = report.to_dict() if hasattr(report, "to_dict") else dict(report)
    budget = r.get("budget") or {}
    lines = [
        "=== HBM budget ===",
        f"backend: {r.get('backend')}"
        + (f" ({r['device_kind']})" if r.get("device_kind") else ""),
        "| class               |       size |",
        "|---|---|",
    ]
    for key, label in (("params", "params (master)"),
                       ("optimizer_state", "optimizer state"),
                       ("kv_cache", "kv cache (pages)"),
                       ("inputs", "inputs (batch etc.)"),
                       ("activations_temps", "activations + temps"),
                       ("outputs", "outputs"),
                       ("generated_code", "generated code")):
        if key == "kv_cache" and not budget.get(key):
            continue          # training steps have no pool; keep tables tidy
        lines.append(f"| {label:<19} | "
                     f"{_human_bytes(budget.get(key)):>10} |")
    alias = r.get("alias_bytes")
    if alias is not None:
        lines.append(f"| aliased (donated)   | "
                     f"{_human_bytes(alias):>10} |")
    don = r.get("donation_ok")
    if don is False:
        lines.append(
            f"** DONATION FAILED: "
            f"{_human_bytes(r.get('undonated_bytes'))} of "
            f"{_human_bytes(r.get('donated_bytes'))} donated input NOT "
            "aliased — a second state copy is alive")
    elif don is True and r.get("donated_bytes"):
        lines.append("donation: ok (donated state aliases in place)")
    if r.get("flops_ok") is False:
        lines.append(
            f"** FLOPS ACCOUNTING DIVERGES: xla {r.get('flops'):.3e} vs "
            f"analytic {r.get('analytic_flops'):.3e} "
            f"({100 * r.get('flops_divergence'):.0f}% — MFU numbers "
            "derived from the analytic count are suspect)")
    elif r.get("flops_divergence") is not None:
        lines.append(
            f"flops: xla agrees with analytic accounting to "
            f"{100 * r['flops_divergence']:.1f}%")
    lint = r.get("lint")
    if lint is not None:
        if lint.get("ok"):
            lines.append("lint: clean (static program passes)")
        else:
            rules = sorted({f.get("rule", "?")
                            for f in lint.get("findings") or []})
            lines.append(
                f"** LINT: {len(lint.get('findings') or [])} "
                f"finding(s) [{', '.join(rules)}] — run "
                "scripts/lint_step.py for the full report")
    comms = r.get("comms")
    if comms is not None:
        if comms.get("collectives") is None:       # analyzer crashed
            lines.append(f"comms: unavailable "
                         f"({comms.get('error', '?')[:80]})")
        else:
            n = sum((comms.get("counts") or {}).values())
            total = comms.get("total_comm_bytes", 0)
            if comms.get("overlap_ok"):
                verdict = ("overlap ok" if comms.get("async_supported")
                           else "overlap n/a on this backend")
            else:
                n_ser = sum(1 for c in comms["collectives"]
                            if c.get("serialized"))
                verdict = f"** {n_ser} SERIALIZED"
            lines.append(
                f"comms: {n} collective(s), {_human_bytes(total)} — "
                f"{verdict} (render_comms_table for the full table)")
    return "\n".join(lines)
