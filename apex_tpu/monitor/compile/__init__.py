"""apex_tpu.monitor.compile — the compile & HBM observatory (ISSUE 5).

The monitor stack's third axis, after "how fast" (metrics/MFU, ISSUE
2) and "where did numerics break" (trace, ISSUE 4): the compiled
program itself.  Three cooperating pieces:

  * report     — `analyze_step(step_fn, args) -> CompileReport`: AOT
                 lower+compile WITHOUT executing; per-program
                 argument/output/temp/alias bytes + generated-code
                 size (memory_analysis), flops/bytes-accessed
                 (cost_analysis), donation verification, the
                 analytic-flops cross-check that validates MFU, and
                 the HBM budget table (params / optimizer state /
                 activations+temps).
  * sentry     — `RecompileSentry`: wraps a jitted step, counts
                 traces/compiles, records the argument signature that
                 triggered each retrace, warns once on steady-state
                 recompiles; events ride into `MetricsLogger` records
                 and the `FlightRecorder` ring.
  * watermarks — per-log-interval `device.memory_stats()` sampling
                 (None on CPU, never a crash) and `is_oom` so the
                 flight-recorder guard can attach the last
                 CompileReport + memory snapshot to a
                 RESOURCE_EXHAUSTED crash dump.

See docs/observability.md ("HBM budget & recompile debugging").
"""

from apex_tpu.monitor.compile.report import (  # noqa: F401
    CompileReport,
    analyze_step,
    render_budget_table,
    tree_bytes,
)
from apex_tpu.monitor.compile.sentry import RecompileSentry  # noqa: F401
# NOTE: the module itself is deliberately NOT shadowed — the function
# export is named hbm_watermarks so `compile.watermarks` stays the
# submodule (recorder/logger import it by module path)
from apex_tpu.monitor.compile.watermarks import (  # noqa: F401
    WATERMARK_FIELDS,
    all_device_memory_stats,
    device_memory_stats,
    hbm_watermarks,
    is_oom,
)
