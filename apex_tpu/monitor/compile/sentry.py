"""`RecompileSentry` — silent-recompile detection for jitted steps.

A steady-state retrace is the observability gap that turns into "the
run got 2x slower and nobody knows why": a batch whose leading dim
drifted, a dtype that flipped after a checkpoint reload, a python
scalar captured as a weak type.  XLA recompiles silently; the only
symptom is step time.

The sentry wraps the step callable.  When the underlying jitted
function exposes `_cache_size()` (the builders attach it as
`step.jitted`), the cache size is polled across each call — the
authoritative signal, catching compiles no argument change announces
(the donated-buffer layout second compile) — and the argument
signature (pytree structure + per-leaf shape/dtype; python scalars by
type+value — a changed scalar retraces too) is computed ONLY when a
compile actually fired, keeping per-step overhead out of timed
benchmark windows.  Without a reachable cache, every call is
fingerprinted and a new signature is the compile proxy.  Every
compile is recorded as
an event carrying the signature that triggered it; after
`mark_steady()` any further compile warns ONCE and counts in
`steady_recompiles` (bench.py asserts that stays 0 per config).

Pure host-side bookkeeping: the wrapped call is forwarded untouched,
so training numerics are bitwise identical with and without the
sentry (tests/test_compile_report.py).
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

_MAX_EVENTS = 64


def _sig_of(args, kwargs) -> str:
    """Stable shape/dtype signature of one call's arguments."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    parts = []
    for l in leaves:
        shape = getattr(l, "shape", None)
        dtype = getattr(l, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{tuple(shape)}:{dtype}")
        else:
            parts.append(f"{type(l).__name__}={l!r}")
    return f"{treedef}|{';'.join(parts)}"


class RecompileSentry:
    """Wrap a step: `sentry = RecompileSentry(step); sentry(*args)`.

    name: label in warnings/events.  recorder: an optional
    `trace.FlightRecorder` — every compile event is also pushed into
    its ring-side event list (`note_compile_event`) so a crash dump
    tells the recompile story too.  warn: emit the one-time
    steady-state warning (disable in benchmarks that assert instead).
    """

    def __init__(self, step_fn: Callable, *, name: str = "train_step",
                 recorder=None, warn: bool = True):
        self._fn = step_fn
        self.name = name
        self.recorder = recorder
        self.warn = warn
        self.calls = 0
        self.n_compiles = 0
        self.steady_recompiles = 0
        self.events = []          # [{call, kind, signature}]
        self._signatures = {}     # sig -> first-seen call index
        self._steady = False
        self._warned = False
        # poll the jit cache when reachable: the builders attach the
        # underlying jitted fn as `step.jitted`; a bare jitted step IS
        # its own cache owner
        cache_owner = getattr(step_fn, "jitted", step_fn)
        self._cache_size = getattr(cache_owner, "_cache_size", None)

    @property
    def wrapped(self):
        """The step underneath — for tools that need to TRACE the step
        without running the sentry's host-side bookkeeping on tracer
        arguments (apex_tpu.lint traces `wrapped`, else the trace
        would bump `calls` and pre-register the argument signature,
        hiding the genuine first compile from the signature-proxy
        path)."""
        return self._fn

    def _poll(self) -> Optional[int]:
        if self._cache_size is None:
            return None
        try:
            return int(self._cache_size())
        except Exception:  # never let introspection break a step
            return None

    def __call__(self, *args, **kwargs):
        before = self._poll()
        polled = before is not None
        # with a working cache poll the signature is only needed when a
        # compile actually happened — computing it per call would put a
        # pytree flatten + treedef repr inside timed benchmark windows
        # (a ~1000-leaf per-leaf state pays real string work per step)
        sig = None if polled else _sig_of(args, kwargs)
        out = self._fn(*args, **kwargs)
        after = self._poll()
        self.calls += 1
        if polled and after is not None:
            # cache growth is authoritative when visible
            compiled = after > before
        else:
            compiled = sig is not None and sig not in self._signatures
        if compiled:
            if sig is None:
                sig = _sig_of(args, kwargs)
            if sig not in self._signatures:
                self._signatures[sig] = self.calls
            self.n_compiles += 1
            event = {"call": self.calls,
                     "kind": ("compile" if self.n_compiles == 1
                              else "retrace"),
                     "steady_state": self._steady,
                     "signature": sig if len(sig) <= 512 else
                     sig[:509] + "..."}
            if len(self.events) < _MAX_EVENTS:
                self.events.append(event)
            if self.recorder is not None:
                try:
                    self.recorder.note_compile_event(
                        dict(event, name=self.name))
                except Exception:
                    pass
            if self._steady:
                self.steady_recompiles += 1
                if self.warn and not self._warned:
                    self._warned = True
                    warnings.warn(
                        f"RecompileSentry({self.name}): steady-state "
                        f"recompile at call {self.calls} — argument "
                        f"signature {event['signature']}; every such "
                        "step pays full XLA compilation",
                        RuntimeWarning, stacklevel=2)
        return out

    def mark_steady(self) -> None:
        """End of warmup: compiles were expected until now; from here
        every compile is a steady-state recompile (warned + counted)."""
        self._steady = True

    @property
    def n_signatures(self) -> int:
        return len(self._signatures)

    def summary(self) -> dict:
        """Flat JSON-able snapshot (bench.py stamps this per config)."""
        return {"calls": self.calls, "n_compiles": self.n_compiles,
                "n_signatures": self.n_signatures,
                "steady_recompiles": self.steady_recompiles}

    def __getattr__(self, item):
        # forward step attributes (tap_names, lower, donate_argnums,
        # arg_names ...) so a sentry-wrapped step still audits/labels
        return getattr(self._fn, item)
