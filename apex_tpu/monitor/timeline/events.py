"""Chrome trace-event parsing for the runtime timeline observatory.

`jax.profiler.start_trace`/`stop_trace` (and therefore
`monitor.ProfileCapture`) write a Chrome trace-event JSON —
`<host>.trace.json.gz` under `logdir/plugins/profile/<stamp>/` — that
nothing in the repo ever read back: the comms observatory (ISSUE 7)
classifies *expected* overlap from HLO structure, but the trace is the
only artifact that records what the scheduler actually DID.  This
module is the backend-free half of closing that loop: it parses the
trace file into typed events without importing jax, so the analysis
layer (`timeline/report.py`) and its tests run on committed/.

The format (one JSON object, `traceEvents` list):

  * `"ph": "M"` metadata events name processes and threads —
    `process_name` args carry `/device:TPU:0`-style names on TPU and
    `/host:CPU` on CPU (where XLA's thunk executor threads play the
    device role), `thread_name` labels the per-pid lanes ("XLA Ops"
    on TPU device pids, `tf_XLATfrtCpuClient/…` on CPU).
  * `"ph": "X"` complete events carry `ts`/`dur` in MICROSECONDS.
    Device-executed HLO ops carry `args.hlo_op` (the instruction name
    of the OPTIMIZED module — the same namespace the comms
    observatory's inventory uses, which is what makes the
    predicted-vs-measured crosscheck exact); `StepTraceAnnotation`
    step markers carry `args.step_num`.

Anything else (`B`/`E` pairs, counters, flow events) is ignored —
jax's converter emits complete events only, and the analysis needs
nothing more.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import os
from typing import Dict, List, Optional, Tuple


class TraceParseError(ValueError):
    """A profiler trace that cannot be parsed — truncated/corrupt gzip,
    invalid JSON, or JSON that is not a Chrome trace-event object.  The
    NAMED error every malformed-trace path raises (the analysis layer
    never lets a bad file escape as a bare json/gzip exception)."""


@dataclasses.dataclass
class TraceEvent:
    """One complete ("X") trace event.  ts/dur in microseconds."""

    name: str
    pid: int
    tid: int
    ts: float
    dur: float
    hlo_op: str                 # args.hlo_op ("" when absent)
    step_num: Optional[int]     # args.step_num (step annotations only)

    @property
    def end(self) -> float:
        return self.ts + self.dur


@dataclasses.dataclass
class TraceEvents:
    """A parsed trace: complete events + the process/thread name maps
    the metadata events declared."""

    events: List[TraceEvent]
    process_names: Dict[int, str]
    thread_names: Dict[Tuple[int, int], str]
    path: Optional[str] = None


def load_trace(path: str) -> dict:
    """Read a `trace.json[.gz]` file into its JSON object.  Raises
    TraceParseError (never a bare gzip/json error) on a truncated or
    corrupt file — a preempted capture must degrade to a named,
    catchable failure, not a crash in the analysis pipeline."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8") as f:
                obj = json.load(f)
        else:
            with open(path, "r", encoding="utf-8") as f:
                obj = json.load(f)
    except FileNotFoundError:
        raise
    except (OSError, EOFError, ValueError, UnicodeDecodeError) as e:
        # gzip truncation raises EOFError/BadGzipFile(OSError); json
        # garbage raises JSONDecodeError(ValueError) — one named error
        raise TraceParseError(
            f"cannot parse profiler trace {path!r}: {e}") from e
    if not isinstance(obj, dict):
        raise TraceParseError(
            f"profiler trace {path!r} is not a trace-event object "
            f"(got {type(obj).__name__})")
    return obj


def parse_trace(obj: dict, path: Optional[str] = None) -> TraceEvents:
    """Parse a Chrome trace-event JSON object (the `load_trace` result,
    or a hand-authored fixture dict) into typed events."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise TraceParseError(
            "trace object has no 'traceEvents' list — not a Chrome "
            "trace-event dump")
    raw = obj["traceEvents"]
    if not isinstance(raw, list):
        raise TraceParseError(
            f"'traceEvents' is {type(raw).__name__}, not a list")
    events: List[TraceEvent] = []
    process_names: Dict[int, str] = {}
    thread_names: Dict[Tuple[int, int], str] = {}
    for e in raw:
        if not isinstance(e, dict):
            continue
        ph = e.get("ph")
        args = e.get("args") or {}
        if ph == "M":
            # same contract as the X branch: a malformed metadata row
            # (non-numeric pid from a foreign converter) costs the
            # ROW, never the trace
            try:
                if e.get("name") == "process_name":
                    process_names[int(e.get("pid", 0))] = str(
                        args.get("name", ""))
                elif e.get("name") == "thread_name":
                    thread_names[(int(e.get("pid", 0)),
                                  int(e.get("tid", 0)))] = str(
                        args.get("name", ""))
            except (TypeError, ValueError):
                pass
            continue
        if ph != "X":
            continue
        step_num = args.get("step_num")
        if step_num is not None:
            try:
                step_num = int(step_num)  # serialized as a string
            except (TypeError, ValueError):
                step_num = None
        try:
            events.append(TraceEvent(
                name=str(e.get("name", "")),
                pid=int(e.get("pid", 0)),
                tid=int(e.get("tid", 0)),
                ts=float(e.get("ts", 0.0)),
                dur=float(e.get("dur", 0.0)),
                hlo_op=str(args.get("hlo_op", "")),
                step_num=step_num))
        except (TypeError, ValueError):
            continue  # a malformed row costs the EVENT, never the trace
    return TraceEvents(events=events, process_names=process_names,
                       thread_names=thread_names, path=path)


def read_trace(path: str) -> TraceEvents:
    """load_trace + parse_trace in one call."""
    return parse_trace(load_trace(path), path=path)


def newest_trace(logdir: str) -> Optional[str]:
    """The newest `*.trace.json.gz` under `logdir` (jax writes it to
    `plugins/profile/<timestamp>/<host>.trace.json.gz`), or None when
    no trace exists — what `ProfileCapture.trace_path()` resolves."""
    newest, newest_m = None, -1.0
    for root, _, files in os.walk(logdir):
        for f in files:
            if f.endswith(".trace.json.gz") or f.endswith(".trace.json"):
                p = os.path.join(root, f)
                try:
                    m = os.path.getmtime(p)
                except OSError:
                    continue
                if m > newest_m:
                    newest, newest_m = p, m
    return newest


def merged_length(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length of a list of (start, end) intervals with
    overlaps merged — the device-busy union."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    total += cur_e - cur_s
    return total


def clipped(intervals: List[Tuple[float, float]], lo: float,
            hi: float) -> List[Tuple[float, float]]:
    """Intervals clipped to the [lo, hi] window (empties dropped)."""
    out = []
    for s, e in intervals:
        s2, e2 = max(s, lo), min(e, hi)
        if e2 > s2:
            out.append((s2, e2))
    return out
