"""apex_tpu.monitor.timeline — the runtime timeline observatory
(ISSUE 15).

Where `monitor.comms` predicts overlap from HLO structure before
anything runs, this package MEASURES what the scheduler did, from the
Chrome trace-event JSON (`trace.json.gz`) that `jax.profiler` /
`monitor.ProfileCapture` writes:

  * events  — backend-free trace parser (`read_trace`, named
              `TraceParseError` on truncated/corrupt files)
  * report  — `analyze_trace(path) -> TimelineReport`: per-step device
              busy fraction + host gap, wall-time category attribution
              (gemm / collective / infeed / other, the comms HLO
              heuristics), MEASURED per-collective overlap fraction,
              `crosscheck_comms` against a `CommsReport`, the v1
              schema + validator + renderer

CI-gated by `scripts/timeline_probe.py` (flagship capture + parse
asserts + committed-fixture drift gate + seeded idle-heavy negative
control).  See docs/observability.md "Reading the timeline".
"""

from apex_tpu.monitor.timeline.events import (  # noqa: F401
    TraceEvent,
    TraceEvents,
    TraceParseError,
    newest_trace,
    parse_trace,
    read_trace,
)
from apex_tpu.monitor.timeline.report import (  # noqa: F401
    CATEGORIES,
    IDLE_BUSY_FLOOR,
    TIMELINE_SCHEMA_VERSION,
    CollectiveSpan,
    StepAnatomy,
    TimelineReport,
    analyze_events,
    analyze_trace,
    classify_op,
    crosscheck_comms,
    render_crosscheck,
    render_timeline_table,
    validate_timeline_report,
)
