"""`analyze_trace(path) -> TimelineReport` — the measured step anatomy
of a real profiler trace.

The runtime half of the overlap story (ISSUE 15): the comms
observatory (PR 7) predicts per-collective `overlap_fraction` from HLO
structure before anything runs; this module measures what the schedule
actually did, from the `trace.json.gz` that `ProfileCapture` already
writes.  Per captured step it derives

  * wall time, device-busy time (the union of device-event intervals —
    concurrent streams never double-count) and the HOST GAP (wall
    minus busy: the time the device sat idle waiting on the host —
    input pipeline, dispatch, python),
  * wall-time attribution per op category — {gemm, collective,
    infeed_outfeed, other} by the op-NAME heuristics the comms
    observatory's HLO parser established (`COLLECTIVE_KINDS`), so a
    "collective" means the same thing in both planes,
  * and per collective the MEASURED overlap fraction: the device-
    compute wall time concurrent with the collective's span, over the
    span — the number `comms_report`'s predicted fraction can be
    cross-checked against (`crosscheck_comms`, mirroring
    `crosscheck_rank_timing`).

Backend honesty, the PR 7 rule: a CPU trace carries real host + "CPU
device" events (XLA's thunk executor, `args.hlo_op`-tagged), so the
parser, step anatomy, and category attribution are fully exercised
from tier-1 — but CPU emits SYNC collectives and the thunk pool
interleaves emulated devices, so concurrency there says nothing about
an async schedule: overlap is reported UNMEASURABLE (`overlap_
measurable=False`, per-collective fraction None), never faked.  Only
a trace whose events live on `/device:TPU*` processes measures the
overlap plane.

Surfaces follow the house pattern: `TIMELINE_SCHEMA_VERSION` +
`validate_timeline_report` (the `timeline_probe.py --selftest` drift
gate), `render_timeline_table` (the operator view), and
`TimelineReport.timeline_record()` (the SCHEMA v11 `timeline_*`
stamps `MetricsLogger(timeline=...)` writes).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

from apex_tpu.monitor.comms import hlo as hlo_lib
from apex_tpu.monitor.timeline import events as events_lib
from apex_tpu.monitor.timeline.events import (
    TraceEvents,
    TraceParseError,
    clipped,
    merged_length,
)

# Bump on any StepAnatomy/CollectiveSpan/TimelineReport field
# add/rename/re-semantics — scripts/timeline_probe.py --selftest
# renders the committed fixture (scripts/timeline_fixture.json) and
# exits nonzero on drift, the lint/comms/slo probe contract.
TIMELINE_SCHEMA_VERSION = 1

# a step whose device-busy fraction is below this is host-bound — the
# renderer flags it DEVICE IDLE (the probe's seeded idle-heavy trace
# is the named negative control for this verdict)
IDLE_BUSY_FLOOR = 0.5

# a collective span shorter than this (total across the capture) is
# latency noise, not a hiding opportunity — never judged serialized
# (the wall-time analogue of the comms OVERLAP_BYTES_FLOOR)
SERIALIZED_FLOOR_MS = 0.1

# the device-event categories the anatomy attributes wall time to;
# host events are counted separately (they are the gap, not the work)
CATEGORIES = ("gemm", "collective", "infeed_outfeed", "other")

_GEMM_PREFIXES = ("dot", "convolution", "conv", "gemm", "matmul",
                  "cublas", "loop_convolution")
_INFEED_PREFIXES = ("infeed", "outfeed", "host-transfer", "send",
                    "send-done", "recv", "recv-done", "copy-start",
                    "copy-done")


def classify_op(name: str, hlo_op: str = "") -> str:
    """Category of one device op by NAME — the heuristics shared with
    the comms observatory's HLO parser (`hlo.COLLECTIVE_KINDS` is the
    single spelling of what counts as a collective).  `hlo_op` (the
    trace's `args.hlo_op`, the optimized-module instruction name) wins
    over the display name when present — TPU traces sometimes shorten
    display names while the arg keeps the real instruction."""
    n = (hlo_op or name).lower()
    for kind in hlo_lib.COLLECTIVE_KINDS:
        if n.startswith(kind):
            return "collective"
    if n.startswith(_INFEED_PREFIXES):
        return "infeed_outfeed"
    if n.startswith("convert"):
        return "other"  # dtype cast — the "conv" prefix below is for
        # convolutions and must not swallow it into gemm
    if n.startswith(_GEMM_PREFIXES):
        return "gemm"
    if n.startswith("fusion") and any(
            k in n for k in ("gemm", "matmul", "dot", "conv")):
        return "gemm"
    return "other"


@dataclasses.dataclass
class StepAnatomy:
    """One captured step's measured anatomy (times in ms)."""

    step: int
    t_start_us: float
    wall_ms: float
    device_busy_ms: float
    device_busy_fraction: float
    host_gap_ms: float
    category_ms: Dict[str, float]
    n_device_events: int

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["category_ms"] = {k: float(v)
                            for k, v in self.category_ms.items()}
        return d


@dataclasses.dataclass
class CollectiveSpan:
    """One collective (aggregated over its occurrences in the capture
    window — the same instruction runs once per step) with its
    MEASURED overlap.  `overlap_fraction` is None when the backend's
    concurrency is not schedule truth (CPU)."""

    name: str
    kind: str
    n_events: int
    total_ms: float
    concurrent_compute_ms: float
    overlap_fraction: Optional[float]
    serialized: bool

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TimelineReport:
    """The measured timeline anatomy (JSON-able via to_dict)."""

    device_type: str                 # "tpu" | "gpu" | "cpu" | "unknown"
    trace_path: Optional[str]
    annotation: str
    n_events: int
    n_device_events: int
    n_host_events: int
    steps: List[StepAnatomy]
    collectives: List[CollectiveSpan]
    # aggregates over the captured steps (whole trace if unannotated)
    device_busy_fraction: float
    host_gap_ms: float               # mean per step
    category_fractions: Dict[str, float]   # of device time; sum ~1
    collective_fraction: float
    overlap_measurable: bool
    measured_overlap_ok: Optional[bool]    # None when unmeasurable

    def to_dict(self) -> dict:
        return {
            "timeline_schema_version": TIMELINE_SCHEMA_VERSION,
            "device_type": self.device_type,
            "trace_path": self.trace_path,
            "annotation": self.annotation,
            "n_events": int(self.n_events),
            "n_device_events": int(self.n_device_events),
            "n_host_events": int(self.n_host_events),
            "steps": [s.to_dict() for s in self.steps],
            "collectives": [c.to_dict() for c in self.collectives],
            "device_busy_fraction": float(self.device_busy_fraction),
            "host_gap_ms": float(self.host_gap_ms),
            "category_fractions": {k: float(v) for k, v in
                                   self.category_fractions.items()},
            "collective_fraction": float(self.collective_fraction),
            "overlap_measurable": bool(self.overlap_measurable),
            "measured_overlap_ok": self.measured_overlap_ok,
        }

    def timeline_record(self) -> dict:
        """The SCHEMA v11 `timeline_*` stamps for
        `MetricsLogger(timeline=report)` — optional-never-null, so the
        overlap verdict is simply absent where unmeasurable (CPU), the
        v4 rule."""
        rec = {
            "timeline_device_busy_fraction":
                float(self.device_busy_fraction),
            "timeline_host_gap_ms": float(self.host_gap_ms),
            "timeline_collective_fraction":
                float(self.collective_fraction),
        }
        if self.measured_overlap_ok is not None:
            rec["timeline_measured_overlap_ok"] = bool(
                self.measured_overlap_ok)
        return rec


# ------------------------------ analysis ------------------------------

def _device_type(process_names: Dict[int, str]) -> str:
    names = " ".join(process_names.values()).lower()
    if "/device:tpu" in names or " tpu" in names:
        return "tpu"
    if "/device:gpu" in names or "gpu" in names:
        return "gpu"
    if names:
        return "cpu"
    return "unknown"


def _device_op_tids(trace: TraceEvents, device_pids) -> Dict[int, set]:
    """Per device pid, the tids of its OP lanes.  TPU trace converters
    mirror the same wall time onto several lanes ("XLA Ops" per-op,
    "XLA Modules" whole-module spans, "Steps", TF name-scope
    hierarchies) — counting more than one lane would double-count the
    busy union and inflate every category.  Prefer threads named
    "XLA Ops*"; a pid with no such thread (or no thread metadata at
    all — hand-authored fixtures) maps to None = every lane counts."""
    by_pid: Dict[int, list] = {}
    for (pid, tid), name in trace.thread_names.items():
        if pid in device_pids and "XLA Ops" in name:
            by_pid.setdefault(pid, []).append(tid)
    return {pid: set(tids) for pid, tids in by_pid.items()}


def _is_device_event(ev, device_pids, op_tids, annotation) -> bool:
    if ev.pid in device_pids:
        # step markers are duplicated onto device pids by the
        # converter (exclude by name); non-op lanes mirror wall time
        lanes = op_tids.get(ev.pid)
        if lanes is not None and ev.tid not in lanes:
            return False
        return ev.name != annotation and ev.step_num is None
    # an hlo_op-tagged event executed program code wherever it ran —
    # on CPU the "device" is XLA's thunk executor thread
    return bool(ev.hlo_op)


def analyze_events(trace: TraceEvents, *,
                   annotation: str = "train-step",
                   trace_path: Optional[str] = None) -> TimelineReport:
    """The analysis proper, over parsed events (hand-authored fixture
    dicts enter through `events.parse_trace` + this)."""
    device_pids = {pid for pid, name in trace.process_names.items()
                   if name.startswith("/device:")}
    device_type = _device_type(trace.process_names)
    # schedule concurrency is only truth where each op lane IS a real
    # device stream; CPU's thunk pool interleaves emulated devices and
    # emits sync collectives — honest answer: unmeasurable
    overlap_measurable = device_type == "tpu"

    op_tids = _device_op_tids(trace, device_pids)
    dev_events, host_events, step_marks = [], [], []
    for ev in trace.events:
        if ev.name == annotation and ev.step_num is not None:
            step_marks.append(ev)
        elif _is_device_event(ev, device_pids, op_tids, annotation):
            dev_events.append(ev)
        else:
            host_events.append(ev)

    # step windows: one per step_num, spanning every mark that carries
    # it (TPU converters duplicate the annotation per device pid)
    windows: Dict[int, Tuple[float, float]] = {}
    for m in step_marks:
        lo, hi = windows.get(m.step_num, (m.ts, m.end))
        windows[m.step_num] = (min(lo, m.ts), max(hi, m.end))
    if not windows and trace.events:
        # unannotated trace: the whole span is one pseudo-step so the
        # aggregates still mean something (the probe REQUIRES real
        # step marks and asserts the count separately)
        lo = min(ev.ts for ev in trace.events)
        hi = max(ev.end for ev in trace.events)
        windows = {-1: (lo, hi)}

    cat_of = {id(ev): classify_op(ev.name, ev.hlo_op)
              for ev in dev_events}
    # multi-chip traces carry one /device: pid PER CHIP whose lanes
    # all advance in the same wall time: pooling them would let one
    # busy device mask another's idle, and (worse) let device A's
    # compute count as "concurrent" with device B's collective.  All
    # per-step busy/category numbers are therefore PER-DEVICE MEANS
    # (n_lanes = number of pids owning device events; 1 on CPU and
    # single-chip, so those numbers are unchanged), and the overlap
    # window only sees compute from the collective's OWN pid.
    dev_lane_pids = sorted({ev.pid for ev in dev_events})
    n_lanes = max(1, len(dev_lane_pids))
    by_pid: Dict[int, list] = {}
    for ev in dev_events:
        by_pid.setdefault(ev.pid, []).append(ev)

    steps: List[StepAnatomy] = []
    for step_num in sorted(windows):
        lo, hi = windows[step_num]
        wall_us = max(hi - lo, 1e-9)
        busy_us = sum(
            merged_length(clipped([(ev.ts, ev.end) for ev in evs],
                                  lo, hi))
            for evs in by_pid.values()) / n_lanes
        cat_ms = {c: 0.0 for c in CATEGORIES}
        n_dev = 0
        for ev in dev_events:
            s, e = max(ev.ts, lo), min(ev.end, hi)
            if e > s:
                cat_ms[cat_of[id(ev)]] += (e - s) / 1e3 / n_lanes
                n_dev += 1
        steps.append(StepAnatomy(
            step=int(step_num), t_start_us=float(lo),
            wall_ms=wall_us / 1e3,
            device_busy_ms=busy_us / 1e3,
            device_busy_fraction=min(1.0, busy_us / wall_us),
            host_gap_ms=max(0.0, wall_us - busy_us) / 1e3,
            category_ms=cat_ms, n_device_events=n_dev))

    # per-collective measured overlap: the SAME device's compute wall
    # time concurrent with each collective occurrence, aggregated by
    # instruction name (total_ms sums across devices AND steps)
    compute_by_pid = {
        pid: [(ev.ts, ev.end) for ev in evs
              if cat_of[id(ev)] != "collective"]
        for pid, evs in by_pid.items()}
    spans: Dict[str, dict] = {}
    for ev in dev_events:
        if cat_of[id(ev)] != "collective":
            continue
        key = ev.hlo_op or ev.name
        d = spans.setdefault(key, {"n": 0, "total": 0.0, "conc": 0.0})
        d["n"] += 1
        d["total"] += ev.dur
        d["conc"] += merged_length(
            clipped(compute_by_pid.get(ev.pid, []), ev.ts, ev.end))
    collectives: List[CollectiveSpan] = []
    for key in sorted(spans):
        d = spans[key]
        # spans only exist for events classify_op labelled collective,
        # i.e. the name starts with a COLLECTIVE_KINDS entry — no
        # default: if the classifier rule ever widens, fail LOUDLY
        # here rather than silently mislabel a kind
        kind = next(k for k in hlo_lib.COLLECTIVE_KINDS
                    if key.lower().startswith(k))
        frac = (min(1.0, d["conc"] / d["total"])
                if overlap_measurable and d["total"] > 0 else None)
        collectives.append(CollectiveSpan(
            name=key, kind=kind, n_events=int(d["n"]),
            total_ms=d["total"] / 1e3,
            concurrent_compute_ms=d["conc"] / 1e3,
            overlap_fraction=frac,
            serialized=bool(frac == 0.0
                            and d["total"] / 1e3 >= SERIALIZED_FLOOR_MS)))

    total_wall = sum(s.wall_ms for s in steps)
    total_busy = sum(s.device_busy_ms for s in steps)
    total_cat = {c: sum(s.category_ms[c] for s in steps)
                 for c in CATEGORIES}
    cat_sum = sum(total_cat.values())
    cat_fracs = {c: (total_cat[c] / cat_sum if cat_sum > 0 else 0.0)
                 for c in CATEGORIES}
    measured_ok = None
    if overlap_measurable:
        measured_ok = not any(c.serialized for c in collectives)

    return TimelineReport(
        device_type=device_type,
        trace_path=trace_path if trace_path is not None else trace.path,
        annotation=annotation,
        n_events=len(trace.events),
        n_device_events=len(dev_events),
        n_host_events=len(host_events),
        steps=steps, collectives=collectives,
        device_busy_fraction=(total_busy / total_wall
                              if total_wall > 0 else 0.0),
        host_gap_ms=(sum(s.host_gap_ms for s in steps) / len(steps)
                     if steps else 0.0),
        category_fractions=cat_fracs,
        collective_fraction=cat_fracs["collective"],
        overlap_measurable=overlap_measurable,
        measured_overlap_ok=measured_ok)


def analyze_trace(path_or_obj, *,
                  annotation: str = "train-step") -> TimelineReport:
    """Parse + analyze one profiler trace.  Accepts a path to a
    `trace.json[.gz]` file (what `ProfileCapture.trace_path()`
    returns), a raw trace-event dict, or a parsed `TraceEvents`.
    Raises `TraceParseError` on a malformed/truncated file — named,
    never a bare gzip/json crash."""
    if isinstance(path_or_obj, TraceEvents):
        return analyze_events(path_or_obj, annotation=annotation)
    if isinstance(path_or_obj, dict):
        return analyze_events(events_lib.parse_trace(path_or_obj),
                              annotation=annotation)
    if path_or_obj is None:
        raise TraceParseError(
            "analyze_trace(None): no trace was captured — did the "
            "ProfileCapture window ever fire? (trace_path() is None "
            "until a window opened and closed)")
    return analyze_events(events_lib.read_trace(path_or_obj),
                          annotation=annotation)


# ---------------------------- schema + gate ----------------------------

_REPORT_FIELDS = {
    "timeline_schema_version": int,
    "device_type": str,
    "trace_path": (str, type(None)),
    "annotation": str,
    "n_events": int,
    "n_device_events": int,
    "n_host_events": int,
    "steps": list,
    "collectives": list,
    "device_busy_fraction": (int, float),
    "host_gap_ms": (int, float),
    "category_fractions": dict,
    "collective_fraction": (int, float),
    "overlap_measurable": bool,
    "measured_overlap_ok": (bool, type(None)),
}

_STEP_FIELDS = {
    "step": int, "t_start_us": (int, float), "wall_ms": (int, float),
    "device_busy_ms": (int, float),
    "device_busy_fraction": (int, float),
    "host_gap_ms": (int, float), "category_ms": dict,
    "n_device_events": int,
}

_COLLECTIVE_FIELDS = {
    "name": str, "kind": str, "n_events": int,
    "total_ms": (int, float), "concurrent_compute_ms": (int, float),
    "overlap_fraction": (int, float, type(None)), "serialized": bool,
}


def validate_timeline_report(report: dict) -> None:
    """Raise ValueError unless `report` (the to_dict form) matches the
    current schema — the drift gate `timeline_probe.py --selftest`
    runs over the committed fixture."""
    if not isinstance(report, dict):
        raise ValueError(f"timeline report must be a dict, got "
                         f"{type(report).__name__}")
    if report.get("timeline_schema_version") != TIMELINE_SCHEMA_VERSION:
        raise ValueError(
            f"timeline_schema_version "
            f"{report.get('timeline_schema_version')!r} != "
            f"{TIMELINE_SCHEMA_VERSION}")
    for name, typ in _REPORT_FIELDS.items():
        if name not in report:
            raise ValueError(f"missing timeline report field {name!r}")
        v = report[name]
        if not isinstance(v, typ):
            raise ValueError(f"timeline report field {name!r} is "
                             f"{type(v).__name__}")
        if typ is int and isinstance(v, bool):
            raise ValueError(f"timeline report field {name!r} is bool")
    for i, s in enumerate(report["steps"]):
        for name, typ in _STEP_FIELDS.items():
            if name not in s:
                raise ValueError(f"steps[{i}] missing field {name!r}")
            if not isinstance(s[name], typ) or (
                    typ is int and isinstance(s[name], bool)):
                raise ValueError(f"steps[{i}].{name} is "
                                 f"{type(s[name]).__name__}")
        for c in CATEGORIES:
            if c not in s["category_ms"]:
                raise ValueError(f"steps[{i}].category_ms missing "
                                 f"category {c!r}")
    for i, c in enumerate(report["collectives"]):
        for name, typ in _COLLECTIVE_FIELDS.items():
            if name not in c:
                raise ValueError(
                    f"collectives[{i}] missing field {name!r}")
            if not isinstance(c[name], typ):
                raise ValueError(f"collectives[{i}].{name} is "
                                 f"{type(c[name]).__name__}")
        if c["kind"] not in hlo_lib.COLLECTIVE_KINDS:
            raise ValueError(f"collectives[{i}] unknown kind "
                             f"{c['kind']!r}")
    for c, v in report["category_fractions"].items():
        if c not in CATEGORIES:
            raise ValueError(f"unknown category {c!r}")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"category_fractions[{c!r}] is "
                             f"{type(v).__name__}")
    frac_sum = sum(report["category_fractions"].values())
    if report["n_device_events"] > 0 and not math.isclose(
            frac_sum, 1.0, abs_tol=1e-6):
        raise ValueError(
            f"category fractions sum to {frac_sum}, not ~1 — the "
            "attribution dropped or double-counted device time")


# ---------------------------- rendering ----------------------------

def render_timeline_table(report, label: str = "trace") -> str:
    """The per-step anatomy table an operator reads next to the comms
    table.  Accepts a TimelineReport or its to_dict()."""
    r = report.to_dict() if hasattr(report, "to_dict") else dict(report)
    lines = [
        f"=== timeline: {label} ===",
        f"device: {r.get('device_type')} | events: "
        f"{r.get('n_device_events')} device / {r.get('n_host_events')} "
        f"host | steps: {len(r.get('steps') or [])}"
        + (f" | {r['trace_path']}" if r.get("trace_path") else ""),
        "| step | wall ms | busy % | host gap ms | gemm % | coll % | "
        "in/out % | other % |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for s in r.get("steps", []):
        cat = s.get("category_ms") or {}
        dev = sum(cat.values()) or 1.0

        def pct(c):
            return f"{100 * cat.get(c, 0.0) / dev:5.1f}"

        lines.append(
            f"| {s['step']:>4} | {s['wall_ms']:7.2f} | "
            f"{100 * s['device_busy_fraction']:5.1f} | "
            f"{s['host_gap_ms']:11.2f} | {pct('gemm')} | "
            f"{pct('collective')} | {pct('infeed_outfeed')} | "
            f"{pct('other')} |")
    lines.append(
        f"aggregate: device busy "
        f"{100 * r.get('device_busy_fraction', 0.0):.1f}% | host gap "
        f"{r.get('host_gap_ms', 0.0):.2f} ms/step | collectives "
        f"{100 * r.get('collective_fraction', 0.0):.1f}% of device "
        "time")
    # heaviest collectives first, capped — a dp=1 CPU smoke trace
    # carries dozens of sub-microsecond degenerate all-reduces that
    # would drown the table (serialized ones always shown)
    colls = sorted(r.get("collectives", []),
                   key=lambda c: (-bool(c.get("serialized")),
                                  -c.get("total_ms", 0.0)))
    shown = [c for i, c in enumerate(colls)
             if i < 8 or c.get("serialized")]
    for c in shown:
        frac = c.get("overlap_fraction")
        overlap = (f"{100 * frac:.0f}% overlapped" if frac is not None
                   else "overlap n/a")
        mark = " **SER**" if c.get("serialized") else ""
        lines.append(
            f"  collective {c['name']} ({c['kind']}): x{c['n_events']}, "
            f"{c['total_ms']:.2f} ms, {overlap}{mark}")
    if len(colls) > len(shown):
        lines.append(f"  … and {len(colls) - len(shown)} more "
                     "collective(s) (by total ms)")
    if not r.get("overlap_measurable"):
        lines.append(
            "overlap: UNMEASURABLE (sync collectives / emulated device "
            "lanes on this backend — run the capture on TPU for the "
            "schedule truth)")
    elif r.get("measured_overlap_ok"):
        lines.append("overlap: measured ok (every collective's span "
                     "held concurrent compute)")
    else:
        ser = [c for c in r.get("collectives", [])
               if c.get("serialized")]
        lines.append(
            f"** {len(ser)} MEASURED-SERIALIZED collective(s): "
            + "; ".join(f"{c['name']} {c['total_ms']:.2f} ms"
                        for c in ser[:4]))
    if (r.get("steps") and r.get("n_device_events", 0) > 0
            and r.get("device_busy_fraction", 1.0) < IDLE_BUSY_FLOOR):
        lines.append(
            f"** DEVICE IDLE: busy fraction "
            f"{r['device_busy_fraction']:.2f} < {IDLE_BUSY_FLOOR} — "
            "the device waited on the host for most of each step "
            "(input pipeline / dispatch bound)")
    return "\n".join(lines)


# ------------------------- comms cross-check -------------------------

def _coll_name_prefix(name: str, strip_start: bool = False) -> str:
    """The HLO instruction name with its uniquifying ".N" suffix
    stripped — the pool key the chunked same-kind instructions of one
    logical collective share.  The async "-start" spelling is KEPT in
    the prefix (it separates the overlapped chunked instances from a
    sync same-kind collective elsewhere in the module — the exact
    distinction kind-ordinal pairing loses); `strip_start=True` gives
    the fallback spelling for a trace that records the op under its
    base name."""
    head, dot, tail = name.rpartition(".")
    base = head if (dot and tail.isdigit()) else name
    if strip_start and base.endswith("-start"):
        base = base[:-len("-start")]
    return base


def crosscheck_comms(timeline, comms_report, *,
                     tolerance: float = 0.25) -> dict:
    """Close the loop between the comms observatory's PREDICTED
    overlap and the timeline's MEASURED one (the `crosscheck_rank_
    timing` pattern): one row per counted collective of the comms
    report (group_size > 1), matched to the trace's collective spans
    by optimized-module instruction name — the trace's `args.hlo_op`
    and the comms inventory parse the SAME module, so exact-name match
    is the common case; unmatched collectives then pair within their
    NAME-PREFIX group (the uniquifying ".N" suffix stripped, async
    "-start" kept: the chunked-overlap pipelines of ISSUE 18 spell
    one logical collective as chunk-count-many same-kind instructions,
    where raw kind-ordinal pairing would judge an overlapped chunk
    against the span of an unrelated sync same-kind collective); only
    leftovers fall back to kind-ordinal pairing (k-th all-reduce ↔
    k-th all-reduce span).

    Row verdicts: AGREE (|predicted − measured| ≤ tolerance),
    DIVERGES (the AOT model and the schedule disagree — the thing this
    function exists to surface), UNMEASURED (no measured fraction:
    CPU backend or span not found in the trace), MEASURED-ONLY (the
    trace measured a fraction the AOT side called sync).  `ok` is
    False only on DIVERGES — an unmeasured plane is honest, not
    green-washed."""
    t = timeline.to_dict() if hasattr(timeline, "to_dict") \
        else dict(timeline)
    c = comms_report.to_dict() if hasattr(comms_report, "to_dict") \
        else dict(comms_report)
    spans_by_name = {s["name"]: s for s in t.get("collectives", [])}
    spans_by_kind: Dict[str, list] = {}
    for s in t.get("collectives", []):
        spans_by_kind.setdefault(s["kind"], []).append(s)
    counted = [coll for coll in c.get("collectives", [])
               if coll.get("group_size", 1) > 1]
    # pass 1 — EXACT name matches claim their spans first (async HLO
    # spells the op "<kind>-start.N"; the trace event is the op
    # itself, so the stripped spelling also counts as exact).  Only
    # then does pass 2 hand out the leftovers by kind-ordinal:
    # fallback running first would let an unmatched collective steal
    # the very span a later collective matches BY NAME, judging two
    # rows against one measurement on the table PERF.md commits.
    claimed = set()
    span_for: Dict[int, Optional[dict]] = {}
    for i, coll in enumerate(counted):
        name = coll.get("name", "")
        span = spans_by_name.get(name)
        if span is None and "-start" in name:
            span = spans_by_name.get(name.replace("-start", "", 1))
        if span is not None and id(span) not in claimed:
            claimed.add(id(span))
            span_for[i] = span
    # pass 1.5 — NAME-PREFIX groups: a chunked program (ISSUE 18)
    # spells one logical collective as N same-kind instructions
    # ("collective-permute.{7..12}"); if the trace renumbered them,
    # raw kind-ordinal pairing could hand a chunk's span to an
    # UNRELATED same-kind collective (the dp grad all-reduce vs the
    # tp ring hop).  Pairing inside the ".N"-stripped prefix pool
    # first keeps chunk spans with their own logical collective.
    spans_by_prefix: Dict[str, list] = {}
    for s in t.get("collectives", []):
        spans_by_prefix.setdefault(
            _coll_name_prefix(s["name"]), []).append(s)
    prefix_cursor: Dict[str, int] = {}
    for i, coll in enumerate(counted):
        if i in span_for:
            continue
        name = coll.get("name", "")
        pref = _coll_name_prefix(name)
        if pref not in spans_by_prefix:
            # trace recorded the base-name spelling of an async op
            pref = _coll_name_prefix(name, strip_start=True)
        pool = spans_by_prefix.get(pref, [])
        j = prefix_cursor.get(pref, 0)
        while j < len(pool) and id(pool[j]) in claimed:
            j += 1
        if j < len(pool):
            claimed.add(id(pool[j]))
            span_for[i] = pool[j]
            prefix_cursor[pref] = j + 1
    kind_cursor: Dict[str, int] = {}
    for i, coll in enumerate(counted):
        if i in span_for:
            continue
        pool = spans_by_kind.get(coll.get("kind", ""), [])
        j = kind_cursor.get(coll.get("kind", ""), 0)
        while j < len(pool) and id(pool[j]) in claimed:
            j += 1
        if j < len(pool):
            claimed.add(id(pool[j]))
            span_for[i] = pool[j]
            kind_cursor[coll.get("kind", "")] = j + 1

    rows = []
    for i, coll in enumerate(counted):
        name, kind = coll.get("name", ""), coll.get("kind", "")
        span = span_for.get(i)
        predicted = coll.get("overlap_fraction")
        measured = span.get("overlap_fraction") if span else None
        if measured is None:
            verdict = "UNMEASURED"
        elif predicted is None:
            verdict = "MEASURED-ONLY"
        elif abs(predicted - measured) <= tolerance:
            verdict = "AGREE"
        else:
            verdict = "DIVERGES"
        rows.append({
            "name": name,
            "kind": kind,
            "expected_overlap": bool(coll.get("expected_overlap")),
            "predicted_overlap_fraction": predicted,
            "measured_overlap_fraction": measured,
            "measured_ms": span.get("total_ms") if span else None,
            "verdict": verdict,
        })
    n = {v: sum(1 for r in rows if r["verdict"] == v)
         for v in ("AGREE", "DIVERGES", "UNMEASURED", "MEASURED-ONLY")}
    return {
        "rows": rows,
        "n_expected_overlap": sum(1 for r in rows
                                  if r["expected_overlap"]),
        "n_agree": n["AGREE"],
        "n_diverge": n["DIVERGES"],
        "n_unmeasured": n["UNMEASURED"],
        "ok": n["DIVERGES"] == 0,
    }


def render_crosscheck(result: dict, label: str = "step") -> str:
    """The predicted-vs-measured table for one crosscheck_comms
    result."""
    lines = [
        f"=== overlap crosscheck: {label} ===",
        "| collective         | kind               | predicted | "
        "measured | verdict |",
        "|---|---|---|---|---|",
    ]

    def fm(v):
        return "n/a" if v is None else f"{100 * v:.0f}%"

    for r in result.get("rows", []):
        exp = "*" if r.get("expected_overlap") else " "
        lines.append(
            f"| {r['name'][:18]:<18} | {r['kind']:<18} | "
            f"{fm(r['predicted_overlap_fraction']):>9} | "
            f"{fm(r['measured_overlap_fraction']):>8} | "
            f"{r['verdict']}{exp} |")
    lines.append(
        f"verdict: {result.get('n_agree', 0)} agree, "
        f"{result.get('n_diverge', 0)} diverge, "
        f"{result.get('n_unmeasured', 0)} unmeasured "
        f"({result.get('n_expected_overlap', 0)} expected-overlap "
        "collective(s); * marks them)")
    return "\n".join(lines)
