"""Device side of the numerics flight recorder: the `TapState` pytree.

Same design discipline as `MetricsState` (metrics.py module docstring):
everything is computed INSIDE the jitted step from values it already
holds, with zero host syncs and zero collectives per tap.  The tap op
itself lives in `ops._common` (the models call it on their hot path and
must not import the monitor package); this module owns the pytree the
hot paths return and the host-side interpretation helpers.

How stats get out of AD: each `tap(x, name)` draws a zeros (2, 4) row
from a `probes` array that is an *argument* of the step's `jax.grad`;
the tap op's custom_vjp returns `[tap_stats(x), tap_stats(cotangent)]`
as that row's gradient.  `finalize()` slices the used rows, unscales
the gradient plane by the loss scale, and computes the first-nonfinite
provenance indices — all still on device.

Provenance convention: the FORWARD plane reads in forward order, so
`first_bad_fwd` is the MINIMUM tapped index with a non-finite value —
the earliest layer where the forward went bad.  The GRADIENT plane
flows loss→embedding, so the first tap the backward corrupted is the
MAXIMUM index (`first_bad_grad`).  -1 = plane clean.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.ops._common import (  # noqa: F401 — re-exported
    TAP_PLANES,
    TAP_STAT_DIM,
    TAP_STAT_FIELDS,
    TapContext,
    active_tap_context,
    grad_tap,
    tap,
    tap_context,
    tap_stats,
)

# Columns of the cross-rank timing vector (see `gather_rank_timings`):
# the host measures these per rank per step and the jitted step
# all_gathers them so every rank's flight recorder sees every rank.
TIMING_FIELDS = ("step_duration_s", "allreduce_duration_s")


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Static knobs for the flight-recorder planes a hot path collects.

    taps: per-layer stat taps (TapState output).  max_taps bounds the
    probes array (rows are tiny — (2, 4) f32 each — so a generous
    default costs nothing; unused rows stay zero and are sliced off at
    trace time).  rank_timing: the cross-rank timing plane — the step
    takes a per-rank local timing vector and returns the all_gathered
    (n_ranks, k) matrix (ONE small collective per step, no per-tap
    collectives)."""

    taps: bool = True
    max_taps: int = 512
    rank_timing: bool = False
    timing_dim: int = len(TIMING_FIELDS)


class TapState(NamedTuple):
    """Per-step tap snapshot riding inside the jitted step.

    fwd/grad: (n_taps, 4) f32 — [absmax, mean, rms, nonfinite count]
    per tap point, forward plane and gradient plane (gradient stats are
    unscaled when the step runs under loss scaling; the nonfinite count
    is of the RAW scaled grads — the thing the overflow skip saw).
    first_bad_fwd / first_bad_grad: i32 provenance indices into the tap
    name list (-1 = clean); see module docstring for the ordering.
    """

    fwd: jnp.ndarray
    grad: jnp.ndarray
    first_bad_fwd: jnp.ndarray
    first_bad_grad: jnp.ndarray


def make_probes(max_taps: int) -> jnp.ndarray:
    """The zeros probes array a tapped trace draws rows from."""
    return jnp.zeros((max_taps, 2, TAP_STAT_DIM), jnp.float32)


def _first_nonfinite(plane: jnp.ndarray, reverse: bool) -> jnp.ndarray:
    n = plane.shape[0]
    if n == 0:
        return jnp.asarray(-1, jnp.int32)
    bad = plane[:, TAP_STAT_FIELDS.index("nonfinite")] > 0
    idx = jnp.arange(n, dtype=jnp.int32)
    if reverse:  # gradient plane: backward hits high indices first
        return jnp.max(jnp.where(bad, idx, -1))
    first = jnp.min(jnp.where(bad, idx, n))
    return jnp.where(first == n, -1, first).astype(jnp.int32)


def finalize(probe_grads: jnp.ndarray, n_taps: int,
             inv_scale=1.0) -> TapState:
    """Build the TapState from jax.grad's probes cotangent.

    probe_grads: (max_taps, 2, 4); n_taps: how many rows the trace used
    (host-side int — `len(ctx.names)` after jax.grad returns).
    inv_scale unscales the gradient plane's absmax/mean/rms so reported
    magnitudes are comparable across loss-scale changes; the nonfinite
    count is left as observed on the raw scaled grads."""
    used = probe_grads[:n_taps]
    fwd = used[:, 0]
    unscale = jnp.asarray(
        [inv_scale, inv_scale, inv_scale, 1.0], jnp.float32)
    grad = used[:, 1] * unscale
    return TapState(
        fwd=fwd, grad=grad,
        first_bad_fwd=_first_nonfinite(fwd, reverse=False),
        first_bad_grad=_first_nonfinite(used[:, 1], reverse=True))


def gather_rank_timings(local_timing, axis_name: str) -> jnp.ndarray:
    """The cross-rank timing plane: ONE all_gather of a tiny vector.

    local_timing: this rank's (k,) f32 host-measured durations (by
    convention `TIMING_FIELDS`).  Returns (n_ranks, k), replicated —
    every rank's recorder sees every rank, which is the whole point:
    on hardware reached only through committed telemetry, rank-skew
    must ride the step itself.  Call inside shard_map/pmap."""
    v = jnp.asarray(local_timing, jnp.float32).reshape(-1)
    return jax.lax.all_gather(v, axis_name)


# --------------------------- host-side helpers ---------------------------

def taps_to_dict(tap_state: TapState,
                 names: Sequence[str]) -> dict:
    """device_get a TapState into the flight-report JSON shape:
    {"fwd": {name: {absmax, mean, rms, nonfinite}}, "grad": {...},
    "first_bad_fwd": name|None, "first_bad_grad": name|None}."""
    st = jax.device_get(tap_state)
    names = list(names)

    def plane(mat):
        return {nm: {f: float(v) for f, v in zip(TAP_STAT_FIELDS, row)}
                for nm, row in zip(names, mat)}

    def badname(i):
        i = int(i)
        return names[i] if 0 <= i < len(names) else None

    return {
        "fwd": plane(st.fwd),
        "grad": plane(st.grad),
        "first_bad_fwd": badname(st.first_bad_fwd),
        "first_bad_grad": badname(st.first_bad_grad),
    }


def provenance(tap_state: TapState,
               names: Sequence[str]) -> Optional[dict]:
    """First-nonfinite attribution, host side (ONE device_get).

    Returns None when both planes are clean.  The FORWARD plane wins
    when it has a hit: a non-finite activation always precedes (and
    causes) the backward corruption downstream of it, so the earliest
    bad forward tap is the origin.  Only when the forward was clean —
    the classic loss-scaling overflow, where fp16/bf16 grads blow up
    in backward alone — does the gradient plane attribute: its first
    bad tap (closest to the loss) is where the overflow entered."""
    st = jax.device_get(tap_state)
    names = list(names)
    for plane_name, idx, mat in (
            ("fwd", int(st.first_bad_fwd), st.fwd),
            ("grad", int(st.first_bad_grad), st.grad)):
        if 0 <= idx < len(names):
            return {
                "plane": plane_name,
                "tap": names[idx],
                "index": idx,
                "stats": {f: float(v) for f, v in
                          zip(TAP_STAT_FIELDS, mat[idx])},
            }
    return None
