"""Host-side rank-straggler detection over the gathered timing plane.

The jitted step all_gathers each rank's host-measured durations
(`taps.gather_rank_timings` — one tiny collective per step); this
module turns the resulting (n_ranks, k) matrices into skew numbers and
persistent-outlier flags.  ≡ the reference debugging workflow of
bisecting a slow DP rank by hand, made a first-class signal (T3, arXiv
2401.16677: fine-grained compute/collective timing visibility).

Skew convention: `skew = max / median` of the per-rank duration — 1.0
is a perfectly balanced step, 2.0 means the slowest rank took twice
the median and the whole data-parallel step waited for it.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from apex_tpu.monitor.trace.taps import TIMING_FIELDS


class StragglerDetector:
    """Flags ranks whose step duration is persistently skewed.

    threshold: a rank is an outlier on a step when its duration exceeds
    threshold x the step's median.  patience: consecutive outlier steps
    before the rank is flagged (one slow step is noise — a preempted
    host, a GC pause; `patience` of them is a straggler).  field:
    which timing column to detect on (default 0 = step duration).
    """

    def __init__(self, threshold: float = 1.5, patience: int = 3,
                 field: int = 0):
        if threshold <= 1.0:
            raise ValueError(f"threshold must be > 1.0, got {threshold}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.threshold = threshold
        self.patience = patience
        self.field = field
        self._consecutive: Optional[np.ndarray] = None
        self.steps_seen = 0
        self.last: Optional[dict] = None

    def reset(self) -> None:
        """Clear all history (counters, last summary, rank count).  The
        elastic-resume rebuild hook: after a dp=N→M topology change the
        rank count legitimately differs, and `update` otherwise refuses
        a mid-run rank-count change."""
        self._consecutive = None
        self.steps_seen = 0
        self.last = None

    def update(self, timings) -> dict:
        """Fold one step's gathered (n_ranks, k) timing matrix in.

        Returns this step's summary (also kept as `.last`):
        {"skew", "median_s", "max_s", "max_rank", "flagged": [
         {"rank", "skew", "consecutive"}]} — `flagged` lists ranks at
        or past `patience` consecutive outlier steps."""
        t = np.asarray(timings, np.float64)
        if t.ndim == 1:
            t = t[:, None]
        col = t[:, self.field]
        n = col.shape[0]
        if self._consecutive is None:
            self._consecutive = np.zeros(n, np.int64)
        elif self._consecutive.shape[0] != n:
            raise ValueError(
                f"rank count changed mid-run: {self._consecutive.shape[0]}"
                f" -> {n}")
        median = float(np.median(col))
        max_rank = int(np.argmax(col))
        mx = float(col[max_rank])
        floor = max(median, 1e-12)
        outlier = col > self.threshold * median if median > 0 else \
            np.zeros(n, bool)
        self._consecutive = np.where(outlier, self._consecutive + 1, 0)
        self.steps_seen += 1
        self.last = {
            "step_index": self.steps_seen,
            "n_ranks": n,
            "median_s": median,
            "max_s": mx,
            "max_rank": max_rank,
            "skew": mx / floor,
            "flagged": [
                {"rank": int(r),
                 "skew": float(col[r] / floor),
                 "consecutive": int(self._consecutive[r])}
                for r in np.nonzero(
                    self._consecutive >= self.patience)[0]],
        }
        return self.last

    @property
    def flagged_ranks(self) -> Sequence[int]:
        if self.last is None:
            return ()
        return tuple(f["rank"] for f in self.last["flagged"])

    def summary(self) -> dict:
        """The flight-report `straggler` section."""
        return {
            "threshold": self.threshold,
            "patience": self.patience,
            "field": TIMING_FIELDS[self.field]
            if self.field < len(TIMING_FIELDS) else self.field,
            "steps_seen": self.steps_seen,
            "last": self.last,
        }
