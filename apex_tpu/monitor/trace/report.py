"""Flight-report rendering: the last-good → first-bad timeline.

Input is the JSON report `FlightRecorder.dump` wrote (see recorder.py
for the schema); `render_report` turns it into the terminal story a
diverged run needs told: which steps were still healthy, where the
first non-finite value entered, WHICH tap (layer + plane) it entered
at, and whether a rank was straggling while it happened.
`scripts/flight_report.py` is the CLI wrapper; its `--selftest` renders
a committed fixture and exits nonzero on schema drift.
"""

from __future__ import annotations

import math
from typing import List, Optional

from apex_tpu.monitor.trace.recorder import FLIGHT_RECORDER_VERSION

_REQUIRED_TOP = ("flight_recorder_version", "monitor_schema_version",
                 "reason", "oom", "capacity", "tap_names",
                 "timing_fields", "straggler", "compile_report",
                 "compile_events", "memory", "records")
_REQUIRED_REC = ("step", "metrics", "taps", "timings")


def validate_report(report: dict) -> None:
    """Raise ValueError unless `report` matches the current
    flight-recorder schema (recorder.py docstring).  The version check
    is exact: a drifted fixture or a stale report from an older build
    must fail loudly, not render garbage."""
    from apex_tpu.monitor import logger as logger_lib
    if not isinstance(report, dict):
        raise ValueError(f"report is {type(report).__name__}, want dict")
    for k in _REQUIRED_TOP:
        if k not in report:
            raise ValueError(f"missing report field {k!r}")
    if report["flight_recorder_version"] != FLIGHT_RECORDER_VERSION:
        raise ValueError(
            f"flight_recorder_version "
            f"{report['flight_recorder_version']!r} != "
            f"{FLIGHT_RECORDER_VERSION}")
    if report["monitor_schema_version"] != logger_lib.SCHEMA_VERSION:
        raise ValueError(
            f"monitor_schema_version "
            f"{report['monitor_schema_version']!r} != "
            f"{logger_lib.SCHEMA_VERSION}")
    if not isinstance(report["records"], list):
        raise ValueError("records is not a list")
    prev = None
    for i, rec in enumerate(report["records"]):
        for k in _REQUIRED_REC:
            if k not in rec:
                raise ValueError(f"record {i} missing field {k!r}")
        if not isinstance(rec["step"], int):
            raise ValueError(f"record {i} step is not an int")
        if prev is not None and rec["step"] <= prev:
            raise ValueError(
                f"non-monotonic record steps: {rec['step']} after {prev}")
        prev = rec["step"]


def _is_bad(rec: dict) -> bool:
    """A record is 'bad' when any tap tripped or the logged loss went
    non-finite (null + marker after JSON sanitization)."""
    taps = rec.get("taps") or {}
    if taps.get("first_bad_fwd") or taps.get("first_bad_grad"):
        return True
    m = rec.get("metrics") or {}
    if "loss_nonfinite" in m:
        return True
    loss = m.get("loss")
    return isinstance(loss, float) and not math.isfinite(loss)


def _fmt_metrics(m: Optional[dict]) -> str:
    if not m:
        return ""
    parts = []
    for k, fmt in (("loss", "{:.4f}"), ("grad_norm", "{:.3e}"),
                   ("loss_scale", "{:g}")):
        v = m.get(k)
        if v is None and f"{k}_nonfinite" in m:
            parts.append(f"{k} {m[f'{k}_nonfinite']}")
        elif isinstance(v, (int, float)):
            parts.append(f"{k} {fmt.format(v)}")
    return " | ".join(parts)


def render_report(report: dict, last: Optional[int] = None) -> str:
    """Render the timeline (newest-last).  `last` limits to the final N
    records.  Raises ValueError on schema drift (validate_report)."""
    validate_report(report)
    records = report["records"]
    if last is not None:
        records = records[-last:]
    lines: List[str] = []
    lines.append("=== numerics flight report ===")
    lines.append(f"reason: {report['reason']}")
    if records:
        lines.append(f"ring: {len(records)} of last {report['capacity']} "
                     f"steps (steps {records[0]['step']}.."
                     f"{records[-1]['step']})")
    else:
        lines.append("ring: empty")

    if report.get("oom"):
        lines.append("!! OOM: the run died RESOURCE_EXHAUSTED — HBM "
                     "budget below")

    strag = report.get("straggler")
    if strag and strag.get("last"):
        s = strag["last"]
        flagged = s.get("flagged") or []
        lines.append(
            f"rank timing ({strag.get('field')}): skew "
            f"{s['skew']:.2f}x (max rank {s['max_rank']}, "
            f"median {s['median_s'] * 1e3:.1f} ms)")
        for f in flagged:
            lines.append(
                f"  ** STRAGGLER rank {f['rank']}: {f['skew']:.2f}x "
                f"median for {f['consecutive']} consecutive steps")

    events = report.get("compile_events") or []
    if events:
        steady = [e for e in events if e.get("steady_state")]
        lines.append(f"compile: {len(events)} compile event(s), "
                     f"{len(steady)} steady-state")
        for e in events[-4:]:  # the tail tells the story
            sig = str(e.get("signature", ""))[:100]
            tag = ("** RECOMPILE" if e.get("steady_state")
                   else "   compile")
            lines.append(f"{tag} at call {e.get('call')} "
                         f"[{e.get('kind')}]: {sig}")

    mem = report.get("memory") or {}
    # device ids are stringified ints: numeric order, not lexicographic
    # (a 16-chip host must not render 0, 1, 10, 11, ..., 2, ...)
    def _dev_key(kv):
        return (0, int(kv[0])) if kv[0].isdigit() else (1, kv[0])

    for dev_id, stats in sorted(mem.items(), key=_dev_key):
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is None and peak is None:
            continue
        line = f"hbm[{dev_id}]:"
        if in_use is not None:
            line += f" {in_use / 2**30:.2f} GiB in use"
        if peak is not None:
            line += f" / {peak / 2**30:.2f} GiB peak"
        if limit is not None:
            line += f" (limit {limit / 2**30:.2f} GiB)"
        lines.append(line)

    if report.get("compile_report") and (report.get("oom") or events):
        # the budget table IS the OOM forensics payload; on a healthy
        # explicit dump it stays out of the way unless compiles fired
        from apex_tpu.monitor.compile import report as compile_report
        try:
            lines.append(compile_report.render_budget_table(
                report["compile_report"]))
        except Exception as e:  # a drifted attachment must not cost
            lines.append(f"(compile report unrenderable: {e!r})")

    last_good = None
    first_bad = None
    for rec in records:
        if _is_bad(rec):
            if first_bad is None:
                first_bad = rec
        elif first_bad is None:
            last_good = rec

    lines.append("--- timeline ---")
    for rec in records:
        bad = _is_bad(rec)
        tag = "  "
        if rec is last_good:
            tag = "OK"
        elif rec is first_bad:
            tag = "!!"
        elif bad:
            tag = " !"
        line = f"{tag} step {rec['step']:>8}"
        ms = _fmt_metrics(rec.get("metrics"))
        if ms:
            line += "  " + ms
        taps = rec.get("taps") or {}
        for plane in ("fwd", "grad"):  # forward origin wins (taps.provenance)
            nm = taps.get(f"first_bad_{plane}")
            if nm:
                stats = (taps.get(plane) or {}).get(nm) or {}
                n_bad = stats.get("nonfinite")
                line += (f"  <- first non-finite [{plane}] at {nm}"
                         + (f" ({n_bad:.0f} elements)"
                            if isinstance(n_bad, float) else ""))
                break
        lines.append(line)

    lines.append("--- verdict ---")
    if report.get("oom"):
        lines.append(
            "death by RESOURCE_EXHAUSTED: compare the HBM budget "
            "table above against the device limit (shrink the batch, "
            "enable remat, or shard the optimizer state)")
    if first_bad is None:
        lines.append("no non-finite step in the recorded window")
    else:
        if last_good is not None:
            lines.append(f"last good step: {last_good['step']}")
        taps = first_bad.get("taps") or {}
        culprit = (taps.get("first_bad_fwd")
                   or taps.get("first_bad_grad"))
        plane = ("fwd" if taps.get("first_bad_fwd") else "grad")
        if culprit:
            lines.append(
                f"first bad step: {first_bad['step']} — non-finite "
                f"values first observed at tap '{culprit}' "
                f"({plane} plane)")
        else:
            lines.append(
                f"first bad step: {first_bad['step']} — loss went "
                "non-finite (no tap attribution recorded; was the "
                "step built with trace taps enabled?)")
    return "\n".join(lines)
