"""`FlightRecorder` — the bounded host-side ring buffer + crash dump.

The device planes (MetricsState, TapState, gathered rank timings) ride
inside the jitted step; the recorder holds the last N steps of them ON
DEVICE (tiny pytrees — a few hundred scalars per step) and only
device_gets when a report is actually dumped — recording a step never
blocks on the step just dispatched (the straggler fold fetches only
the PREVIOUS, already-materialized timing matrix; see record()).  On an exception inside
`guard()` — or an explicit `dump()` from a SIGTERM handler — the ring
is fetched and written as ONE self-contained JSON report that
`monitor.trace.report` (or `scripts/flight_report.py`) renders into
the last-good → first-bad timeline.

Report schema (validated by `report.validate_report`; bump
FLIGHT_RECORDER_VERSION on any field add/rename/re-semantics):

    {"flight_recorder_version": 2,
     "monitor_schema_version":  <logger.SCHEMA_VERSION>,
     "reason": "exception: ..." | "explicit" | ...,
     "oom": bool,                        # RESOURCE_EXHAUSTED death?
     "capacity": N, "tap_names": [...], "timing_fields": [...],
     "straggler": {...} | null,          # StragglerDetector.summary()
     "compile_report": {...} | null,     # last attached CompileReport
     "compile_events": [{...}],          # RecompileSentry events
     "memory": {device_id: stats} | null,  # memory_stats at dump time
     "serve": {...} | null,              # attach_serve telemetry_report
     "records": [{"step": int,
                  "metrics": {...} | null,   # flat MetricsLogger record
                  "taps": {...} | null,      # taps.taps_to_dict shape
                  "timings": {"per_rank": [[...], ...]} | null}]}

v2 (ISSUE 5) added the compile & HBM observatory plane: the last
`CompileReport` (attach via `attach_compile_report`, or let
`compile.RecompileSentry(step, recorder=...)` push its events), and —
the OOM-forensics contract — `guard()` classifies a
RESOURCE_EXHAUSTED death (`compile.is_oom`) and dumps with `oom:
true` plus a fresh per-device memory snapshot, so an OOM dies with a
budget table instead of a bare stack trace.  A report produced by
`analyze_step(..., lint=True)` (ISSUE 6) additionally carries the
static linter's verdict in its `lint` field — the crash dump then
tells the lint story too, with no schema change here (the field rides
inside compile_report).

The serving plane (ISSUE 10) rides the same no-schema-change
attachment pattern: `attach_serve(engine)` keeps a reference to a
`serve.DecodeEngine` (or anything with `telemetry_report()`, or a
plain dict) and the dump materializes its request-lifecycle ledger
tail + gauges + engine stats under a `serve` key — an ADDITIVE
optional field (`validate_report` tolerates extras, like the lint
verdict above), so v2 reports from older builds still render.  A
serving crash then dies with its last N requests' lifecycle stamps
next to the compile events, instead of a bare stack trace.

Non-finite floats (an overflow step's absmax is ±inf by construction)
are serialized through `sinks.sanitize_json_floats` — the report is
always parseable JSON, which is the entire point of a crash artifact.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
from typing import Optional, Sequence

import jax

from apex_tpu.monitor.sinks import sanitize_json_floats
from apex_tpu.monitor.trace import taps as taps_lib
from apex_tpu.monitor.trace.straggler import StragglerDetector

FLIGHT_RECORDER_VERSION = 2

# compile events are rare (a healthy run has a handful at warmup);
# bound the list anyway — a pathological retrace-every-step run must
# not grow the crash artifact without bound
_MAX_COMPILE_EVENTS = 64


class FlightRecorder:
    """Ring buffer of the last `capacity` steps' telemetry planes.

    path: where `dump()` writes the JSON report.  tap_names: ordered
    tap labels (usually `step.tap_names()` after the first call — pass
    later via `record(tap_names=...)` if unknown at construction).
    straggler: an optional StragglerDetector fed each step's gathered
    timings (its summary lands in the report).
    """

    def __init__(self, path, capacity: int = 64,
                 tap_names: Optional[Sequence[str]] = None,
                 timing_fields: Sequence[str] = taps_lib.TIMING_FIELDS,
                 straggler: Optional[StragglerDetector] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = os.fspath(path)
        self.capacity = capacity
        self.tap_names = list(tap_names) if tap_names is not None else None
        self.timing_fields = list(timing_fields)
        self.straggler = straggler
        self._ring = collections.deque(maxlen=capacity)
        # timing matrices awaiting the straggler fold (at most one —
        # see record(): the newest step's output may still be in
        # flight, so its device_get is deferred one call)
        self._pending_timings = collections.deque()
        # the compile & HBM observatory plane (ISSUE 5)
        self._compile_report = None
        self._compile_events = collections.deque(
            maxlen=_MAX_COMPILE_EVENTS)
        # the serving plane (ISSUE 10): a live source, resolved at
        # dump time so the crash artifact carries the ledger tail AS
        # OF the crash, not as of attachment
        self._serve_source = None

    def __len__(self) -> int:
        return len(self._ring)

    def attach_compile_report(self, report) -> None:
        """Keep the latest AOT audit (`compile.CompileReport` or its
        to_dict form) so a crash — an OOM especially — dumps WITH the
        HBM budget that explains it."""
        if hasattr(report, "to_dict"):
            report = report.to_dict()
        self._compile_report = report

    def attach_serve(self, source) -> None:
        """Attach the serving observatory (ISSUE 10): `source` is a
        `serve.DecodeEngine` — anything with `telemetry_report()` —
        or an already-materialized dict.  The report gains a `serve`
        key holding the request-lifecycle ledger tail, gauges/peaks,
        and engine stats, resolved AT DUMP TIME (a crash dumps the
        requests that were actually in flight).  Additive-optional:
        no recorder version bump, old reports still validate
        (the lint-inside-compile_report precedent)."""
        self._serve_source = source

    def _serve_report(self):
        src = self._serve_source
        if src is None:
            return None
        try:
            if hasattr(src, "telemetry_report"):
                return src.telemetry_report()
            return dict(src)
        except Exception as e:  # pragma: no cover — a poisoned engine
            return {"fetch_error": repr(e)}  # must not cost the report

    def note_compile_event(self, event: dict) -> None:
        """Record one sentry compile event (bounded list; the
        `compile.RecompileSentry(step, recorder=...)` hookup calls
        this so retraces land in the crash artifact)."""
        self._compile_events.append(dict(event))

    def record(self, step: int, *, metrics: Optional[dict] = None,
               taps=None, timings=None,
               tap_names: Optional[Sequence[str]] = None) -> None:
        """Append one step.  metrics: the flat host record
        `MetricsLogger.log_step` returned (already fetched).  taps: the
        step's TapState (kept as DEVICE arrays until dump).  timings:
        the gathered (n_ranks, k) matrix (device or host).

        An attached StragglerDetector needs EVERY step in order (its
        consecutive-outlier counts cannot be reconstructed from the
        bounded ring at dump time), but fetching the newest step's
        output here would block on the step that was just dispatched.
        So the fold is deferred one call: step N's matrix is
        device_get when step N+1 is recorded — by then it is
        materialized and the fetch is free — and `report()` drains the
        last one."""
        if tap_names is not None and self.tap_names is None:
            self.tap_names = list(tap_names)
        if timings is not None and self.straggler is not None:
            self._pending_timings.append(timings)
            while len(self._pending_timings) > 1:
                self.straggler.update(
                    jax.device_get(self._pending_timings.popleft()))
        self._ring.append(
            {"step": int(step), "metrics": metrics, "taps": taps,
             "timings": timings})

    def report(self, reason: str = "explicit", oom: bool = False) -> dict:
        """Materialize the report dict (device_gets the ring)."""
        while self._pending_timings:  # the deferred straggler fold
            try:
                self.straggler.update(
                    jax.device_get(self._pending_timings.popleft()))
            except Exception:  # a poisoned buffer must not cost the
                pass           # whole report
        records = []
        for entry in self._ring:
            rec = {"step": entry["step"], "metrics": entry["metrics"],
                   "taps": None, "timings": None}
            try:
                if entry["taps"] is not None:
                    rec["taps"] = taps_lib.taps_to_dict(
                        entry["taps"], self.tap_names or [])
                if entry["timings"] is not None:
                    t = jax.device_get(entry["timings"])
                    rec["timings"] = {
                        "per_rank": [[float(v) for v in row]
                                     for row in t]}
            except Exception as e:  # a poisoned device buffer must not
                rec["fetch_error"] = repr(e)  # cost us the whole report
            records.append(rec)
        from apex_tpu.monitor import logger as logger_lib
        import apex_tpu.monitor.compile.watermarks as wm
        try:
            # a fresh allocator snapshot at dump time (None on CPU);
            # on an OOM this is the "how full was the chip" answer
            memory = wm.all_device_memory_stats()
        except Exception:  # pragma: no cover — never cost the report
            memory = None
        return {
            "flight_recorder_version": FLIGHT_RECORDER_VERSION,
            "monitor_schema_version": logger_lib.SCHEMA_VERSION,
            "reason": reason,
            "oom": bool(oom),
            "capacity": self.capacity,
            "tap_names": list(self.tap_names or []),
            "timing_fields": list(self.timing_fields),
            "straggler": (self.straggler.summary()
                          if self.straggler is not None else None),
            "compile_report": self._compile_report,
            "compile_events": list(self._compile_events),
            "memory": memory,
            "serve": self._serve_report(),
            "records": records,
        }

    def dump(self, reason: str = "explicit", oom: bool = False) -> dict:
        """Write the report to `self.path` (atomic: tmp + rename — a
        crash artifact that is itself truncated is worse than none) and
        return it."""
        rep = sanitize_json_floats(self.report(reason, oom=oom))
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rep, f, indent=1, allow_nan=False)
            f.write("\n")
        os.replace(tmp, self.path)
        return rep

    @contextlib.contextmanager
    def guard(self):
        """Wrap the training loop: any exception dumps the report
        (reason = the exception repr) and re-raises.  A
        RESOURCE_EXHAUSTED / out-of-memory death (`compile.is_oom`)
        dumps with `oom: true` — together with the attached
        CompileReport and the per-device memory snapshot the report
        already carries, the run dies with an HBM budget table
        instead of a bare stack trace."""
        import apex_tpu.monitor.compile.watermarks as wm
        try:
            yield self
        except BaseException as e:
            self.dump(reason=f"exception: {e!r}", oom=wm.is_oom(e))
            raise
