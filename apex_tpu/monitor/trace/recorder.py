"""`FlightRecorder` — the bounded host-side ring buffer + crash dump.

The device planes (MetricsState, TapState, gathered rank timings) ride
inside the jitted step; the recorder holds the last N steps of them ON
DEVICE (tiny pytrees — a few hundred scalars per step) and only
device_gets when a report is actually dumped — recording a step never
blocks on the step just dispatched (the straggler fold fetches only
the PREVIOUS, already-materialized timing matrix; see record()).  On an exception inside
`guard()` — or an explicit `dump()` from a SIGTERM handler — the ring
is fetched and written as ONE self-contained JSON report that
`monitor.trace.report` (or `scripts/flight_report.py`) renders into
the last-good → first-bad timeline.

Report schema (validated by `report.validate_report`; bump
FLIGHT_RECORDER_VERSION on any field add/rename/re-semantics):

    {"flight_recorder_version": 1,
     "monitor_schema_version":  <logger.SCHEMA_VERSION>,
     "reason": "exception: ..." | "explicit" | ...,
     "capacity": N, "tap_names": [...], "timing_fields": [...],
     "straggler": {...} | null,          # StragglerDetector.summary()
     "records": [{"step": int,
                  "metrics": {...} | null,   # flat MetricsLogger record
                  "taps": {...} | null,      # taps.taps_to_dict shape
                  "timings": {"per_rank": [[...], ...]} | null}]}

Non-finite floats (an overflow step's absmax is ±inf by construction)
are serialized through `sinks.sanitize_json_floats` — the report is
always parseable JSON, which is the entire point of a crash artifact.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
from typing import Optional, Sequence

import jax

from apex_tpu.monitor.sinks import sanitize_json_floats
from apex_tpu.monitor.trace import taps as taps_lib
from apex_tpu.monitor.trace.straggler import StragglerDetector

FLIGHT_RECORDER_VERSION = 1


class FlightRecorder:
    """Ring buffer of the last `capacity` steps' telemetry planes.

    path: where `dump()` writes the JSON report.  tap_names: ordered
    tap labels (usually `step.tap_names()` after the first call — pass
    later via `record(tap_names=...)` if unknown at construction).
    straggler: an optional StragglerDetector fed each step's gathered
    timings (its summary lands in the report).
    """

    def __init__(self, path, capacity: int = 64,
                 tap_names: Optional[Sequence[str]] = None,
                 timing_fields: Sequence[str] = taps_lib.TIMING_FIELDS,
                 straggler: Optional[StragglerDetector] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.path = os.fspath(path)
        self.capacity = capacity
        self.tap_names = list(tap_names) if tap_names is not None else None
        self.timing_fields = list(timing_fields)
        self.straggler = straggler
        self._ring = collections.deque(maxlen=capacity)
        # timing matrices awaiting the straggler fold (at most one —
        # see record(): the newest step's output may still be in
        # flight, so its device_get is deferred one call)
        self._pending_timings = collections.deque()

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, step: int, *, metrics: Optional[dict] = None,
               taps=None, timings=None,
               tap_names: Optional[Sequence[str]] = None) -> None:
        """Append one step.  metrics: the flat host record
        `MetricsLogger.log_step` returned (already fetched).  taps: the
        step's TapState (kept as DEVICE arrays until dump).  timings:
        the gathered (n_ranks, k) matrix (device or host).

        An attached StragglerDetector needs EVERY step in order (its
        consecutive-outlier counts cannot be reconstructed from the
        bounded ring at dump time), but fetching the newest step's
        output here would block on the step that was just dispatched.
        So the fold is deferred one call: step N's matrix is
        device_get when step N+1 is recorded — by then it is
        materialized and the fetch is free — and `report()` drains the
        last one."""
        if tap_names is not None and self.tap_names is None:
            self.tap_names = list(tap_names)
        if timings is not None and self.straggler is not None:
            self._pending_timings.append(timings)
            while len(self._pending_timings) > 1:
                self.straggler.update(
                    jax.device_get(self._pending_timings.popleft()))
        self._ring.append(
            {"step": int(step), "metrics": metrics, "taps": taps,
             "timings": timings})

    def report(self, reason: str = "explicit") -> dict:
        """Materialize the report dict (device_gets the ring)."""
        while self._pending_timings:  # the deferred straggler fold
            try:
                self.straggler.update(
                    jax.device_get(self._pending_timings.popleft()))
            except Exception:  # a poisoned buffer must not cost the
                pass           # whole report
        records = []
        for entry in self._ring:
            rec = {"step": entry["step"], "metrics": entry["metrics"],
                   "taps": None, "timings": None}
            try:
                if entry["taps"] is not None:
                    rec["taps"] = taps_lib.taps_to_dict(
                        entry["taps"], self.tap_names or [])
                if entry["timings"] is not None:
                    t = jax.device_get(entry["timings"])
                    rec["timings"] = {
                        "per_rank": [[float(v) for v in row]
                                     for row in t]}
            except Exception as e:  # a poisoned device buffer must not
                rec["fetch_error"] = repr(e)  # cost us the whole report
            records.append(rec)
        from apex_tpu.monitor import logger as logger_lib
        return {
            "flight_recorder_version": FLIGHT_RECORDER_VERSION,
            "monitor_schema_version": logger_lib.SCHEMA_VERSION,
            "reason": reason,
            "capacity": self.capacity,
            "tap_names": list(self.tap_names or []),
            "timing_fields": list(self.timing_fields),
            "straggler": (self.straggler.summary()
                          if self.straggler is not None else None),
            "records": records,
        }

    def dump(self, reason: str = "explicit") -> dict:
        """Write the report to `self.path` (atomic: tmp + rename — a
        crash artifact that is itself truncated is worse than none) and
        return it."""
        rep = sanitize_json_floats(self.report(reason))
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rep, f, indent=1, allow_nan=False)
            f.write("\n")
        os.replace(tmp, self.path)
        return rep

    @contextlib.contextmanager
    def guard(self):
        """Wrap the training loop: any exception dumps the report
        (reason = the exception repr) and re-raises."""
        try:
            yield self
        except BaseException as e:
            self.dump(reason=f"exception: {e!r}")
            raise
