"""apex_tpu.monitor.trace — the numerics flight recorder (ISSUE 4).

PR 2's `monitor` answers "how fast / how healthy" per step; this
subpackage answers "WHERE did it go wrong" with three capture planes
that all ride inside the jitted step (zero host syncs, no per-tap
collectives — the MetricsState discipline):

  * taps      — `TapState`: per-layer [absmax, mean, rms, nonfinite]
                for forward activations AND their gradients at named
                tap points (`ops._common.tap`, threaded through
                models/gpt.py + models/bert.py), plus on-device
                first-nonfinite provenance indices.  Compiled out
                entirely when disabled.
  * timing    — `gather_rank_timings`: one all_gather of a tiny
                per-rank duration vector per step; the host-side
                `StragglerDetector` turns the history into max/median
                skew and persistent-outlier flags.
  * recorder  — `FlightRecorder`: bounded ring of the last N steps'
                planes (kept on device until needed) that dumps ONE
                JSON report on exception / explicit dump();
                `render_report` / scripts/flight_report.py print the
                last-good → first-bad timeline.

See docs/observability.md ("Debugging a divergence") for the recipes.
"""

from apex_tpu.monitor.trace.recorder import (  # noqa: F401
    FLIGHT_RECORDER_VERSION,
    FlightRecorder,
)
from apex_tpu.monitor.trace.report import (  # noqa: F401
    render_report,
    validate_report,
)
from apex_tpu.monitor.trace.straggler import StragglerDetector  # noqa: F401
from apex_tpu.monitor.trace.taps import (  # noqa: F401
    TAP_PLANES,
    TAP_STAT_DIM,
    TAP_STAT_FIELDS,
    TIMING_FIELDS,
    TapContext,
    TapState,
    TraceConfig,
    finalize,
    gather_rank_timings,
    make_probes,
    provenance,
    tap,
    tap_context,
    tap_stats,
    taps_to_dict,
)
