"""Per-model FLOP accounting for MFU — the formulas of
`scripts/gpt_anatomy.py` (round 6 roofline anatomy) packaged as a
library so `MetricsLogger` can derive MFU from step time.

Conventions match the anatomy script exactly so MFU here agrees with
the committed roofline tables in docs/PERF.md:

  * every GEMM counts 2*M*K*N, and fwd+bwd counts 3x fwd (dgrad +
    wgrad are the two transposed matmuls of the backward);
  * attention scores/context count the FULL S x S square even for
    causal models — at the bench block configs the flash kernel
    executes the full square (gpt_anatomy.py module docstring), so
    this is executed-flop MFU, not a 2x-flattering "causal" MFU;
  * LayerNorm/softmax/optimizer FLOPs are omitted (sub-1% and
    bandwidth-bound).
"""

from __future__ import annotations

# v5e bf16 matmul peak — the PEAK constant of scripts/gpt_anatomy.py.
V5E_BF16_PEAK = 197e12


def transformer_step_flops(*, hidden: int, num_layers: int,
                           num_heads: int, vocab_size: int, batch: int,
                           seq: int, ffn_mult: int = 4,
                           with_head: bool = True) -> int:
    """Fwd+bwd FLOPs of one training step of a standard pre-LN
    transformer (GPT/BERT body): QKV+out projections, S x S attention,
    ffn_mult MLP, optional tied LM head."""
    b, s, h, l = batch, seq, hidden, num_layers
    d = hidden // num_heads
    proj = 2 * b * s * h * 4 * h            # qkv (3h) + out (h) GEMMs
    sdpa = 2 * b * num_heads * s * s * d * 2  # scores + context
    attn = (proj + sdpa) * 3
    mlp = 2 * b * s * h * (2 * ffn_mult * h) * 3   # up + down GEMMs
    total = (attn + mlp) * l
    if with_head:
        total += 2 * b * s * h * vocab_size * 3
    return int(total)


def gpt_step_flops(config, batch: int, seq=None) -> int:
    """Step FLOPs for a `models.gpt.GPTConfig` (seq defaults to the
    config's seq_len)."""
    return transformer_step_flops(
        hidden=config.hidden, num_layers=config.num_layers,
        num_heads=config.num_heads, vocab_size=config.vocab_size,
        batch=batch, seq=config.seq_len if seq is None else seq,
        ffn_mult=config.ffn_mult, with_head=True)


def bert_step_flops(config, batch: int, seq=None) -> int:
    """Step FLOPs for a `models.bert.BertConfig` (MLM head = the same
    tied vocab GEMM; the NSP head is negligible)."""
    return transformer_step_flops(
        hidden=config.hidden, num_layers=config.num_layers,
        num_heads=config.num_heads, vocab_size=config.vocab_size,
        batch=batch, seq=config.seq_len if seq is None else seq,
        ffn_mult=getattr(config, "ffn_mult", 4), with_head=True)


def mfu(flops_per_step: float, step_time_s: float,
        peak_flops: float = V5E_BF16_PEAK) -> float:
    """Model FLOP utilization in [0, inf): achieved model FLOP/s over
    the hardware peak.  >1 means the accounting under-counts (or the
    peak is wrong for the backend)."""
    if step_time_s <= 0 or peak_flops <= 0:
        return 0.0
    return flops_per_step / step_time_s / peak_flops
