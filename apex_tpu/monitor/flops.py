"""Per-model FLOP accounting for MFU — the formulas of
`scripts/gpt_anatomy.py` (round 6 roofline anatomy) packaged as a
library so `MetricsLogger` can derive MFU from step time.

Conventions match the anatomy script exactly so MFU here agrees with
the committed roofline tables in docs/PERF.md:

  * every GEMM counts 2*M*K*N, and fwd+bwd counts 3x fwd (dgrad +
    wgrad are the two transposed matmuls of the backward);
  * attention scores/context count the FULL S x S square even for
    causal models — at the bench block configs the flash kernel
    executes the full square (gpt_anatomy.py module docstring), so
    this is executed-flop MFU, not a 2x-flattering "causal" MFU;
  * LayerNorm/softmax/optimizer FLOPs are omitted (sub-1% and
    bandwidth-bound).
"""

from __future__ import annotations

from typing import Optional

# v5e bf16 matmul peak — the PEAK constant of scripts/gpt_anatomy.py,
# and the documented fallback when the device kind is unknown (CPU
# test runs, exotic kinds): existing published numbers don't move.
V5E_BF16_PEAK = 197e12

# normalized device generation -> per-chip bf16 dense matmul peak
# (FLOP/s).  Sources: the public TPU spec sheets; v5e matches the
# PEAK every roofline table in docs/PERF.md scores against.
DEVICE_BF16_PEAKS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _normalize_device_kind(kind: str) -> Optional[str]:
    """Map a raw `device.device_kind` string ("TPU v4", "TPU v5 lite",
    "TPU v5e", "TPU v5p", "TPU v6 lite"...) onto a DEVICE_BF16_PEAKS
    key.  Order matters: "v5 lite"/"v5e" must win before the bare
    "v5" of v5p-style strings."""
    k = kind.lower()
    if "v6" in k or "trillium" in k:
        return "v6e"
    if "v5e" in k or "v5 lite" in k or "v5lite" in k:
        return "v5e"
    if "v5p" in k or "v5" in k:
        return "v5p"
    if "v4" in k:
        return "v4"
    if "v3" in k:
        return "v3"
    if "v2" in k:
        return "v2"
    return None


def device_peak_flops(device_kind: Optional[str] = None, *,
                      override: Optional[float] = None,
                      default: float = V5E_BF16_PEAK) -> float:
    """Per-chip bf16 peak for MFU, resolved from the device kind.

    override wins outright (the explicit knob — a sliced-clock pod, a
    peak measured rather than quoted).  device_kind=None reads
    `jax.devices()[0].device_kind`; an unrecognized kind (including
    "cpu") falls back to `default` = V5E_BF16_PEAK, so every number
    published before this table existed is unchanged.
    """
    if override is not None:
        return float(override)
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return default
    norm = _normalize_device_kind(str(device_kind))
    return DEVICE_BF16_PEAKS.get(norm, default)


def transformer_step_flops(*, hidden: int, num_layers: int,
                           num_heads: int, vocab_size: int, batch: int,
                           seq: int, ffn_mult: int = 4,
                           with_head: bool = True) -> int:
    """Fwd+bwd FLOPs of one training step of a standard pre-LN
    transformer (GPT/BERT body): QKV+out projections, S x S attention,
    ffn_mult MLP, optional tied LM head."""
    b, s, h, l = batch, seq, hidden, num_layers
    d = hidden // num_heads
    proj = 2 * b * s * h * 4 * h            # qkv (3h) + out (h) GEMMs
    sdpa = 2 * b * num_heads * s * s * d * 2  # scores + context
    attn = (proj + sdpa) * 3
    mlp = 2 * b * s * h * (2 * ffn_mult * h) * 3   # up + down GEMMs
    total = (attn + mlp) * l
    if with_head:
        total += 2 * b * s * h * vocab_size * 3
    return int(total)


def gpt_step_flops(config, batch: int, seq=None) -> int:
    """Step FLOPs for a `models.gpt.GPTConfig` (seq defaults to the
    config's seq_len)."""
    return transformer_step_flops(
        hidden=config.hidden, num_layers=config.num_layers,
        num_heads=config.num_heads, vocab_size=config.vocab_size,
        batch=batch, seq=config.seq_len if seq is None else seq,
        ffn_mult=config.ffn_mult, with_head=True)


def bert_step_flops(config, batch: int, seq=None) -> int:
    """Step FLOPs for a `models.bert.BertConfig` (MLM head = the same
    tied vocab GEMM; the NSP head is negligible)."""
    return transformer_step_flops(
        hidden=config.hidden, num_layers=config.num_layers,
        num_heads=config.num_heads, vocab_size=config.vocab_size,
        batch=batch, seq=config.seq_len if seq is None else seq,
        ffn_mult=getattr(config, "ffn_mult", 4), with_head=True)


def mfu(flops_per_step: float, step_time_s: float,
        peak_flops: Optional[float] = None) -> float:
    """Model FLOP utilization in [0, inf): achieved model FLOP/s over
    the hardware peak.  >1 means the accounting under-counts (or the
    peak is wrong for the backend).

    peak_flops=None resolves the per-chip peak from the device kind
    (`device_peak_flops`); unknown kinds — CPU test runs included —
    fall back to V5E_BF16_PEAK, so pre-table numbers don't move.
    Multi-chip MFU wants the AGGREGATE peak: pass
    `device_peak_flops() * n_chips` explicitly."""
    if peak_flops is None:
        peak_flops = device_peak_flops()
    if step_time_s <= 0 or peak_flops <= 0:
        return 0.0
    return flops_per_step / step_time_s / peak_flops
