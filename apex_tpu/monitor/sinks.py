"""Host-side metric sinks: where `MetricsLogger` records go.

Three built-ins cover the reference workflows:

  * JSONLSink   — one JSON object per step, append-only; the schema is
                  versioned (`logger.SCHEMA_VERSION`) and validated by
                  tests and bench.py
  * ConsoleSink — the reference's periodic "iteration … | loss … |
                  loss scale …" line (≡ Megatron/apex training_log)
  * SummaryWriterSink — wraps anything with the TensorBoard
                  `SummaryWriter.add_scalar(tag, value, step)` method

and `ScalarWriter` is a minimal `SummaryWriter`-COMPATIBLE object
(implements `add_scalar`) that fans out to sinks — so `Timers.write`,
which expects a `SummaryWriter`, can target the monitor stack directly.
"""

from __future__ import annotations

import json
import math
import os
from typing import Callable, Optional


def sanitize_json_floats(obj):
    """Replace non-finite floats so the result serializes as VALID JSON
    (`json.dumps` defaults to allow_nan=True and emits bare `NaN` /
    `Infinity` tokens — not JSON; they break every schema-validating
    reader downstream, bench.py and the tests included).

    Dict values become `None` plus a `"<key>_nonfinite"` marker holding
    "nan" / "inf" / "-inf" (so the record stays self-describing);
    list/tuple elements become the marker string directly (a list slot
    cannot carry a sibling key).  Finite values pass through untouched;
    nested dicts/lists are handled recursively.
    """
    def marker(v):
        return "nan" if math.isnan(v) else ("inf" if v > 0 else "-inf")

    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if isinstance(v, float) and not math.isfinite(v):
                out[k] = None
                out[f"{k}_nonfinite"] = marker(v)
            else:
                out[k] = sanitize_json_floats(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [marker(v) if isinstance(v, float) and not math.isfinite(v)
                else sanitize_json_floats(v) for v in obj]
    return obj


class MetricSink:
    """One record per logged step.  `write(record)` with a flat
    str→scalar dict; `close()` flushes/releases resources."""

    def write(self, record: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(MetricSink):
    """One JSON line per record; flushed per record so a killed run
    keeps every completed step.  Truncates by default — a fresh run's
    steps restart at 1, and appending onto an old trajectory would make
    the file fail the package's own monotonic-step validation.  Pass
    mode="a" when resuming a run whose step counter continues."""

    def __init__(self, path, mode: str = "w"):
        if mode not in ("w", "a"):
            raise ValueError(f"mode must be 'w' or 'a', got {mode!r}")
        self.path = os.fspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, mode)

    def write(self, record: dict) -> None:
        # allow_nan=False enforces the sanitizer's contract: a NaN/Inf
        # loss on an overflow step must serialize as null + a
        # "<key>_nonfinite" marker, never as a bare NaN token that
        # makes the whole line invalid JSON
        self._f.write(json.dumps(sanitize_json_floats(record),
                                 allow_nan=False) + "\n")
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class ConsoleSink(MetricSink):
    """One human line per record ≡ the reference's training_log string.
    `print_fn` hooks a logger (e.g. `log_util` logger.info)."""

    _ORDER = ("step", "loss", "grad_norm", "loss_scale", "step_time_ms",
              "tokens_per_sec", "mfu")
    _FMT = {"loss": "{:.4f}", "grad_norm": "{:.3e}", "loss_scale": "{:g}",
            "step_time_ms": "{:.1f}", "tokens_per_sec": "{:,.0f}",
            "mfu": "{:.1%}"}

    def __init__(self, print_fn: Optional[Callable[[str], None]] = None):
        self.print_fn = print_fn or print

    def write(self, record: dict) -> None:
        parts = []
        for k in self._ORDER:
            if k in record and record[k] is not None:
                fmt = self._FMT.get(k, "{}")
                parts.append(f"{k} {fmt.format(record[k])}")
        if len(parts) <= 1:
            return  # step-only record (e.g. a ScalarWriter timer tag)
        self.print_fn(" | ".join(parts))


class SummaryWriterSink(MetricSink):
    """Forward every numeric field to a TensorBoard-style writer
    (anything with `add_scalar(tag, value, step)`); `prefix` namespaces
    the tags (`train/loss`, …)."""

    def __init__(self, writer, prefix: str = "train/"):
        if not hasattr(writer, "add_scalar"):
            raise TypeError(
                f"writer {type(writer).__name__} has no add_scalar; need "
                "a SummaryWriter-compatible object")
        self.writer = writer
        self.prefix = prefix
        self._auto_step = 0

    def write(self, record: dict) -> None:
        if "step" in record:
            step = int(record["step"])
            self._auto_step = step
        else:
            # no "step" field: tag with an internal monotonic step
            # instead of silently piling every record onto step 0
            self._auto_step += 1
            step = self._auto_step
        for k, v in record.items():
            # bool is an int subclass — without the explicit skip,
            # flag fields (overflowed_this_window, future overflow
            # markers) would land as 0/1 scalar curves
            if (k == "step" or isinstance(v, bool)
                    or not isinstance(v, (int, float))):
                continue
            self.writer.add_scalar(self.prefix + k, v, step)

    def close(self) -> None:
        if hasattr(self.writer, "flush"):
            self.writer.flush()


class ScalarWriter:
    """Minimal `SummaryWriter`-compatible adapter over sinks.

    Implements the one method this codebase's consumers use —
    `add_scalar(tag, value, step)` (`Timers.write` calls exactly this)
    — and emits each call as a one-field record `{"step": step, tag:
    value}` to every sink.  Lets timer traces land in the same JSONL
    stream as the step metrics.
    """

    def __init__(self, *sinks: MetricSink):
        self.sinks = list(sinks)

    def add_scalar(self, tag: str, value, step: int) -> None:
        rec = {"step": int(step), tag: float(value)}
        for s in self.sinks:
            s.write(rec)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        for s in self.sinks:
            s.close()
