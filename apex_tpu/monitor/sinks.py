"""Host-side metric sinks: where `MetricsLogger` records go.

Three built-ins cover the reference workflows:

  * JSONLSink   — one JSON object per step, append-only; the schema is
                  versioned (`logger.SCHEMA_VERSION`) and validated by
                  tests and bench.py
  * ConsoleSink — the reference's periodic "iteration … | loss … |
                  loss scale …" line (≡ Megatron/apex training_log)
  * SummaryWriterSink — wraps anything with the TensorBoard
                  `SummaryWriter.add_scalar(tag, value, step)` method

and `ScalarWriter` is a minimal `SummaryWriter`-COMPATIBLE object
(implements `add_scalar`) that fans out to sinks — so `Timers.write`,
which expects a `SummaryWriter`, can target the monitor stack directly.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional


class MetricSink:
    """One record per logged step.  `write(record)` with a flat
    str→scalar dict; `close()` flushes/releases resources."""

    def write(self, record: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(MetricSink):
    """One JSON line per record; flushed per record so a killed run
    keeps every completed step.  Truncates by default — a fresh run's
    steps restart at 1, and appending onto an old trajectory would make
    the file fail the package's own monotonic-step validation.  Pass
    mode="a" when resuming a run whose step counter continues."""

    def __init__(self, path, mode: str = "w"):
        if mode not in ("w", "a"):
            raise ValueError(f"mode must be 'w' or 'a', got {mode!r}")
        self.path = os.fspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, mode)

    def write(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class ConsoleSink(MetricSink):
    """One human line per record ≡ the reference's training_log string.
    `print_fn` hooks a logger (e.g. `log_util` logger.info)."""

    _ORDER = ("step", "loss", "grad_norm", "loss_scale", "step_time_ms",
              "tokens_per_sec", "mfu")
    _FMT = {"loss": "{:.4f}", "grad_norm": "{:.3e}", "loss_scale": "{:g}",
            "step_time_ms": "{:.1f}", "tokens_per_sec": "{:,.0f}",
            "mfu": "{:.1%}"}

    def __init__(self, print_fn: Optional[Callable[[str], None]] = None):
        self.print_fn = print_fn or print

    def write(self, record: dict) -> None:
        parts = []
        for k in self._ORDER:
            if k in record and record[k] is not None:
                fmt = self._FMT.get(k, "{}")
                parts.append(f"{k} {fmt.format(record[k])}")
        if len(parts) <= 1:
            return  # step-only record (e.g. a ScalarWriter timer tag)
        self.print_fn(" | ".join(parts))


class SummaryWriterSink(MetricSink):
    """Forward every numeric field to a TensorBoard-style writer
    (anything with `add_scalar(tag, value, step)`); `prefix` namespaces
    the tags (`train/loss`, …)."""

    def __init__(self, writer, prefix: str = "train/"):
        if not hasattr(writer, "add_scalar"):
            raise TypeError(
                f"writer {type(writer).__name__} has no add_scalar; need "
                "a SummaryWriter-compatible object")
        self.writer = writer
        self.prefix = prefix

    def write(self, record: dict) -> None:
        step = int(record.get("step", 0))
        for k, v in record.items():
            if k == "step" or not isinstance(v, (int, float)):
                continue
            self.writer.add_scalar(self.prefix + k, v, step)

    def close(self) -> None:
        if hasattr(self.writer, "flush"):
            self.writer.flush()


class ScalarWriter:
    """Minimal `SummaryWriter`-compatible adapter over sinks.

    Implements the one method this codebase's consumers use —
    `add_scalar(tag, value, step)` (`Timers.write` calls exactly this)
    — and emits each call as a one-field record `{"step": step, tag:
    value}` to every sink.  Lets timer traces land in the same JSONL
    stream as the step metrics.
    """

    def __init__(self, *sinks: MetricSink):
        self.sinks = list(sinks)

    def add_scalar(self, tag: str, value, step: int) -> None:
        rec = {"step": int(step), tag: float(value)}
        for s in self.sinks:
            s.write(rec)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        for s in self.sinks:
            s.close()
