"""`comms_report(step, args) -> CommsReport` — the collective inventory
+ overlap analysis + ICI roofline of one compiled train step.

The communications half of the compile observatory (ISSUE 7): where
`analyze_step` answers "what does this program hold" (HBM) and "what
does it compute" (flops), this answers "what does it SAY over the
interconnect, and does that talk hide behind compute or serialize
against it" — the plane ZeRO-3 and the TP-overlap work (ROADMAP 1-2)
are developed against.

Three layers, all AOT (lower+compile, never execute):

  * inventory — every all-reduce / all-gather / reduce-scatter /
    all-to-all / collective-permute in the OPTIMIZED module: kind,
    operand dtype/bytes, replica groups mapped back to the step's mesh
    axis names, async start/done pairing.
  * overlap — for each async collective, the instructions scheduled
    between its start and done, priced as dot FLOPs: a collective
    whose window holds zero dot flops SERIALIZED (the step waited on
    the wire).  `async_supported=False` (CPU: XLA emits sync
    collectives only) means the plane is unmeasurable, reported as
    such — never faked.
  * roofline — each collective priced analytically against the
    per-device-kind ICI table (`roofline.collective_seconds`),
    totalled into predicted comm seconds, the comm fraction of the
    step (vs flops/peak compute time), and a comm-bound verdict.

`scripts/comms_probe.py` turns the serialized classification into a CI
gate; `crosscheck_rank_timing` closes the loop against the measured
allreduce durations the rank-timing plane (`TraceConfig(
rank_timing=True)`) already gathers at runtime.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import math
from typing import Any, List, Optional, Sequence, Tuple

from apex_tpu.monitor.comms import hlo as hlo_lib
from apex_tpu.monitor.comms import roofline as roofline_lib
# one byte formatter for the whole observatory — the comms table
# prints next to the HBM budget and both must agree what "16.00 MiB"
# is (compile.report does not import comms at module level, so this
# cannot cycle)
from apex_tpu.monitor.compile.report import _human_bytes

# Bump on any Collective/CommsReport field add/rename/re-semantics —
# scripts/comms_probe.py --selftest renders the committed fixture
# (scripts/comms_fixture.json) and exits nonzero on drift, same
# contract as the flight recorder's and the linter's.
COMMS_SCHEMA_VERSION = 1

# a collective smaller than this is never expected to overlap (scalar
# loss pmeans, found_inf psum-ORs, the rank-timing all_gather): hiding
# a 4-byte flag behind a GEMM is noise, not a lever
OVERLAP_BYTES_FLOOR = 1 << 20  # 1 MiB

# the kinds the overlap gate holds to the expected-overlap rule.
# collective-permute joined with the chunked ring-overlap pipelines
# (parallel/overlap.py, ISSUE 18): a >= 1 MiB async ring hop exists
# PRECISELY to hide behind the partial GEMM of the previous chunk, so
# a serialized one is the regression the gate was built for (small
# latency-bound hops stay under OVERLAP_BYTES_FLOOR and are exempt).
# all-to-all overlap stays workload-specific: the MoE micro-chunk
# exchange overlaps chunk k+1's a2a with chunk k's expert FFN, but a
# sync-spelled a2a on a non-chunked path is legitimate.
_EXPECTED_OVERLAP_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                           "collective-permute")


@dataclasses.dataclass
class Collective:
    """One collective of the optimized module (JSON-able via to_dict).

    `operand_bytes` is the total input bytes (for an all-gather: this
    rank's shard — see roofline.py for what each kind's formula does
    with it).  `axes` is the mesh-axis tuple the replica groups span
    (() = degenerate single-device groups, None = unmappable — no mesh
    info, or ids outside the mesh).  `overlap_fraction` is None for a
    sync collective (no start/done window to classify), else the
    fraction of the predicted comm time covered by dot FLOPs scheduled
    inside the window, clamped to 1."""

    name: str
    kind: str
    dtype: str
    operand_bytes: int
    output_bytes: int
    group_size: int
    n_groups: int
    axes: Optional[Tuple[str, ...]]
    async_pair: bool
    n_between: int
    overlapped_flops: float
    predicted_s: float
    overlap_fraction: Optional[float]
    expected_overlap: bool
    serialized: bool
    op_name: str

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["axes"] = None if self.axes is None else list(self.axes)
        return d


@dataclasses.dataclass
class CommsReport:
    """The step's communication anatomy (JSON-able via to_dict)."""

    backend: str
    device_kind: Optional[str]
    mesh_axis_names: Optional[Tuple[str, ...]]
    mesh_axis_sizes: Optional[Tuple[int, ...]]
    collectives: List[Collective]
    # aggregates over NON-degenerate collectives (group_size > 1)
    counts: dict                     # kind -> count
    bytes_by_kind: dict              # kind -> total operand bytes
    total_comm_bytes: int
    # roofline
    link_bandwidth: float
    bandwidth_source: str            # "override" | "table:<kind>" | "default"
    predicted_comm_s: float
    compute_s: Optional[float]       # xla flops / device peak (None: no
    comm_fraction: Optional[float]   # cost analysis on this backend)
    comm_bound: Optional[bool]
    # overlap plane
    async_supported: bool            # any start/done pair in the module
    serialized_comm_bytes: int
    overlap_ok: bool                 # vacuously True when not measurable

    def to_dict(self) -> dict:
        return {
            "comms_schema_version": COMMS_SCHEMA_VERSION,
            "backend": self.backend,
            "device_kind": self.device_kind,
            "mesh_axis_names": (None if self.mesh_axis_names is None
                                else list(self.mesh_axis_names)),
            "mesh_axis_sizes": (None if self.mesh_axis_sizes is None
                                else list(self.mesh_axis_sizes)),
            "collectives": [c.to_dict() for c in self.collectives],
            "counts": dict(self.counts),
            "bytes_by_kind": dict(self.bytes_by_kind),
            "total_comm_bytes": int(self.total_comm_bytes),
            "link_bandwidth": float(self.link_bandwidth),
            "bandwidth_source": self.bandwidth_source,
            "predicted_comm_s": float(self.predicted_comm_s),
            "compute_s": self.compute_s,
            "comm_fraction": self.comm_fraction,
            "comm_bound": self.comm_bound,
            "async_supported": bool(self.async_supported),
            "serialized_comm_bytes": int(self.serialized_comm_bytes),
            "overlap_ok": bool(self.overlap_ok),
        }


# ----------------------- replica-group -> mesh axes -----------------------

def _unravel(i: int, sizes: Sequence[int]) -> Optional[Tuple[int, ...]]:
    total = 1
    for s in sizes:
        total *= s
    if not (0 <= i < total):
        return None
    coords = []
    for s in reversed(sizes):
        coords.append(i % s)
        i //= s
    return tuple(reversed(coords))


def _axes_for_groups(groups, axis_names, axis_sizes):
    """Map replica groups to the mesh axes they span.

    Group ids are LOGICAL device indices of the program's device
    assignment, which for a jit over a Mesh is the row-major flatten of
    `mesh.devices` — so `unravel(id, axis_sizes)` is the device's mesh
    coordinate.  The group's axes = the coordinates that vary within a
    group.  Returns () for degenerate single-member groups and None
    when no mesh info was given or an id falls outside the mesh."""
    if axis_names is None or axis_sizes is None \
            or len(axis_names) != len(axis_sizes):
        return None
    varying = set()
    for g in groups:
        coords = []
        for i in g:
            c = _unravel(int(i), axis_sizes)
            if c is None:
                return None
            coords.append(c)
        for dim in range(len(axis_sizes)):
            if len({c[dim] for c in coords}) > 1:
                varying.add(dim)
    return tuple(axis_names[d] for d in sorted(varying))


# ------------------------------ inventory ------------------------------

def _comp_collective_kind(comp) -> Optional[str]:
    for instr in comp.instructions:
        if instr.opcode in hlo_lib.COLLECTIVE_KINDS:
            return instr.opcode
    return None


def _resolve_kind(instr, kinds_by_comp) -> Optional[str]:
    """Collective kind of a start/done/sync instruction, or None."""
    op = instr.opcode
    if op in hlo_lib.COLLECTIVE_KINDS:
        return op
    for kind in hlo_lib.COLLECTIVE_KINDS:
        if op in (f"{kind}-start", f"{kind}-done"):
            return kind
    if op in ("async-start", "async-done", "async-update"):
        for callee in instr.called:
            k = kinds_by_comp.get(callee)
            if k:
                return k
    return None


def inventory_from_hlo(hlo_text: str, *,
                       mesh_axis_names=None, mesh_axis_sizes=None,
                       peak_flops: float,
                       link_bandwidth: float,
                       overlap_bytes_floor: int = OVERLAP_BYTES_FLOOR,
                       ) -> Tuple[List[Collective], bool]:
    """Parse one optimized-HLO module into the collective inventory.

    Returns (collectives, async_supported).  Pure text analysis — the
    unit the committed-fixture tests exercise without a backend."""
    comps = hlo_lib.parse_module(hlo_text)
    comp_flops = hlo_lib.computation_flops(comps)
    # `replica_groups={}` means one group of ALL participants — the
    # total comes from the mesh when we have one, else the module
    # header (replica_count / num_partitions)
    world = None
    if mesh_axis_sizes:
        world = 1
        for s in mesh_axis_sizes:
            world *= int(s)
    if world is None:
        world = hlo_lib.parse_world_size(hlo_text)
    by_name = {c.name: c for c in comps}
    kinds_by_comp = {c.name: _comp_collective_kind(c) for c in comps}
    # computations wrapped by async-start/done instructions: their
    # inner collective is the async op's body, not a second collective
    async_wrapped = set()
    for comp in comps:
        for instr in comp.instructions:
            if instr.opcode.startswith("async-"):
                async_wrapped.update(instr.called)
    out: List[Collective] = []
    async_supported = False

    for comp in comps:
        starts = {}           # instr name -> (kind, instr)
        done_for = {}         # start name -> done instr
        alias = {}            # async-update name -> its chain's start
        sync = []
        for instr in comp.instructions:
            op = instr.opcode
            if op.endswith("-done"):
                # pairing is by start-name reference — possibly
                # through an async-update chain (the done's operand is
                # the LAST update, not the start); the done op itself
                # often carries neither groups nor calls=
                for ref in instr.operand_names:
                    root = alias.get(ref, ref)
                    if root in starts:
                        done_for[root] = instr
                        break
                continue
            if op.endswith("-update"):
                # bridge start -> update -> ... -> done: without the
                # alias the window would run to the end of the
                # computation and a serialized collective would count
                # post-done dots as overlap
                for ref in instr.operand_names:
                    root = alias.get(ref, ref)
                    if root in starts:
                        alias[instr.name] = root
                        break
                continue
            kind = _resolve_kind(instr, kinds_by_comp)
            if kind is None:
                continue
            if op.endswith("-start"):
                starts[instr.name] = (kind, instr)
            elif comp.name in async_wrapped:
                pass  # the wrapper's start/done entry covers it
            else:
                sync.append((kind, instr))

        for name, (kind, start) in starts.items():
            async_supported = True
            done = done_for.get(name)
            end_idx = done.index if done is not None \
                else len(comp.instructions)
            window = comp.instructions[start.index + 1:end_idx]
            flops_between = sum(
                hlo_lib.instruction_flops(w, comp_flops) for w in window)
            # an async-start wrapper carries no replica_groups itself;
            # the inner collective (inside the called computation) does
            detail = start
            if start.replica_groups is None \
                    and start.source_target_pairs is None:
                for callee in start.called:
                    inner_comp = by_name.get(callee)
                    if inner_comp is None:
                        continue
                    for inner in inner_comp.instructions:
                        if inner.opcode in hlo_lib.COLLECTIVE_KINDS:
                            detail = inner
                            break
            out.append(_build(kind, start, done, mesh_axis_names,
                              mesh_axis_sizes, peak_flops,
                              link_bandwidth, overlap_bytes_floor,
                              async_pair=True,
                              n_between=len(window),
                              overlapped_flops=flops_between,
                              detail=detail, world=world))
        for kind, instr in sync:
            out.append(_build(kind, instr, None, mesh_axis_names,
                              mesh_axis_sizes, peak_flops,
                              link_bandwidth, overlap_bytes_floor,
                              async_pair=False, n_between=0,
                              overlapped_flops=0.0, world=world))
    return out, async_supported


def _build(kind, instr, done, axis_names, axis_sizes, peak_flops,
           link_bandwidth, floor, *, async_pair, n_between,
           overlapped_flops, detail=None, world=None) -> Collective:
    detail = detail if detail is not None else instr
    operand_bytes = sum(s.bytes for s in instr.operand_shapes)
    result = done if done is not None else instr
    output_bytes = sum(s.bytes for s in result.shapes)
    dtype = (instr.operand_shapes[0].dtype if instr.operand_shapes
             else (instr.shapes[0].dtype if instr.shapes else "?"))
    if detail.source_target_pairs is not None:
        pairs = detail.source_target_pairs
        groups = [list(p) for p in pairs]
        group_size = 2 if pairs else 1
        n_groups = len(pairs)
    else:
        groups = detail.replica_groups or []
        if not groups and detail.replica_groups is not None \
                and world and world > 1:
            # `replica_groups={}` = ONE group of ALL participants —
            # NOT a degenerate collective; expand it so a global
            # all-reduce is counted, priced, and gated
            groups = [list(range(world))]
        group_size = max((len(g) for g in groups), default=1)
        n_groups = len(groups)
    axes = _axes_for_groups(groups, axis_names, axis_sizes) \
        if groups else ()
    predicted = roofline_lib.collective_seconds(
        kind, operand_bytes, group_size, link_bandwidth)
    expected = (async_pair and kind in _EXPECTED_OVERLAP_KINDS
                and group_size > 1 and operand_bytes >= floor)
    if not async_pair:
        frac = None
    elif predicted > 0:
        frac = min(1.0, (overlapped_flops / peak_flops) / predicted)
    else:
        frac = 1.0 if overlapped_flops > 0 else 0.0
    return Collective(
        name=instr.name, kind=kind, dtype=dtype,
        operand_bytes=int(operand_bytes), output_bytes=int(output_bytes),
        group_size=int(group_size), n_groups=int(n_groups), axes=axes,
        async_pair=bool(async_pair), n_between=int(n_between),
        overlapped_flops=float(overlapped_flops),
        predicted_s=float(predicted), overlap_fraction=frac,
        expected_overlap=bool(expected),
        serialized=bool(expected and overlapped_flops == 0),
        op_name=(instr.op_name or detail.op_name)[:160])


# ------------------------------ the report ------------------------------

def comms_report(step_fn=None, args: Sequence[Any] = (), *,
                 compiled=None, hlo_text: Optional[str] = None,
                 optimized: bool = True,
                 mesh=None, mesh_axis_names=None, mesh_axis_sizes=None,
                 device_kind: Optional[str] = None,
                 bandwidth_override: Optional[float] = None,
                 overlap_bytes_floor: int = OVERLAP_BYTES_FLOOR,
                 ) -> CommsReport:
    """Lower + compile `step_fn(*args)` WITHOUT executing and inventory
    its collectives.

    step_fn: anything with `.lower(*args)` — a jitted function or a
    builder-attached step (whose `mesh_axis_names`/`mesh_axis_sizes`
    label the replica-group mapping automatically).  args may be real
    arrays or ShapeDtypeStructs, exactly like `analyze_step`.

    compiled: skip the compile and reuse an existing executable (what
    `analyze_step(..., comms=True)` passes so the audit compiles
    ONCE).  hlo_text: skip the backend entirely and analyze a saved
    optimized-HLO dump.  mesh: a `jax.sharding.Mesh` to read axis
    names/sizes from; explicit mesh_axis_names/mesh_axis_sizes win
    over both the mesh and the step attributes.

    optimized=False inventories the PRE-optimization HLO
    (`lower(...).as_text(dialect="hlo")` — no compile at all) instead.
    Use it for authored-dtype claims on non-TPU backends: CPU XLA's
    float-normalization pass rewrites every bf16 collective to f32
    with converts at the boundaries, so the optimized module's wire
    dtype there is a backend artifact, while the pre-opt module keeps
    the dtypes the program actually wrote (and a TPU run keeps bf16
    end to end).  No schedule exists pre-optimization, so the overlap
    plane reports `async_supported=False` and there is no cost
    analysis to derive a comm fraction from.
    """
    import jax

    if compiled is not None and not optimized:
        raise ValueError(
            "comms_report(compiled=..., optimized=False) is "
            "contradictory: an executable only carries the OPTIMIZED "
            "module (on CPU its bf16 collectives are already "
            "float-normalized to f32) — pass the step/args instead so "
            "the pre-optimization HLO can be read from .lower()")
    if hlo_text is None:
        if compiled is None:
            lower = getattr(step_fn, "lower", None)
            if lower is None:
                raise TypeError(
                    f"{type(step_fn).__name__} has no .lower — pass a "
                    "jitted function or a step built by "
                    "ddp.make_train_step / make_tp_dp_train_step")
            if optimized:
                compiled = lower(*args).compile()
            else:
                hlo_text = lower(*args).as_text(dialect="hlo")
        if hlo_text is None:
            hlo_text = compiled.as_text()

    if mesh_axis_names is None:
        if mesh is not None:
            mesh_axis_names = tuple(str(a) for a in mesh.axis_names)
        else:
            mesh_axis_names = getattr(step_fn, "mesh_axis_names", None)
    if mesh_axis_sizes is None:
        if mesh is not None:
            mesh_axis_sizes = tuple(
                int(s) for s in mesh.devices.shape)
        else:
            mesh_axis_sizes = getattr(step_fn, "mesh_axis_sizes", None)
    if mesh_axis_names is not None:
        mesh_axis_names = tuple(mesh_axis_names)
    if mesh_axis_sizes is not None:
        mesh_axis_sizes = tuple(int(s) for s in mesh_axis_sizes)

    backend = jax.default_backend()
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None

    from apex_tpu.monitor import flops as flops_lib
    peak = flops_lib.device_peak_flops(device_kind)
    bw, bw_src = roofline_lib.resolve_link_bandwidth(
        device_kind, override=bandwidth_override)

    collectives, async_supported = inventory_from_hlo(
        hlo_text, mesh_axis_names=mesh_axis_names,
        mesh_axis_sizes=mesh_axis_sizes, peak_flops=peak,
        link_bandwidth=bw, overlap_bytes_floor=overlap_bytes_floor)

    counts: dict = {}
    bytes_by_kind: dict = {}
    total = 0
    predicted = 0.0
    serialized_bytes = 0
    for c in collectives:
        if c.group_size <= 1:
            continue  # degenerate (tp=1 psum etc.) — listed, not counted
        counts[c.kind] = counts.get(c.kind, 0) + 1
        bytes_by_kind[c.kind] = bytes_by_kind.get(c.kind, 0) \
            + c.operand_bytes
        total += c.operand_bytes
        predicted += c.predicted_s
        if c.serialized:
            serialized_bytes += c.operand_bytes

    compute_s = comm_fraction = comm_bound = None
    if compiled is not None:
        from apex_tpu.monitor.compile.report import _cost_entry
        cost = _cost_entry(compiled)
        xla_flops = cost.get("flops") if cost else None
        # `is not None`: flops == 0.0 is a real answer (a collective-only
        # program is 100% comm-bound), not a missing cost analysis
        if xla_flops is not None:
            compute_s = float(xla_flops) / peak
    if compute_s is not None:
        denom = compute_s + predicted
        comm_fraction = predicted / denom if denom > 0 else 0.0
        comm_bound = predicted > compute_s

    return CommsReport(
        backend=backend, device_kind=device_kind,
        mesh_axis_names=mesh_axis_names, mesh_axis_sizes=mesh_axis_sizes,
        collectives=collectives, counts=counts,
        bytes_by_kind=bytes_by_kind, total_comm_bytes=total,
        link_bandwidth=bw, bandwidth_source=bw_src,
        predicted_comm_s=predicted, compute_s=compute_s,
        comm_fraction=comm_fraction, comm_bound=comm_bound,
        async_supported=async_supported,
        serialized_comm_bytes=serialized_bytes,
        overlap_ok=not any(c.serialized for c in collectives))


# ---------------------------- schema + gate ----------------------------

_REPORT_FIELDS = {
    "comms_schema_version": int, "backend": str,
    "device_kind": (str, type(None)),
    "mesh_axis_names": (list, type(None)),
    "mesh_axis_sizes": (list, type(None)),
    "collectives": list, "counts": dict, "bytes_by_kind": dict,
    "total_comm_bytes": int, "link_bandwidth": (int, float),
    "bandwidth_source": str, "predicted_comm_s": (int, float),
    "compute_s": (int, float, type(None)),
    "comm_fraction": (int, float, type(None)),
    "comm_bound": (bool, type(None)),
    "async_supported": bool, "serialized_comm_bytes": int,
    "overlap_ok": bool,
}

_COLLECTIVE_FIELDS = {
    "name": str, "kind": str, "dtype": str, "operand_bytes": int,
    "output_bytes": int, "group_size": int, "n_groups": int,
    "axes": (list, type(None)), "async_pair": bool, "n_between": int,
    "overlapped_flops": (int, float), "predicted_s": (int, float),
    "overlap_fraction": (int, float, type(None)),
    "expected_overlap": bool, "serialized": bool, "op_name": str,
}


def validate_comms_report(report: dict) -> None:
    """Raise ValueError unless `report` (the to_dict form) matches the
    current schema — the drift gate `comms_probe.py --selftest` runs
    over the committed fixture."""
    if not isinstance(report, dict):
        raise ValueError(f"comms report must be a dict, got "
                         f"{type(report).__name__}")
    if report.get("comms_schema_version") != COMMS_SCHEMA_VERSION:
        raise ValueError(
            f"comms_schema_version "
            f"{report.get('comms_schema_version')!r} != "
            f"{COMMS_SCHEMA_VERSION}")
    for name, typ in _REPORT_FIELDS.items():
        if name not in report:
            raise ValueError(f"missing comms report field {name!r}")
        v = report[name]
        if not isinstance(v, typ):
            raise ValueError(f"comms report field {name!r} is "
                             f"{type(v).__name__}")
        if not isinstance(typ, tuple) and typ in (int,) \
                and isinstance(v, bool):
            raise ValueError(f"comms report field {name!r} is bool")
    for i, c in enumerate(report["collectives"]):
        for name, typ in _COLLECTIVE_FIELDS.items():
            if name not in c:
                raise ValueError(
                    f"collective[{i}] missing field {name!r}")
            if not isinstance(c[name], typ):
                raise ValueError(
                    f"collective[{i}].{name} is "
                    f"{type(c[name]).__name__}")
        if c["kind"] not in hlo_lib.COLLECTIVE_KINDS:
            raise ValueError(f"collective[{i}] unknown kind "
                             f"{c['kind']!r}")


def serialized_collectives(report) -> List[dict]:
    """The gate's findings: expected-overlap collectives whose async
    window held zero dot flops.  Accepts a CommsReport or its dict."""
    d = report.to_dict() if hasattr(report, "to_dict") else report
    return [c for c in d["collectives"] if c.get("serialized")]


def parse_allowlist(text: str) -> List[Tuple[str, str]]:
    """`KIND location-glob` lines (fnmatch; `#` comments) accepting
    deliberately serialized collectives out of the gate — the format
    of scripts/lint_allowlist.txt, with collective kinds as the rule
    column.  The committed scripts/comms_allowlist.txt starts EMPTY."""
    entries = []
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split(None, 1)
        kind = parts[0]
        if kind not in hlo_lib.COLLECTIVE_KINDS:
            raise ValueError(
                f"comms allowlist line {ln}: unknown collective kind "
                f"{kind!r}")
        glob = parts[1].strip() if len(parts) > 1 else "*"
        entries.append((kind, glob))
    return entries


def apply_allowlist(findings: Sequence[dict], entries, target: str):
    """Split serialized-collective findings into (new, allowlisted);
    the glob matches `target:instruction-name`."""
    new, allowed = [], []
    for f in findings:
        loc = f"{target}:{f.get('name', '')}"
        if any(k == f.get("kind") and fnmatch.fnmatch(loc, g)
               for k, g in entries):
            allowed.append(f)
        else:
            new.append(f)
    return new, allowed


# ---------------------------- rendering ----------------------------


def _human_s(s) -> str:
    if s is None or not math.isfinite(s):
        return "n/a"
    if s >= 1.0:
        return f"{s:.2f} s"
    if s >= 1e-3:
        return f"{s * 1e3:.2f} ms"
    return f"{s * 1e6:.0f} us"


def render_comms_table(report, label: str = "step") -> str:
    """The comms table an operator reads next to the HBM budget.
    Accepts a CommsReport or its to_dict() (the crash-dump form)."""
    r = report.to_dict() if hasattr(report, "to_dict") else dict(report)
    mesh = ""
    if r.get("mesh_axis_names") and r.get("mesh_axis_sizes"):
        mesh = " | mesh " + "x".join(
            f"{n}={s}" for n, s in zip(r["mesh_axis_names"],
                                       r["mesh_axis_sizes"]))
    lines = [
        f"=== comms: {label} ===",
        f"backend: {r.get('backend')}"
        + (f" ({r['device_kind']})" if r.get("device_kind") else "")
        + mesh
        + f" | ICI {r.get('link_bandwidth', 0) / 1e9:.0f} GB/s"
        + f" ({r.get('bandwidth_source')})",
        "| kind               | dtype |      bytes | axes   | n | "
        "async | overlap | predicted |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for c in r.get("collectives", []):
        if c.get("group_size", 1) <= 1:
            continue
        axes = ("?" if c.get("axes") is None
                else ",".join(c["axes"]) or "-")
        frac = c.get("overlap_fraction")
        overlap = ("sync" if not c.get("async_pair")
                   else f"{100 * frac:.0f}%" if frac is not None
                   else "?")
        mark = " **SER**" if c.get("serialized") else ""
        lines.append(
            f"| {c['kind']:<18} | {c['dtype']:<5} | "
            f"{_human_bytes(c['operand_bytes']):>10} | {axes:<6} | "
            f"{c['group_size']} | {str(c['async_pair']).lower():<5} | "
            f"{overlap:>7} | {_human_s(c['predicted_s']):>9} |{mark}")
    n_deg = sum(1 for c in r.get("collectives", [])
                if c.get("group_size", 1) <= 1)
    counts = r.get("counts") or {}
    by_kind = ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
    lines.append(
        f"totals: {sum(counts.values())} collective(s) "
        f"({by_kind or 'none'}), "
        f"{_human_bytes(r.get('total_comm_bytes', 0))}"
        + (f"; {n_deg} degenerate single-device group(s) not counted"
           if n_deg else ""))
    comp = r.get("compute_s")
    if comp is not None and r.get("comm_fraction") is not None:
        verdict = "COMM-BOUND" if r.get("comm_bound") else "compute-bound"
        lines.append(
            f"roofline: predicted comm {_human_s(r['predicted_comm_s'])}"
            f" vs compute {_human_s(comp)} — "
            f"{100 * r['comm_fraction']:.0f}% of step, {verdict}")
    else:
        lines.append(
            f"roofline: predicted comm "
            f"{_human_s(r.get('predicted_comm_s'))} "
            "(no cost analysis on this backend — comm fraction n/a)")
    if not r.get("async_supported"):
        lines.append(
            "overlap: not measurable (no async start/done pairs — "
            "this backend emits sync collectives; run on TPU for the "
            "schedule truth)")
    elif r.get("overlap_ok"):
        lines.append("overlap: ok (every expected-overlap collective's "
                     "window holds compute)")
    else:
        ser = serialized_collectives(r)
        lines.append(
            f"** {len(ser)} SERIALIZED collective(s) "
            f"({_human_bytes(r.get('serialized_comm_bytes', 0))}): "
            + "; ".join(f"{c['kind']} {c['name']} "
                        f"{_human_bytes(c['operand_bytes'])}"
                        for c in ser[:4]))
    return "\n".join(lines)


# ------------------------- runtime cross-check -------------------------

def crosscheck_rank_timing(report, timings, *,
                           field: Optional[int] = None) -> dict:
    """Close the loop between the AOT roofline and what the step
    actually measured: `timings` is the gathered (n_ranks, k) matrix
    the rank-timing plane (`TraceConfig(rank_timing=True)`) returns.
    `field` defaults to the `allreduce_duration_s` column, resolved
    from `trace.TIMING_FIELDS` by NAME so a column reorder there can't
    silently repoint this at step time.  Returns the measured median
    across ranks,
    the report's predicted comm seconds, and their ratio — a measured/
    predicted ratio far above ~1.5 means the table bandwidth is
    optimistic for this topology (or the collective serialized behind
    something the roofline can't see); far below 1 means the table
    under-quotes the links and should be refreshed with an override."""
    import numpy as np

    if field is None:
        from apex_tpu.monitor.trace import TIMING_FIELDS
        field = TIMING_FIELDS.index("allreduce_duration_s")
    r = report.to_dict() if hasattr(report, "to_dict") else dict(report)
    t = np.asarray(timings, np.float64)
    if t.ndim == 1:
        col = t  # a bare per-rank allreduce-duration vector
    elif field < t.shape[1]:
        col = t[:, field]
    else:
        # never silently repoint at another column (step time would
        # inflate the ratio and tell the operator the table is wrong)
        raise ValueError(
            f"timings has {t.shape[1]} column(s); column {field} "
            "(allreduce_duration_s) is missing — pass the full "
            "TIMING_FIELDS matrix or a 1-D allreduce vector")
    measured = float(np.median(col))
    predicted = float(r.get("predicted_comm_s") or 0.0)
    return {
        "measured_s": measured,
        "predicted_comm_s": predicted,
        "ratio": (measured / predicted) if predicted > 0 else None,
        "n_ranks": int(col.shape[0]),
    }
