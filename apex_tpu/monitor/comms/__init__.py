"""apex_tpu.monitor.comms — the collective & overlap observatory
(ISSUE 7).

The communications half of the compile observatory: where
`monitor.compile` audits what the compiled step HOLDS (HBM budget) and
COMPUTES (flops), this audits what it says over the interconnect —
the plane ZeRO-3 and the TP compute/collective overlap work (ROADMAP
items 1-2) live or die by.  Three cooperating pieces:

  * hlo      — optimized-HLO text parsing (instructions, replica
               groups, async start/done pairing, dot-FLOP accounting);
               no jax import, testable on committed fixtures.
  * roofline — `DEVICE_ICI_BANDWIDTH` (the sibling of
               `flops.DEVICE_BF16_PEAKS`) + the ring-algorithm cost
               formulas that price each collective analytically.
  * report   — `comms_report(step, args) -> CommsReport`: the
               inventory, the per-collective overlap classification
               (dot flops scheduled between an async collective's
               start and done), the comm-bound verdict, the
               serialized-collective gate findings, and the runtime
               cross-check against the rank-timing plane.

Wiring: `monitor.analyze_step(..., comms=True)` attaches the report to
the `CompileReport` (and thereby the flight-recorder crash dump);
`scripts/comms_probe.py` is the CI gate; `scripts/gpt_anatomy.py comms`
prints the tables for the bench configs.  See docs/observability.md
"Reading the comms table".
"""

from apex_tpu.monitor.comms import hlo  # noqa: F401
from apex_tpu.monitor.comms.report import (  # noqa: F401
    COMMS_SCHEMA_VERSION,
    OVERLAP_BYTES_FLOOR,
    Collective,
    CommsReport,
    apply_allowlist,
    comms_report,
    crosscheck_rank_timing,
    inventory_from_hlo,
    parse_allowlist,
    render_comms_table,
    serialized_collectives,
    validate_comms_report,
)
from apex_tpu.monitor.comms.roofline import (  # noqa: F401
    DEVICE_ICI_BANDWIDTH,
    V5E_ICI_BYTES_PER_S,
    collective_seconds,
    device_link_bandwidth,
)
