"""Optimized-HLO text parsing for the comms observatory.

The collective inventory reads the POST-optimization HLO module
(`step.lower(*args).compile().as_text()`) — the program XLA actually
schedules — not stablehlo: collective combining, async conversion, and
the instruction schedule only exist after optimization, and those are
exactly what the overlap analysis is about.

This module is a text parser, deliberately: `as_text()` is the one
stable, backend-independent view of the optimized module that every
jaxlib this repo supports exposes (the in-memory
`hlo_modules()`/buffer-assignment APIs drift per version).  It parses
only what the inventory needs —

  * computations and their instruction lists, in printed order (for a
    scheduled module the printed order of the entry computation IS the
    schedule; for an unscheduled one it is a topological order, which
    the analyzer reports as such via `async_supported=False`),
  * per-instruction: name, opcode, result/operand shapes,
    `replica_groups` (both the explicit `{{0,1},{2,3}}` and the iota
    `[2,2]<=[4]` forms), `source_target_pairs`, `channel_id`,
    `calls=`/`to_apply=` edges, and the `metadata={op_name=...}` hint,
  * dot FLOPs per computation (2 * prod(output) * prod(contracted lhs
    dims)), folded transitively through fusion/call edges so the
    overlap window can price the compute scheduled between an async
    collective's start and done.  While/conditional bodies count ONCE
    (trip counts are runtime values) — documented undercount, fine for
    a "did anything overlap at all" classification.

Nothing here imports jax — the parser is testable on committed HLO
text fixtures.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

# HLO primitive element type -> bytes.  token/opaque/tuple contribute 0.
_ITEMSIZE = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e4m3": 1, "f8e5m2": 1,
    "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# the five collective families the inventory tracks (ISSUE 7)
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")


def itemsize(dtype: str) -> int:
    return _ITEMSIZE.get(dtype, 0)


@dataclasses.dataclass
class Shape:
    dtype: str
    dims: Tuple[int, ...]

    @property
    def elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def bytes(self) -> int:
        return self.elements * itemsize(self.dtype)


@dataclasses.dataclass
class Instruction:
    name: str
    opcode: str
    shapes: List[Shape]            # result leaf shapes (tuple flattened)
    operand_shapes: List[Shape]
    operand_names: List[str]
    replica_groups: Optional[List[List[int]]]
    source_target_pairs: Optional[List[Tuple[int, int]]]
    channel_id: Optional[int]
    called: List[str]              # calls= / to_apply= / body= targets
    op_name: str                   # metadata op_name hint ("" if none)
    index: int                     # position within its computation
    lhs_contracting: Tuple[int, ...] = ()   # dot contracting dims


@dataclasses.dataclass
class Computation:
    name: str
    is_entry: bool
    instructions: List[Instruction]


_SHAPE_RE = re.compile(r"([a-z]\w*)\[([0-9,]*)\]")
_ATTR_CALL_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations)="
    r"\{?%?([\w.\-]+)")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_OPNAME_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_GROUPS_RE = re.compile(r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|"
                        r"\{\}|\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=(\{\{[^=]*?\}\})")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s+=\s+(.*)$")
# the param list may hold tuple TYPES with nested parens (while/cond
# bodies take the loop carry as one tuple param: `(param.7: (s32[],
# f32[2,8]))`), so the group must span to the line's LAST `)` —
# `[^)]*` would stop at the first and drop every loop body from the
# parse, collectives included
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)(?:\s+\(.*\))?"
                      r"\s*(?:->.*)?\{\s*$")


def _parse_shapes(text: str) -> List[Shape]:
    """Every `dtype[d,d,...]` shape literal in `text`, in order."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _ITEMSIZE and dtype not in ("token", "opaque"):
            continue
        out.append(Shape(dtype=dtype,
                         dims=tuple(int(d) for d in dims.split(",") if d)))
    return out


def _split_result_op(rest: str) -> Tuple[str, str, str]:
    """Split `<result-type> <opcode>(<operands>), attrs` into
    (result_type_text, opcode, tail).  The result type may be a tuple
    `(f32[2]{0}, u32[])` containing spaces — balance parens."""
    rest = rest.strip()
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    result = rest[: i + 1]
                    tail = rest[i + 1:].lstrip()
                    break
        else:
            return rest, "", ""
    else:
        sp = rest.find(" ")
        if sp < 0:
            return rest, "", ""
        result, tail = rest[:sp], rest[sp + 1:]
    m = re.match(r"([\w\-]+)\(", tail)
    if not m:
        return result, "", ""
    opcode = m.group(1)
    return result, opcode, tail[len(opcode):]


def _operand_span(tail: str) -> str:
    """The text inside the opcode's balanced `(...)` operand list."""
    depth = 0
    for i, ch in enumerate(tail):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return tail[1:i]
    return tail[1:] if tail.startswith("(") else tail


def _parse_replica_groups(text: str) -> Optional[List[List[int]]]:
    m = _GROUPS_RE.search(text)
    if not m:
        return None
    spec = m.group(1)
    if spec.startswith("{"):
        groups = []
        for g in re.findall(r"\{([0-9,\s]*)\}", spec):
            ids = [int(x) for x in g.replace(" ", "").split(",") if x]
            if ids:
                groups.append(ids)
        return groups
    # iota form: [G,S]<=[d0,d1,...](T(p...))? — ids are
    # arange(prod(d)).reshape(d).transpose(p).reshape(G, S)
    m2 = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?",
                  spec)
    if not m2:
        return None
    gshape = [int(x) for x in m2.group(1).split(",")]
    rshape = [int(x) for x in m2.group(2).split(",")]
    perm = ([int(x) for x in m2.group(3).split(",")]
            if m2.group(3) else list(range(len(rshape))))
    total = 1
    for d in rshape:
        total *= d
    ids = list(range(total))

    def coord(i):
        c = []
        for d in reversed(rshape):
            c.append(i % d)
            i //= d
        return list(reversed(c))

    # transpose: position of id in the permuted layout
    strides = [0] * len(rshape)
    acc = 1
    pshape = [rshape[p] for p in perm]
    for j in range(len(pshape) - 1, -1, -1):
        strides[j] = acc
        acc *= pshape[j]
    flat = [0] * total
    for i in ids:
        c = coord(i)
        pos = sum(c[p] * strides[j] for j, p in enumerate(perm))
        flat[pos] = i
    g, s = gshape if len(gshape) == 2 else (1, gshape[0])
    return [flat[i * s:(i + 1) * s] for i in range(g)]


def _split_top_level(text: str) -> List[str]:
    """Split an operand list on top-level commas (commas inside shape
    layouts `{1,0}`, tuple types `(f32[2], u32[])`, and dims `[4,4]`
    don't count)."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(text[start:i])
            start = i + 1
    tail = text[start:]
    if tail.strip():
        parts.append(tail)
    return parts


_NAME_TOKEN_RE = re.compile(r"%?([\w.\-]+)\s*$")


def _operand_names(operands: str) -> List[str]:
    """Operand instruction names: the trailing token of each top-level
    operand.  Optimized dumps spell `f32[64]{0} %conv.4`; pre-opt
    dumps (`as_text(dialect="hlo")`) spell a bare `conv.4` — both end
    in the name."""
    names = []
    for part in _split_top_level(operands):
        m = _NAME_TOKEN_RE.search(part.strip())
        if m:
            names.append(m.group(1))
    return names


def _parse_pairs(text: str) -> Optional[List[Tuple[int, int]]]:
    m = _PAIRS_RE.search(text)
    if not m:
        return None
    return [tuple(int(x) for x in p.split(","))
            for p in re.findall(r"\{(\d+,\d+)\}", m.group(1))]


_REPLICA_COUNT_RE = re.compile(r"replica_count=(\d+)")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def parse_world_size(hlo_text: str) -> Optional[int]:
    """Total participant count from the HloModule header —
    `replica_count * num_partitions` (SPMD-partitioned jit programs
    carry num_partitions; pmap-style ones carry replica_count).  None
    when the header names neither.  Needed because
    `replica_groups={}` means ONE GROUP OF ALL PARTICIPANTS in HLO,
    and the group list alone can't say how many that is."""
    head = hlo_text.split("\n", 1)[0]
    r = _REPLICA_COUNT_RE.search(head)
    p = _NUM_PARTITIONS_RE.search(head)
    if r is None and p is None:
        return None
    return (int(r.group(1)) if r else 1) * (int(p.group(1)) if p else 1)


def parse_module(hlo_text: str) -> List[Computation]:
    """Parse an optimized-HLO module dump into computations."""
    comps: List[Computation] = []
    current: Optional[Computation] = None
    producers: Dict[str, Instruction] = {}   # name -> instr, per comp
    for raw in hlo_text.splitlines():
        line = raw.rstrip()
        if current is None:
            # optimized dumps print `%name (params...) -> type {`;
            # pre-optimization dumps (`as_text(dialect="hlo")`) print
            # a bare `name {` — accept both, let _COMP_RE decide
            if line.endswith("{") and not line.startswith("HloModule"):
                m = _COMP_RE.match(line.strip())
                if m:
                    current = Computation(name=m.group(2),
                                          is_entry=bool(m.group(1)),
                                          instructions=[])
                    producers = {}
            continue
        if line.strip() == "}":
            comps.append(current)
            current = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rest = m.group(1), m.group(2)
        result, opcode, tail = _split_result_op(rest)
        if not opcode:
            continue
        operands = _operand_span(tail)
        attrs = tail[len(operands) + 2:] if operands else tail
        operand_names = _operand_names(operands)
        operand_shapes = _parse_shapes(operands)
        if not operand_shapes and operand_names:
            # pre-optimization dumps don't repeat operand types inline
            # — resolve them from the producing instructions (HLO is
            # printed in def order within a computation)
            for ref in operand_names:
                producer = producers.get(ref)
                if producer is not None:
                    operand_shapes.extend(producer.shapes)
        current.instructions.append(Instruction(
            name=name, opcode=opcode,
            shapes=_parse_shapes(result),
            operand_shapes=operand_shapes,
            operand_names=operand_names,
            replica_groups=_parse_replica_groups(attrs),
            source_target_pairs=_parse_pairs(attrs),
            channel_id=(int(c.group(1))
                        if (c := _CHANNEL_RE.search(attrs)) else None),
            called=_ATTR_CALL_RE.findall(tail),
            op_name=(o.group(1)
                     if (o := _OPNAME_RE.search(attrs)) else ""),
            index=len(current.instructions),
            lhs_contracting=(tuple(
                int(x) for x in k.group(1).split(",") if x)
                if (k := _LHS_CONTRACT_RE.search(attrs)) else ())))
        producers[name] = current.instructions[-1]
    return comps


def _dot_flops(instr: Instruction) -> float:
    """2 * prod(output dims) * prod(lhs contracted dims) — exact for
    batched dots too (batch dims live in the output product)."""
    if instr.opcode != "dot" or not instr.shapes \
            or not instr.operand_shapes:
        return 0.0
    out = instr.shapes[0].elements
    lhs = instr.operand_shapes[0]
    k = 1
    for d in instr.lhs_contracting:
        if 0 <= d < len(lhs.dims):
            k *= lhs.dims[d]
    return 2.0 * out * k


def computation_flops(comps: Sequence[Computation]) -> Dict[str, float]:
    """Per-computation dot FLOPs, folded transitively through
    fusion/call/while edges (each called body counted once)."""
    by_name = {c.name: c for c in comps}
    memo: Dict[str, float] = {}

    def visit(name: str, stack: frozenset) -> float:
        if name in memo:
            return memo[name]
        comp = by_name.get(name)
        if comp is None or name in stack:
            return 0.0
        total = 0.0
        for instr in comp.instructions:
            total += _dot_flops(instr)
            for callee in instr.called:
                total += visit(callee, stack | {name})
        memo[name] = total
        return total

    for c in comps:
        visit(c.name, frozenset())
    return memo


def instruction_flops(instr: Instruction,
                      comp_flops: Dict[str, float]) -> float:
    """Dot FLOPs attributable to one scheduled instruction (its own
    dot, plus everything inside the computations it calls)."""
    total = _dot_flops(instr)
    for callee in instr.called:
        total += comp_flops.get(callee, 0.0)
    return total
