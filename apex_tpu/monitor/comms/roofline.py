"""ICI link-bandwidth table + analytic collective pricing.

The comms sibling of `monitor.flops.DEVICE_BF16_PEAKS`: a per-device-
generation interconnect bandwidth table and the standard ring-algorithm
cost formulas, so every collective in the inventory gets a predicted
wall-clock BEFORE the step ever runs — the number the overlap analysis
and the comm-bound/compute-bound verdict divide by.

Bandwidth convention: BYTES/SECOND of aggregate per-chip ICI
bandwidth, from the public TPU spec sheets (quoted there in Gbps of
total interchip bandwidth per chip; /8 for bytes).  These are LINK
peaks, not achieved collective bandwidth — real rings see ~70-90% of
link peak depending on topology (2D/3D torus wraparound, slice shape)
and message size.  Treat the predictions as a roofline: a collective
predicted at 2 ms will not run in 1 ms, and a measured 10 ms against a
2 ms prediction is a finding.  On real hardware, refresh against a
measured number via `device_link_bandwidth(override=...)` and the
rank-timing cross-check (`crosscheck_rank_timing`) — docs/
observability.md "Reading the comms table" says where to measure.

Ring-algorithm cost model over n participants for D bytes of *input*
(the operand bytes the inventory already extracted):

    all-reduce          2 (n-1)/n * D / bw     (reduce-scatter + all-gather phases)
    reduce-scatter        (n-1)/n * D / bw     (D = full un-scattered input)
    all-gather            (n-1)   * D / bw     (D = this rank's shard; output = n*D)
    all-to-all            (n-1)/n * D / bw
    collective-permute              D / bw     (one hop, full operand)

n == 1 collectives (a tp collective on a tp=1 mesh) cost 0 — degenerate
by construction, XLA compiles most of them away anyway.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.monitor.flops import _normalize_device_kind

# v5e aggregate ICI per chip — the fallback for unknown kinds (CPU test
# runs included), mirroring flops.V5E_BF16_PEAK's role: predictions on
# unknown backends are stable and clearly table-priced, never zero.
V5E_ICI_BYTES_PER_S = 200e9  # 1600 Gbps

# normalized device generation -> aggregate per-chip ICI bytes/s.
# Sources: public TPU spec sheets (interchip interconnect bandwidth per
# chip, all links): v2 496 Gbps, v3 656 Gbps, v4 2400 Gbps, v5e 1600
# Gbps, v5p 4800 Gbps, v6e 3584 Gbps.
DEVICE_ICI_BANDWIDTH = {
    "v2": 62e9,
    "v3": 82e9,
    "v4": 300e9,
    "v5e": 200e9,
    "v5p": 600e9,
    "v6e": 448e9,
}


def resolve_link_bandwidth(device_kind: Optional[str], *,
                           override: Optional[float] = None,
                           default: float = V5E_ICI_BYTES_PER_S,
                           ) -> "tuple[float, str]":
    """(bytes/s, source) with source one of "override" /
    "table:<kind>" / "default" — the single resolution path both
    `device_link_bandwidth` and `comms_report` price against, so a
    new device generation lands in one table."""
    if override is not None:
        return float(override), "override"
    norm = _normalize_device_kind(str(device_kind or ""))
    if norm in DEVICE_ICI_BANDWIDTH:
        return DEVICE_ICI_BANDWIDTH[norm], f"table:{norm}"
    return float(default), "default"


def device_link_bandwidth(device_kind: Optional[str] = None, *,
                          override: Optional[float] = None,
                          default: float = V5E_ICI_BYTES_PER_S) -> float:
    """Aggregate per-chip ICI bytes/s, resolved from the device kind.

    Same contract as `flops.device_peak_flops`: `override` wins
    outright (a measured ring bandwidth, a sliced topology);
    device_kind=None reads `jax.devices()[0].device_kind`; unknown
    kinds — CPU included — fall back to the v5e number so CPU-run
    predictions are stable table prices, not zeros."""
    if override is None and device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return default
    return resolve_link_bandwidth(device_kind, override=override,
                                  default=default)[0]


def collective_seconds(kind: str, operand_bytes: int, group_size: int,
                       bandwidth: float) -> float:
    """Predicted ring-algorithm seconds for one collective (see module
    docstring for the per-kind formulas and what D means for each)."""
    n, d = int(group_size), float(operand_bytes)
    if n <= 1 or d <= 0 or bandwidth <= 0:
        return 0.0
    if kind == "all-reduce":
        return 2.0 * (n - 1) / n * d / bandwidth
    if kind == "reduce-scatter":
        return (n - 1) / n * d / bandwidth
    if kind == "all-gather":
        return (n - 1) * d / bandwidth
    if kind == "all-to-all":
        return (n - 1) / n * d / bandwidth
    if kind == "collective-permute":
        return d / bandwidth
    return d / bandwidth  # unknown kind: one full traversal
