"""apex_tpu.monitor — on-device training telemetry (ISSUE 2).

Three layers:

  * metrics  — `MetricsState`, a tiny all-scalar pytree that rides
               INSIDE jitted train steps (no host syncs to collect);
               the hot paths (`parallel.ddp.make_train_step`,
               `schedules.forward_backward_no_pipelining`,
               `amp.FP16_Optimizer.step`) thread it via their optional
               `metrics=` hooks
  * logger   — host-side `MetricsLogger` + sinks (JSONL / console /
               SummaryWriter adapter) + derived rates (step time,
               tokens/sec, MFU from `monitor.flops` accounting)
  * profiler — `profile_capture(step_range)`: jax.profiler trace armed
               over a chosen step window
  * trace    — the numerics flight recorder (ISSUE 4): per-layer stat
               taps with NaN/overflow provenance, cross-rank timing +
               straggler detection, and the crash-dump ring buffer
               (`monitor.trace` subpackage)
  * compile  — the compile & HBM observatory (ISSUE 5): AOT memory/
               cost audit (`analyze_step` -> `CompileReport`, HBM
               budget table, donation + flops cross-checks), the
               `RecompileSentry`, and device-memory watermarks + OOM
               forensics (`monitor.compile` subpackage)
  * comms    — the collective & overlap observatory (ISSUE 7):
               optimized-HLO collective inventory
               (`comms_report` -> `CommsReport`), async start/done
               overlap classification, and the per-device-kind ICI
               roofline (`monitor.comms` subpackage; CI-gated by
               `scripts/comms_probe.py`)
  * timeline — the runtime timeline observatory (ISSUE 15): parses
               the profiler traces `ProfileCapture` writes into a
               MEASURED per-step anatomy (`analyze_trace` ->
               `TimelineReport`: device-busy/host-gap, category
               attribution, per-collective measured overlap) and
               cross-checks the comms plane's predictions
               (`crosscheck_comms`; CI-gated by
               `scripts/timeline_probe.py`)

See docs/observability.md for the JSONL schema and recipes, and
examples/train_with_monitor.py for the end-to-end loop.
"""

from apex_tpu.monitor import flops  # noqa: F401
from apex_tpu.monitor.flops import (  # noqa: F401
    DEVICE_BF16_PEAKS,
    V5E_BF16_PEAK,
    bert_step_flops,
    device_peak_flops,
    gpt_step_flops,
    mfu,
    transformer_step_flops,
)
from apex_tpu.monitor import compile  # noqa: F401,A004 — subpackage
from apex_tpu.monitor.compile import (  # noqa: F401
    CompileReport,
    RecompileSentry,
    analyze_step,
    device_memory_stats,
    render_budget_table,
)
from apex_tpu.monitor import comms  # noqa: F401
from apex_tpu.monitor.comms import (  # noqa: F401
    DEVICE_ICI_BANDWIDTH,
    CommsReport,
    comms_report,
    device_link_bandwidth,
    render_comms_table,
)
from apex_tpu.monitor import timeline  # noqa: F401
from apex_tpu.monitor.timeline import (  # noqa: F401
    TIMELINE_SCHEMA_VERSION,
    TimelineReport,
    TraceParseError,
    analyze_trace,
    crosscheck_comms,
    render_timeline_table,
    validate_timeline_report,
)
from apex_tpu.monitor.logger import (  # noqa: F401
    SCHEMA,
    SCHEMA_VERSION,
    MetricsLogger,
    validate_record,
    validate_records,
)
from apex_tpu.monitor.metrics import (  # noqa: F401
    MetricsConfig,
    MetricsState,
    global_norm,
    infer_tokens_per_step,
    init_metrics,
    update_metrics,
)
from apex_tpu.monitor.profiler import (  # noqa: F401
    ProfileCapture,
    ProfileStepReentryError,
    profile_capture,
)
from apex_tpu.monitor.sinks import (  # noqa: F401
    ConsoleSink,
    JSONLSink,
    MetricSink,
    ScalarWriter,
    SummaryWriterSink,
    sanitize_json_floats,
)
from apex_tpu.monitor import trace  # noqa: F401
from apex_tpu.monitor.trace import (  # noqa: F401
    FlightRecorder,
    StragglerDetector,
    TapState,
    TraceConfig,
)
