"""Runtime-timeline CI gate for the flagship train steps (ISSUE 15).

usage:
  python scripts/timeline_probe.py [targets...]  # default: gpt gpt_zero2
  python scripts/timeline_probe.py --selftest    # fixture drift gate +
                                                 # seeded negative controls
  python scripts/timeline_probe.py --steps N     # capture window (default 3)
  python scripts/timeline_probe.py --json        # machine-readable reports
  python scripts/timeline_probe.py --backend tpu # device truth on hardware

Where `comms_probe.py` gates what the schedule is PREDICTED to do,
this probe gates the measured plane end to end: build each flagship
step (the EXACT bench programs; CPU smoke configs substitute, same
build path), warm it up, arm a `monitor.ProfileCapture` over N steady
steps, EXECUTE them, and run `monitor.timeline.analyze_trace` on the
trace the profiler wrote.  Structure asserts (nonzero exit on any):

  * the trace parsed and carries device events (`n_device_events > 0`
    — a capture that saw only python is a broken profiler wiring),
  * the step count matches the capture window (N annotated steps in,
    N step anatomies out),
  * per-category wall-time fractions sum to ~1 (the attribution
    dropped or double-counted nothing),
  * the report round-trips its JSON schema (`validate_timeline_
    report`), the `timeline_probe --selftest` drift contract.

On the ZeRO-2 dp target the probe also closes the predicted-vs-
measured loop: `crosscheck_comms(timeline, comms_report)` must
produce a row for every counted collective — every expected-overlap
collective included — and on a measurable backend (TPU) a DIVERGES
row or a measured-serialized collective fails the gate.  On CPU the
backend emits sync collectives through an emulated-device thunk pool,
so the overlap plane is honestly UNMEASURABLE (asserted, printed,
PASS) — exactly the comms_probe convention; the parser/anatomy layer
is still fully exercised.

`--selftest` validates + renders the committed fixture
(scripts/timeline_fixture.json), checks its seeded MEASURED-SERIALIZED
collective is still flagged, and runs two seeded in-code controls: an
idle-heavy trace that must trip the DEVICE IDLE verdict BY NAME (the
negative control) and a busy trace that must not.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# scripts/ itself, for the shared gpt_anatomy/comms_probe builders
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

# resolve the backend BEFORE the first jax import (argv peek, the
# comms_probe convention): the probe EXECUTES steps, so `--backend
# tpu` is the operator's explicit ask for device truth
if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the ZeRO-2 target needs a dp axis: on the CPU backend force a 2-way
# virtual mesh (must precede the first jax import, conftest-style)
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(_HERE, "timeline_fixture.json")

# markers the fixture rendering must contain; losing one means the
# renderer no longer tells the story the fixture encodes
_FIXTURE_MARKERS = (
    "=== timeline: fixture-step ===",
    "| step |",
    "aggregate: device busy",
    "collective",
    "collective-permute",
    "**SER**",
    "MEASURED-SERIALIZED",
)

# the seeded serialized-chunk negative control (ISSUE 18): one chunk
# of the fixture's chunked-TP ring pair is seeded MEASURED-SERIALIZED
# and must stay flagged BY NAME — a renderer or analyzer change that
# stops surfacing a serialized ring hop would blind the measured gate
# to exactly the regression chunked overlap exists to prevent
_SEEDED_SERIALIZED_CHUNK = "collective-permute.8"


# ------------------------- seeded control traces -------------------------

def _seeded_trace(busy_frac: float, n_steps: int = 3) -> dict:
    """A deterministic TPU-style trace: per step a fixed wall window
    with device ops covering `busy_frac` of it — the in-code seed for
    the selftest's idle/busy controls (no profiler, no backend)."""
    wall = 10_000.0  # us per step
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
    ]
    for i in range(n_steps):
        t0 = i * wall
        events.append({"ph": "X", "pid": 9, "tid": 1,
                       "name": "train-step", "ts": t0, "dur": wall,
                       "args": {"step_num": str(i)}})
        events.append({"ph": "X", "pid": 1, "tid": 10, "name": "fusion.1",
                       "ts": t0 + 10.0, "dur": busy_frac * wall,
                       "args": {"hlo_op": "fusion.1"}})
    return {"traceEvents": events}


def selftest() -> int:
    from apex_tpu.monitor import timeline

    with open(FIXTURE) as f:
        rep = json.load(f)
    try:
        timeline.validate_timeline_report(rep)
        text = timeline.render_timeline_table(rep, label="fixture-step")
    except ValueError as e:
        print(f"timeline_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? update scripts/timeline_fixture.json "
              "to the new schema)", file=sys.stderr)
        return 1
    missing = [m for m in _FIXTURE_MARKERS if m not in text]
    if missing:
        print(text)
        print(f"timeline_probe --selftest: rendering lost expected "
              f"markers: {missing}", file=sys.stderr)
        return 1
    ser = [c for c in rep["collectives"] if c.get("serialized")]
    if not ser or rep.get("measured_overlap_ok") is not False:
        print("timeline_probe --selftest: the fixture's seeded "
              "measured-serialized collective is no longer flagged — "
              "the gate is blind", file=sys.stderr)
        return 1
    if _SEEDED_SERIALIZED_CHUNK not in {c["name"] for c in ser}:
        print("timeline_probe --selftest: the seeded serialized ring "
              f"CHUNK ({_SEEDED_SERIALIZED_CHUNK}) is no longer "
              "flagged — the measured gate is blind to chunked-"
              "overlap regressions", file=sys.stderr)
        return 1
    if _SEEDED_SERIALIZED_CHUNK not in text:
        print("timeline_probe --selftest: the serialized ring chunk "
              "vanished from the rendering", file=sys.stderr)
        return 1
    print(text)

    # negative control, BY NAME: a seeded idle-heavy trace (device
    # busy 10% of each step) must trip the DEVICE IDLE verdict
    idle = timeline.analyze_trace(_seeded_trace(busy_frac=0.1))
    idle_text = timeline.render_timeline_table(idle, label="idle-seed")
    if (idle.device_busy_fraction >= timeline.IDLE_BUSY_FLOOR
            or "DEVICE IDLE" not in idle_text):
        print(idle_text)
        print("timeline_probe --selftest: the seeded idle-heavy trace "
              "did NOT trip the DEVICE IDLE verdict — the negative "
              "control is dead", file=sys.stderr)
        return 1
    print(f"negative control: idle-heavy seed (busy "
          f"{idle.device_busy_fraction:.2f}) flagged DEVICE IDLE — OK")
    # ...and a busy trace must NOT trip it (the verdict discriminates)
    busy = timeline.analyze_trace(_seeded_trace(busy_frac=0.9))
    if "DEVICE IDLE" in timeline.render_timeline_table(busy):
        print("timeline_probe --selftest: the busy seed tripped "
              "DEVICE IDLE — the verdict lost its floor",
              file=sys.stderr)
        return 1
    print("timeline_probe --selftest: OK")
    return 0


# ------------------------------ full probe ------------------------------

def _materialize(args):
    """Real zero-filled arrays for the builders' ShapeDtypeStructs —
    the probe EXECUTES the step (token id 0 is valid in every
    config)."""
    import jax
    import jax.numpy as jnp

    def mat(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return jnp.zeros(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(
        mat, args, is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))


def _build(target, on_tpu):
    """(step, abstract_args, runner) for one probe target.  The
    abstract args feed `comms_report` (AOT, the predicted side); the
    runner executes one step on materialized state, rebinding donated
    buffers."""
    if target == "gpt_zero2":
        import comms_probe

        step, (state, scaler, batch) = comms_probe._build_gpt_zero2(
            on_tpu)
        live = [_materialize(state), scaler, _materialize(batch)]

        def run():
            out = step(live[0], live[1], live[2])
            live[0], live[1] = out[0], out[1]
            return out[2]

        return step, (state, scaler, batch), run
    if target == "gpt_tp_overlap":
        # the chunked-TP flagship (ISSUE 18): the ppermute-ring /
        # chunked-reduce program whose measured per-hop overlap the
        # crosscheck below judges against the AOT prediction
        import comms_probe

        step, (opt_state, tokens, labels) = \
            comms_probe._build_gpt_tp_overlap(on_tpu)
        live = [_materialize(opt_state), _materialize(tokens),
                _materialize(labels)]

        def run():
            out = step(live[0], live[1], live[2])
            live[0] = out[0]
            return out[1]

        return step, (opt_state, tokens, labels), run
    import gpt_anatomy

    import jax

    key = {"gpt": "350m", "bert": "bert"}[target]
    _, step, (opt_state, tokens, labels), _ = \
        gpt_anatomy._build_bench_step(key, on_tpu, mode="comms")
    live = [opt_state, _materialize(tokens), _materialize(labels)]

    def run():
        out = step(live[0], live[1], live[2])
        live[0] = out[0]
        return out[1]

    return step, (opt_state, tokens, labels), run


TARGETS = ("gpt", "gpt_zero2", "bert", "gpt_tp_overlap")
DEFAULT_TARGETS = ("gpt", "gpt_zero2", "gpt_tp_overlap")


def _probe_target(target, n_steps, logdir, as_json) -> int:
    import jax

    from apex_tpu import monitor
    from apex_tpu.monitor import comms as comms_lib
    from apex_tpu.monitor import timeline

    on_tpu = jax.default_backend() not in ("cpu",)
    step, abstract_args, run = _build(target, on_tpu)

    # two warmups absorb the compile (+ the donated-layout second
    # compile, the bench.py rule) so the capture holds STEADY steps
    for _ in range(2):
        jax.block_until_ready(run())
    cap = monitor.profile_capture(
        range(0, n_steps), logdir=os.path.join(logdir, target))
    try:
        for i in range(n_steps):
            with cap.step(i):
                jax.block_until_ready(run())
    finally:
        cap.close()  # a raise mid-capture must stop the profiler
        # (a leaked open trace would poison the NEXT target's capture)

    path = cap.trace_path()
    if path is None:
        print(f"timeline_probe {target}: FAIL — the capture window "
              "fired but no trace.json.gz was written", file=sys.stderr)
        return 1
    rep = timeline.analyze_trace(path)

    rc = 0
    # structure asserts — the gate proper
    if rep.n_device_events <= 0:
        print(f"timeline_probe {target}: FAIL — trace parsed to ZERO "
              "device events", file=sys.stderr)
        rc = 1
    if len(rep.steps) != n_steps:
        print(f"timeline_probe {target}: FAIL — captured {n_steps} "
              f"steps but the anatomy holds {len(rep.steps)}",
              file=sys.stderr)
        rc = 1
    frac_sum = sum(rep.category_fractions.values())
    if rep.n_device_events > 0 and abs(frac_sum - 1.0) > 1e-6:
        print(f"timeline_probe {target}: FAIL — category fractions "
              f"sum to {frac_sum}, not ~1", file=sys.stderr)
        rc = 1
    try:
        timeline.validate_timeline_report(
            json.loads(json.dumps(rep.to_dict())))
    except ValueError as e:
        print(f"timeline_probe {target}: FAIL — schema round-trip: "
              f"{e}", file=sys.stderr)
        rc = 1
    # backend honesty: a CPU capture must never fake the overlap plane
    if not on_tpu and (rep.overlap_measurable
                       or rep.measured_overlap_ok is not None):
        print(f"timeline_probe {target}: FAIL — CPU capture claims a "
              "measurable overlap plane", file=sys.stderr)
        rc = 1
    if rep.overlap_measurable and rep.measured_overlap_ok is False:
        print(f"timeline_probe {target}: FAIL — measured-serialized "
              "collective(s) in the schedule", file=sys.stderr)
        rc = 1

    xc = None
    if target in ("gpt_zero2", "gpt_tp_overlap"):
        # the predicted-vs-measured loop: one row per counted
        # collective of the AOT report, expected-overlap ones
        # included.  On the chunked-TP target this is where the
        # chunk-count-many ring hops meet their measured spans — the
        # name-prefix grouping in crosscheck_comms keeps a chunk's
        # span with its own logical collective when the trace
        # renumbers instances
        crep = comms_lib.comms_report(step, abstract_args)
        xc = timeline.crosscheck_comms(rep, crep)
        n_counted = sum(crep.to_dict()["counts"].values())
        if len(xc["rows"]) != n_counted:
            print(f"timeline_probe {target}: FAIL — crosscheck has "
                  f"{len(xc['rows'])} rows for {n_counted} counted "
                  "collective(s)", file=sys.stderr)
            rc = 1
        missing = [r["name"] for r in xc["rows"]
                   if r["expected_overlap"]
                   and r["measured_overlap_fraction"] is None
                   and rep.overlap_measurable]
        if missing:
            print(f"timeline_probe {target}: FAIL — expected-overlap "
                  f"collective(s) unmatched in the trace: {missing}",
                  file=sys.stderr)
            rc = 1
        if rep.overlap_measurable and not xc["ok"]:
            print(f"timeline_probe {target}: FAIL — predicted vs "
                  f"measured overlap DIVERGES on "
                  f"{xc['n_diverge']} collective(s)", file=sys.stderr)
            rc = 1

    if as_json:
        print(json.dumps({"target": target, "report": rep.to_dict(),
                          "crosscheck": xc, "ok": rc == 0}))
    else:
        print(timeline.render_timeline_table(rep, label=target))
        if xc is not None:
            print(timeline.render_crosscheck(xc, label=target))
        if not rep.overlap_measurable:
            print("overlap plane: UNMEASURABLE on this backend "
                  "(honest) — gate judges structure only")
        print(f"timeline_probe {target}: "
              f"{'PASS' if rc == 0 else 'FAIL'}")
        print()
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(
        description="runtime-timeline CI gate for the flagship steps")
    ap.add_argument("targets", nargs="*",
                    help=f"subset of {sorted(TARGETS)} "
                         f"(default: {list(DEFAULT_TARGETS)})")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate + seeded idle/busy "
                         "controls; exit 1 on drift")
    ap.add_argument("--steps", type=int, default=3,
                    help="steady steps to capture (default 3)")
    ap.add_argument("--logdir", default=None,
                    help="keep traces here (default: a temp dir)")
    ap.add_argument("--backend", metavar="NAME", default=None,
                    help="JAX_PLATFORMS for the run (e.g. tpu); "
                         "consumed before the first jax import by the "
                         "argv peek above — registered here so "
                         "argparse accepts it")
    ap.add_argument("--json", action="store_true",
                    help="print JSON instead of tables")
    args = ap.parse_args()
    if args.selftest:
        return selftest()

    targets = args.targets or list(DEFAULT_TARGETS)
    bad = [t for t in targets if t not in TARGETS]
    if bad:
        ap.error(f"unknown target(s) {bad}; choices: {sorted(TARGETS)}")

    logdir = args.logdir or tempfile.mkdtemp(prefix="timeline_probe_")

    from apex_tpu.parallel import mesh as M

    rc = 0
    for t in targets:
        rc |= _probe_target(t, args.steps, logdir, args.json)
        M.destroy_model_parallel()
    if not args.json:
        verdict = "PASS" if rc == 0 else "FAIL"
        print(f"timeline_probe: {len(targets)} target(s), {verdict}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
