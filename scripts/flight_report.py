"""Render a numerics flight-recorder crash report (ISSUE 4).

usage:
  python scripts/flight_report.py REPORT.json [--last N]
  python scripts/flight_report.py --selftest

REPORT.json is what `monitor.trace.FlightRecorder.dump()` wrote (on an
exception inside `recorder.guard()`, or explicitly from a SIGTERM
handler).  The renderer prints the last-good → first-bad timeline with
the offending tap (layer + plane) highlighted, plus the cross-rank
straggler summary.

`--selftest` renders the committed fixture
(scripts/flight_report_fixture.json) and exits nonzero when the report
schema drifted or the rendering lost its load-bearing markers — the CI
guard that a report written by today's FlightRecorder stays readable by
today's renderer (mirrors `gpt_anatomy.py tune --check`).  Run from the
tier-1 suite (tests/test_trace.py).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pure host-side rendering — never let a pinned TPU tunnel stall a
# crash-report read on a dead machine
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "flight_report_fixture.json")

# markers the fixture rendering must contain; losing one means the
# renderer no longer tells the story the fixture encodes
_FIXTURE_MARKERS = (
    "first non-finite [grad] at block1/attn",
    "STRAGGLER rank 2",
    "last good step: 41001",
    "first bad step: 41002",
    # the compile & HBM observatory plane (ISSUE 5): the steady-state
    # retrace, the device watermark, and the HBM budget table
    "RECOMPILE at call 40970",
    "hbm[0]: 13.50 GiB in use / 14.00 GiB peak",
    "=== HBM budget ===",
    "donation: ok",
)


def selftest() -> int:
    from apex_tpu.monitor.trace import report as report_mod

    with open(FIXTURE) as f:
        rep = json.load(f)
    try:
        text = report_mod.render_report(rep)
    except ValueError as e:
        print(f"flight_report --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? update scripts/"
              "flight_report_fixture.json to the new schema)",
              file=sys.stderr)
        return 1
    missing = [m for m in _FIXTURE_MARKERS if m not in text]
    if missing:
        print(text)
        print(f"flight_report --selftest: rendering lost expected "
              f"markers: {missing}", file=sys.stderr)
        return 1
    print(text)
    print("flight_report --selftest: OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="render a numerics flight-recorder report")
    ap.add_argument("report", nargs="?",
                    help="report JSON written by FlightRecorder.dump()")
    ap.add_argument("--last", type=int, default=None,
                    help="only the final N recorded steps")
    ap.add_argument("--selftest", action="store_true",
                    help="render the committed fixture; exit 1 on "
                         "schema drift")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if not args.report:
        ap.error("REPORT.json required (or --selftest)")
    from apex_tpu.monitor.trace import report as report_mod

    with open(args.report) as f:
        rep = json.load(f)
    print(report_mod.render_report(rep, last=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
