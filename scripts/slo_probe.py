"""Serving-SLO CI gate (ISSUE 10): drive the flagship engine under a
churn workload and hold the serving observatory to its contract.

usage:
  python scripts/slo_probe.py             # full probe
  python scripts/slo_probe.py --selftest  # fixture drift gate
  python scripts/slo_probe.py --json      # machine-readable result

The full probe builds the flagship serve engine
(`serve.build_flagship_engine` — the SAME program bench.py measures
and the lint/comms gates probe) and drives a churn workload (more
requests than slots, ragged prompts and budgets) through
`measure_decode`, then asserts:

  1. LEDGER      — the request-lifecycle ledger reconciles EXACTLY
                   with the engine's own accounting: submitted ==
                   admitted == retired == the summed `(admitted,
                   retired)` that `step()` returned, per-request
                   token counts match the FinishedRequests, and every
                   record is causally ordered (submit <= admit <=
                   first-token <= retire).
  2. QUEUE       — with requests > slots, head-of-line-blocked
                   requests show nonzero queue wait (the gauge plane
                   has teeth, not zeros).
  3. ESTIMATOR   — the streaming percentile estimators agree with the
                   NumPy oracle over the same samples (exact below
                   reservoir capacity — this workload is below it).
  4. SLO         — the `ServeSLO` verdict is green under the given
                   thresholds (defaults are generous enough for any
                   CI box; tighten with the flags on real hardware)
                   and NO configured axis was skipped for lack of
                   samples.
  5. SENTRY      — zero steady-state recompiles under churn.
  6. BITWISE     — a telemetry-OFF engine over the same workload
                   produces byte-identical tokens (the observatory
                   observes, it never steers).

Exit is nonzero on any failure.  On a CPU backend the smoke config
substitutes through the same build path; on TPU run it as-is.

`--selftest` is the tier-1 fixture-drift gate (mirrors
`resume_probe.py --selftest`): the committed telemetry report
fixture (scripts/slo_fixture.json) must still validate against
`serve.validate_serve_report`, the estimator must reproduce the
NumPy oracle on a deterministic sample stream, and the fixture's
SEEDED SLO BREACH — a summary whose TTFT p99 violates its SLO — must
be reported as a breach naming the `ttft` axis (the gate's own
negative control: a verdict that stops flagging its seeded breach is
not a gate).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "slo_fixture.json")


# ---------------------------------------------------------------------------
# selftest (tier-1)
# ---------------------------------------------------------------------------

def selftest() -> int:
    import numpy as np

    from apex_tpu.serve import (ServeSLO, StreamingPercentiles,
                                validate_serve_report)

    with open(FIXTURE) as f:
        fixture = json.load(f)

    # 1. schema drift: the committed telemetry report must still
    # validate (bump-side change? regenerate scripts/slo_fixture.json
    # via `slo_probe.py --write-fixture`)
    try:
        validate_serve_report(fixture["report"])
    except ValueError as e:
        print(f"slo_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(regenerate scripts/slo_fixture.json with "
              "`python scripts/slo_probe.py --write-fixture`)",
              file=sys.stderr)
        return 1

    # 2. estimator vs oracle on a deterministic stream: exact below
    # capacity, tolerance-bounded above it
    rng = np.random.RandomState(1234)
    small = rng.lognormal(mean=0.0, sigma=1.0, size=200)
    est = StreamingPercentiles(capacity=4096, seed=0)
    est.extend(small)
    for q in (50.0, 95.0, 99.0):
        got, want = est.percentile(q), float(np.percentile(small, q))
        if abs(got - want) > 1e-12 * max(1.0, abs(want)):
            print(f"slo_probe --selftest: estimator p{q:g} {got!r} != "
                  f"oracle {want!r} below capacity (must be EXACT)",
                  file=sys.stderr)
            return 1
    big = rng.lognormal(mean=0.0, sigma=1.0, size=20_000)
    est2 = StreamingPercentiles(capacity=2048, seed=0)
    est2.extend(big)
    p50, p99 = est2.percentile(50.0), est2.percentile(99.0)
    o50, o99 = (float(np.percentile(big, 50)),
                float(np.percentile(big, 99)))
    if abs(p50 - o50) / o50 > 0.15 or abs(p99 - o99) / o99 > 0.35:
        print(f"slo_probe --selftest: reservoir estimate drifted from "
              f"the oracle (p50 {p50:.4f} vs {o50:.4f}, p99 {p99:.4f} "
              f"vs {o99:.4f})", file=sys.stderr)
        return 1

    # 3. negative control: the committed SEEDED BREACH must fail, and
    # must fail on the axis it seeds — BY NAME.  A green verdict here
    # means ServeSLO lost its teeth.
    br = fixture["seeded_breach"]
    verdict = ServeSLO(**br["slo"]).evaluate_summary(br["summary"])
    if verdict.ok:
        print("slo_probe --selftest: seeded SLO breach was NOT "
              "flagged — ServeSLO.evaluate lost its teeth",
              file=sys.stderr)
        return 1
    axes = [b.axis for b in verdict.breaches]
    if br["expect_axis"] not in axes:
        print(f"slo_probe --selftest: seeded breach flagged axes "
              f"{axes}, expected {br['expect_axis']!r} named",
              file=sys.stderr)
        return 1
    pcts = [b.percentile for b in verdict.breaches
            if b.axis == br["expect_axis"]]
    if br["expect_percentile"] not in pcts:
        print(f"slo_probe --selftest: seeded breach on "
              f"{br['expect_axis']!r} reported percentile {pcts}, "
              f"expected {br['expect_percentile']!r}", file=sys.stderr)
        return 1
    # the breach text must NAME the axis (what an operator greps for)
    if br["expect_axis"] not in verdict.describe():
        print("slo_probe --selftest: verdict text does not name the "
              f"violated axis: {verdict.describe()!r}", file=sys.stderr)
        return 1
    print("slo_probe --selftest: OK")
    return 0


# ---------------------------------------------------------------------------
# full probe
# ---------------------------------------------------------------------------

def _churn_workload(eng, n_requests, max_new_cap, seed=0):
    """Submit a ragged churn workload: more requests than slots,
    ragged prompt lengths and budgets (deterministic)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    mp = eng.serve_cfg.max_prompt_len
    rids = []
    for _ in range(n_requests):
        plen = int(rng.randint(1, mp + 1))
        budget = int(rng.randint(1, max_new_cap + 1))
        prompt = rng.randint(0, eng.model_cfg.vocab_size, plen).tolist()
        rids.append(eng.submit(prompt, budget))
    return rids


def probe(args) -> int:
    import jax
    import numpy as np

    from apex_tpu.serve import (ServeSLO, build_flagship_engine,
                                measure_decode, validate_serve_report)

    on_tpu = jax.default_backend() not in ("cpu",)
    slo = ServeSLO(ttft_p99_ms=args.slo_ttft_p99_ms,
                   per_token_p99_ms=args.slo_token_p99_ms,
                   max_queue_wait_ms=args.slo_queue_wait_ms)
    eng = build_flagship_engine(on_tpu)
    eng.slo = slo
    n_slots = eng.serve_cfg.n_slots
    n_requests = args.requests or 3 * n_slots
    # the probe's per-request tail checks and estimator-vs-oracle
    # EXACTNESS need the full run retained: size the telemetry to the
    # workload (the default 1024-tail / 4096-reservoir caps would
    # turn a healthy --requests 5000 run into bogus FAILs)
    from apex_tpu.serve import ServeTelemetry
    eng.telemetry = ServeTelemetry(
        tail_cap=n_requests + 8,
        estimator_capacity=max(4096, n_requests + 8))
    max_new = min(args.max_new or (16 if on_tpu else 8),
                  eng.serve_cfg.max_new_cap)
    rids = _churn_workload(eng, n_requests, max_new)

    failures = []
    result = {"backend": "tpu" if on_tpu else "cpu",
              "n_slots": n_slots, "n_requests": n_requests,
              "max_new": max_new}
    m = measure_decode(eng, max_steps=n_requests * max_new + 64)
    led = eng.telemetry.ledger
    result["steps"] = m["steps"]
    result["churn_steps"] = m["churn_steps"]
    result["tokens_per_sec"] = round(m["tokens_per_sec"], 1)

    # 1. ledger <-> engine reconciliation (exact).  A healthy
    # deadline-less run must also show ZERO terminal casualties and a
    # closed balance identity (ISSUE 14): expiry/shed/cancel firing
    # here would mean the resilience plane steers healthy traffic.
    bal = led.balance()
    ok = (led.n_submitted == led.n_admitted == led.n_retired
          == m["admitted"] == m["retired"] == n_requests
          and led.n_open == 0)
    result["ledger_reconciles"] = ok
    if not ok:
        failures.append(
            f"ledger does not reconcile: submitted {led.n_submitted} / "
            f"admitted {led.n_admitted} / retired {led.n_retired} vs "
            f"step() sums admitted {m['admitted']} / retired "
            f"{m['retired']} over {n_requests} requests "
            f"({led.n_open} still open)")
    if not bal["ok"]:
        failures.append(f"terminal-state balance violated: {bal}")
    if led.n_shed or led.n_expired or led.n_cancelled:
        failures.append(
            f"healthy run hit terminal states: shed {led.n_shed} / "
            f"expired {led.n_expired} / cancelled {led.n_cancelled} — "
            "the resilience plane fired on deadline-less traffic")
    fin_tokens = {f.request_id: len(f.tokens) for f in m["finished"]}
    tail = {r.request_id: r for r in led.tail}
    if set(fin_tokens) != set(rids):
        failures.append("finished request ids != submitted ids")
    for rid, n in fin_tokens.items():
        rec = tail.get(rid)
        if rec is None:
            failures.append(f"request {rid} missing from ledger tail")
            continue
        if rec.n_tokens != n:
            failures.append(
                f"request {rid}: ledger n_tokens {rec.n_tokens} != "
                f"{n} tokens actually returned")
        stamps = (rec.submit_t, rec.admit_t, rec.first_token_t,
                  rec.retire_t)
        if any(s is None for s in stamps) or not all(
                a <= b for a, b in zip(stamps, stamps[1:])):
            failures.append(
                f"request {rid}: lifecycle stamps out of order "
                f"{stamps}")
    if led.tokens_emitted != sum(fin_tokens.values()):
        failures.append(
            f"ledger tokens_emitted {led.tokens_emitted} != "
            f"{sum(fin_tokens.values())} returned")

    # 2. queueing has teeth: requests > slots must show head-of-line
    # waits strictly above the first-admitted cohort's
    waits = [r.queue_wait_s for r in led.tail]
    result["queue_wait_max_ms"] = round(1e3 * max(waits), 3)
    if n_requests > n_slots and max(waits) <= 0:
        failures.append(
            "requests > slots but no request shows queue wait — the "
            "queue-wait plane is not measuring")

    # 3. estimator vs oracle over the SAME samples (exact: this
    # workload is below reservoir capacity)
    for name, est, samples in (
            ("ttft", led.ttft, [r.ttft_s for r in led.tail]),
            ("queue_wait", led.queue_wait, waits),
            ("per_token", led.token_lat,
             [r.per_token_s for r in led.tail
              if r.per_token_s is not None])):
        if not samples:
            continue
        got = est.percentile(99.0)
        want = float(np.percentile(samples, 99))
        result[f"{name}_p99_ms"] = round(1e3 * got, 3)
        if abs(got - want) > 1e-9 * max(1.0, abs(want)):
            failures.append(
                f"{name} estimator p99 {got!r} != numpy oracle "
                f"{want!r} on the same {len(samples)} samples")

    # 4. the SLO verdict (no configured axis may be skipped: an axis
    # with no samples cannot claim green)
    verdict = eng.slo_verdict()
    result["slo_ok"] = verdict.ok
    result["slo"] = slo.to_dict()
    if not verdict.ok:
        failures.append(verdict.describe())
    if verdict.skipped:
        failures.append(
            f"SLO axes with no samples: {verdict.skipped} — the probe "
            "must measure every configured axis")

    # 5. zero steady-state recompiles under churn
    result["recompile_ok"] = eng.recompile_ok
    if not eng.recompile_ok:
        failures.append(
            f"steady-state recompile under churn: "
            f"{eng.sentry.summary()}")

    # 6. the observatory observes, it never steers: telemetry-off
    # engine, same weights + workload, byte-identical tokens
    eng_off = build_flagship_engine(on_tpu, params=eng.params)
    eng_off.telemetry = None
    rids_off = _churn_workload(eng_off, n_requests, max_new)
    fins_off = {f.request_id: f.tokens
                for f in eng_off.run(max_steps=n_requests * max_new + 64)}
    fins_on = {f.request_id: f.tokens for f in m["finished"]}
    bitwise = (dict(zip(rids, [fins_on[r] for r in rids]))
               == dict(zip(rids_off, [fins_off[r] for r in rids_off])))
    result["bitwise_telemetry_off"] = bitwise
    if not bitwise:
        failures.append(
            "decode outputs differ telemetry-on vs telemetry-off")

    # the report the crash dump would carry must be valid JSON-able
    try:
        rep = eng.telemetry_report()
        validate_serve_report(rep)
        json.dumps(rep)
    except (ValueError, TypeError) as e:
        failures.append(f"telemetry_report invalid: {e}")

    result["ok"] = not failures
    if args.json:
        # ONE line so callers can reverse-scan stdout past plugin
        # noise (the bench _run_isolated convention)
        print(json.dumps(result, sort_keys=True))
    else:
        for k in sorted(result):
            print(f"  {k}: {result[k]}")
    if failures:
        for f in failures:
            print(f"slo_probe: FAIL — {f}", file=sys.stderr)
        return 1
    print("slo_probe: OK (ledger reconciles, estimator == oracle, SLO "
          "green, zero steady-state recompiles, bitwise with "
          "telemetry off)")
    return 0


# ---------------------------------------------------------------------------
# fixture (re)generation — run once, commit the result
# ---------------------------------------------------------------------------

def write_fixture() -> int:
    from apex_tpu.serve import build_flagship_engine, measure_decode

    eng = build_flagship_engine(False)
    _churn_workload(eng, 2 * eng.serve_cfg.n_slots, 6)
    measure_decode(eng, max_steps=4096)
    fixture = {
        "_comment": "slo_probe --selftest fixture: a real smoke-run "
                    "telemetry report (schema drift gate) + a seeded "
                    "SLO breach (negative control).  Regenerate with "
                    "`python scripts/slo_probe.py --write-fixture`.",
        "report": eng.telemetry_report(),
        "seeded_breach": {
            "slo": {"ttft_p99_ms": 10.0, "per_token_p99_ms": 50.0,
                    "max_queue_wait_ms": 100.0},
            "summary": {"ttft_p99_ms": 25.0, "per_token_p99_ms": 1.0,
                        "queue_wait_max_ms": 2.0, "n_retired": 16},
            "expect_axis": "ttft",
            "expect_percentile": "p99",
        },
    }
    with open(FIXTURE, "w") as f:
        json.dump(fixture, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {FIXTURE}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="serving observatory / SLO CI gate")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate; exit 1 on drift")
    ap.add_argument("--write-fixture", action="store_true",
                    help="regenerate scripts/slo_fixture.json")
    ap.add_argument("--requests", type=int, default=None,
                    help="churn workload size (default 3x slots)")
    ap.add_argument("--max-new", type=int, default=None,
                    help="per-request token budget cap "
                         "(default 8 CPU / 16 TPU)")
    ap.add_argument("--slo-ttft-p99-ms", type=float, default=120_000.0,
                    help="TTFT p99 SLO in ms (default generous for "
                         "CI; tighten on real hardware)")
    ap.add_argument("--slo-token-p99-ms", type=float, default=60_000.0,
                    help="per-token p99 SLO in ms")
    ap.add_argument("--slo-queue-wait-ms", type=float,
                    default=240_000.0,
                    help="max queue wait SLO in ms")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result")
    ap.add_argument("--backend", default=None,
                    help="JAX_PLATFORMS override (resolved pre-import)")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if args.write_fixture:
        return write_fixture()
    return probe(args)


if __name__ == "__main__":
    sys.exit(main())
