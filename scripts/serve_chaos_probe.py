"""Serving-resilience chaos gate (ISSUE 14): drive the flagship
engine through an overload + kill matrix and hold the failure
semantics to their contract.

usage:
  python scripts/serve_chaos_probe.py             # full matrix
  python scripts/serve_chaos_probe.py --selftest  # fixture drift gate
  python scripts/serve_chaos_probe.py --json      # machine-readable

The full probe builds the flagship serve engine
(`serve.build_flagship_engine` — the SAME program bench.py measures
and the lint/comms/slo gates probe), records an UNLOADED baseline run
(every request alone against an unbounded queue, no faults), then
re-runs the same workload through every leg of the matrix and asserts,
for each:

  BITWISE     — every request that ends `ok` produces tokens bitwise
                equal to the unloaded baseline (overload, stalls,
                poisons and kills may shed/expire/cancel requests,
                but they may never CHANGE a survivor's output);
  POOL        — the page pool reconciles to zero leaks at every fail
                point (free pages == usable pages once drained);
  LEDGER      — the terminal-state balance identity closes exactly:
                n_submitted == n_retired + n_expired + n_cancelled +
                n_shed + n_open (`RequestLedger.balance`);
  SENTRY      — zero steady-state recompiles per engine.

Matrix legs (chaos points: `checkpoint.chaos.SERVE_POINTS`):

  overload    — bounded queue at 4x slot capacity with mixed
                deadlines + mid-run cancellation; negative controls
                asserted BY NAME: the seeded deadline breach ends
                `expired`, shed-under-overload fires (`shed` terminal,
                policy-ordered victim), the cancelled requests end
                `cancelled`;
  stall       — `serve.stall_step` wedges the decode loop; the
                `EngineWatchdog` must trip (`EngineStalledError`
                naming the stuck step — the watchdog-trip negative
                control), dump a flight report, and `restart()` from
                its periodic snapshot must resume MID-GENERATION
                bitwise;
  poison      — `serve.poison_logits` corrupts the output ring; the
                retire poll must refuse it (`PoisonedOutputError`
                naming slot/request/step) and the watchdog's
                last-KNOWN-GOOD snapshot must recover bitwise;
  kill-drain  — `serve.kill_mid_drain` kills a deploy's graceful
                drain partway; the snapshot contract recovers, the
                drained snapshot restores into a fresh engine, and
                the still-queued requests finish there bitwise.

Exit is nonzero on any failure.  On a CPU backend the smoke config
substitutes through the same build path; on TPU run it as-is.

`--selftest` is the tier-1 fixture-drift gate (mirrors
`slo_probe.py --selftest`): the committed telemetry report fixture
(scripts/serve_chaos_fixture.json) must still validate against
`serve.validate_serve_report`, and three SEEDED NEGATIVE CONTROLS
must fail by name without building an engine: a ledger whose deadline
breach must end `expired` with the balance identity closing, a
shed-policy replay whose named victim must be chosen, and a stub
engine whose watchdog must raise `EngineStalledError` naming the
stuck step under an injected clock.  A gate that stops flagging its
seeded failures is not a gate.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "serve_chaos_fixture.json")

# bound every drive loop: a wedged scheduler must FAIL the gate, not
# hang it (the serve_gpt example's convention)
_MAX_STEPS = 4096


# ---------------------------------------------------------------------------
# selftest (tier-1)
# ---------------------------------------------------------------------------

def selftest() -> int:
    from apex_tpu.serve import (EngineStalledError, EngineWatchdog,
                                RequestLedger, choose_shed_victim,
                                validate_serve_report)

    with open(FIXTURE) as f:
        fixture = json.load(f)

    # 1. schema drift: the committed chaos-run telemetry report must
    # still validate (bump-side change? regenerate with
    # `serve_chaos_probe.py --write-fixture`)
    try:
        validate_serve_report(fixture["report"])
    except ValueError as e:
        print(f"serve_chaos_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(regenerate scripts/serve_chaos_fixture.json with "
              "`python scripts/serve_chaos_probe.py --write-fixture`)",
              file=sys.stderr)
        return 1
    led = fixture["report"]["ledger"]
    if not (led["n_shed"] > 0 and led["n_expired"] > 0
            and led["n_cancelled"] > 0 and led["balance_ok"]):
        print("serve_chaos_probe --selftest: the committed report no "
              "longer carries every terminal state with a closed "
              f"balance (shed {led['n_shed']} / expired "
              f"{led['n_expired']} / cancelled {led['n_cancelled']} / "
              f"balance_ok {led['balance_ok']})", file=sys.stderr)
        return 1

    # 2. negative control: DEADLINE BREACH.  A pure-ledger replay of
    # the seeded lifecycle — the expired request must end in terminal
    # `expired` BY NAME and the balance identity must still close.
    br = fixture["seeded_deadline_breach"]
    ledger = RequestLedger()
    ledger.on_submit(0, 4, 8, 0.0)
    ledger.on_submit(1, 4, 8, 0.0, deadline_ms=br["deadline_ms"])
    ledger.on_admit(0, 0, 0.001)
    ledger.on_first_token([0], 0.002)
    # the deadline passes while request 1 is still queued
    ledger.on_expire(1, br["deadline_ms"] / 1e3 + 0.001, where="queue")
    ledger.on_retire(0, 8, 0.01)
    rec = {r.request_id: r for r in ledger.tail}
    if rec[1].status != "expired" or rec[1].where != "queue":
        print(f"serve_chaos_probe --selftest: seeded deadline breach "
              f"ended {rec[1].status!r}/{rec[1].where!r}, expected "
              "'expired'/'queue' — the TTL terminal lost its name",
              file=sys.stderr)
        return 1
    bal = ledger.balance()
    if not bal["ok"] or bal["n_expired"] != 1:
        print(f"serve_chaos_probe --selftest: balance identity does "
              f"not close over the seeded breach: {bal}",
              file=sys.stderr)
        return 1
    # ...and a seeded IMBALANCE must be flagged: drop a terminal event
    bad = RequestLedger()
    bad.on_submit(0, 4, 8, 0.0)
    bad.on_submit(1, 4, 8, 0.0)
    bad.on_admit(0, 0, 0.001)
    bad.on_retire(0, 8, 0.01)
    bad._open.pop(1)              # the seeded hole: vanished request
    if bad.balance()["ok"]:
        print("serve_chaos_probe --selftest: seeded ledger imbalance "
              "(a request that vanished without a terminal state) was "
              "NOT flagged — balance() lost its teeth", file=sys.stderr)
        return 1

    # 3. negative control: SHED-UNDER-OVERLOAD policy ordering.  The
    # committed scenario replays through the ONE policy spelling the
    # engine uses; the named victim must be chosen.
    class _C:
        def __init__(self, rid, deadline_t):
            self.rid, self.deadline_t = rid, deadline_t

    sh = fixture["seeded_shed"]
    cands = [_C(c["rid"], c.get("deadline_t")) for c in sh["candidates"]]
    victim = choose_shed_victim(cands, sh["policy"])
    if victim.rid != sh["expect_victim"]:
        print(f"serve_chaos_probe --selftest: policy {sh['policy']!r} "
              f"shed rid {victim.rid}, fixture expects "
              f"{sh['expect_victim']} — shed ordering drifted",
              file=sys.stderr)
        return 1
    newest = choose_shed_victim(cands, "shed-newest")
    if newest.rid != cands[-1].rid:
        print("serve_chaos_probe --selftest: shed-newest did not pick "
              "the incoming request", file=sys.stderr)
        return 1

    # 4. negative control: WATCHDOG TRIP.  A stub engine that stops
    # heartbeating under an injected clock must raise
    # EngineStalledError naming the stuck step.
    class _StubEngine:
        steps_completed = 7
        pending = 3
        _live = {0: None, 1: None}
        _pending = [None]
        watchdog = None

    wd = fixture["seeded_watchdog"]
    t = [0.0]
    dog = EngineWatchdog(_StubEngine(),
                         stall_timeout_s=wd["stall_timeout_s"],
                         clock=lambda: t[0])
    dog.check()                        # armed, no progress yet
    t[0] = wd["stall_timeout_s"] + wd["overshoot_s"]
    try:
        dog.check()
    except EngineStalledError as e:
        if "step 7" not in str(e) or e.step != 7:
            print(f"serve_chaos_probe --selftest: watchdog trip does "
                  f"not name the stuck step: {e}", file=sys.stderr)
            return 1
    else:
        print("serve_chaos_probe --selftest: seeded stall did NOT "
              "trip the watchdog — EngineWatchdog lost its teeth",
              file=sys.stderr)
        return 1

    print("serve_chaos_probe --selftest: OK")
    return 0


# ---------------------------------------------------------------------------
# full probe
# ---------------------------------------------------------------------------

def _workload(eng, n_requests, max_new, seed=0, deadlines=None):
    """Deterministic ragged workload; `deadlines` (rid-index aligned)
    attaches per-request deadline_ms."""
    import numpy as np

    rng = np.random.RandomState(seed)
    mp = eng.serve_cfg.max_prompt_len
    rids = []
    for i in range(n_requests):
        plen = int(rng.randint(1, mp + 1))
        budget = int(rng.randint(1, max_new + 1))
        prompt = rng.randint(0, eng.model_cfg.vocab_size, plen).tolist()
        dl = deadlines[i] if deadlines else None
        rids.append(eng.submit(prompt, budget, deadline_ms=dl))
    return rids


def _drive(eng, fins, max_steps=_MAX_STEPS, watchdog=None):
    steps = 0
    while eng.pending:
        if steps >= max_steps:
            raise RuntimeError(f"drive: {eng.pending} request(s) still "
                               f"live after {max_steps} steps")
        eng.step()
        for f in eng.poll():
            fins[f.request_id] = f
        if watchdog is not None:
            watchdog.check()
        steps += 1
    return steps


def _leg_checks(name, eng, fins, ref, failures):
    """The invariants EVERY leg must hold: ok-survivors bitwise,
    pool reconciled, ledger balanced, sentry clean."""
    ok = {r: f.tokens for r, f in fins.items() if f.status == "ok"}
    for rid, toks in ok.items():
        if toks != ref[rid]:
            failures.append(
                f"{name}: request {rid} survived with NON-BITWISE "
                f"tokens vs the unloaded baseline")
            break
    if eng.cache.free_pages != eng.kv_config.usable_pages:
        failures.append(
            f"{name}: page pool leaked — {eng.cache.free_pages} free "
            f"of {eng.kv_config.usable_pages} usable after the storm")
    if eng.telemetry is not None:
        bal = eng.telemetry.ledger.balance()
        if not bal["ok"]:
            failures.append(f"{name}: ledger balance violated: {bal}")
    if not eng.recompile_ok:
        failures.append(f"{name}: steady-state recompile — "
                        f"{eng.sentry.summary()}")
    return ok


def probe(args) -> int:
    import time

    import jax

    from apex_tpu.checkpoint import chaos
    from apex_tpu.serve import (EngineStalledError, EngineWatchdog,
                                PoisonedOutputError, ServeSLO,
                                build_flagship_engine,
                                validate_serve_report)

    on_tpu = jax.default_backend() not in ("cpu",)
    chaos.disarm_all()
    failures = []
    result = {"backend": "tpu" if on_tpu else "cpu"}

    # ---------------- unloaded baseline (the bitwise oracle) ----------
    eng0 = build_flagship_engine(on_tpu)
    n_slots = eng0.serve_cfg.n_slots
    n_requests = args.requests or 4 * n_slots       # the 4x storm size
    max_new = min(args.max_new or (8 if on_tpu else 6),
                  eng0.serve_cfg.max_new_cap)
    result.update(n_slots=n_slots, n_requests=n_requests,
                  max_new=max_new)
    _workload(eng0, n_requests, max_new)
    ref_fins = {}
    _drive(eng0, ref_fins)
    ref = {r: f.tokens for r, f in ref_fins.items()}
    if len(ref) != n_requests:
        failures.append("baseline did not finish every request")
    params = eng0.params

    # ---------------- leg 1: overload + deadlines + cancel ------------
    eng1 = build_flagship_engine(
        on_tpu, params=params,
        serve_overrides={"max_queue_depth": 2 * n_slots,
                         "shed_policy": "shed-lowest-deadline"})
    eng1.slo = ServeSLO(max_queue_wait_ms=args.slo_queue_wait_ms)
    # mixed deadlines: one seeded breach (expires in queue), a band of
    # long-but-finite ones (the shed-lowest-deadline policy's victim
    # pool), the rest unbounded.  The breach rides EARLY — before the
    # bounded queue fills — so it dies by EXPIRY at the next submit's
    # sweep (microseconds later), never by shed: the two negative
    # controls must fire separately, each by name.
    deadlines = [None] * n_requests
    for i in range(n_requests // 4, n_requests // 2):
        deadlines[i] = 60_000.0             # feasible everywhere
    breach_idx = n_requests // 8
    deadlines[breach_idx] = 0.002           # the seeded deadline breach
    rids1 = _workload(eng1, n_requests, max_new, deadlines=deadlines)
    shed_in_submit = eng1.telemetry.ledger.n_shed
    # cancel one queued + one live request mid-storm
    fins1 = {}
    eng1.step()
    live_rid = next(iter(eng1._live.values())).rid
    queued_rid = next((r.rid for r in eng1._pending
                       if r.deadline_t is None), None)
    assert eng1.cancel(live_rid), "live cancel refused"
    if queued_rid is not None and not eng1.cancel(queued_rid):
        failures.append("overload: queued cancel refused")
    time.sleep(0.01)                        # let the breach deadline pass
    _drive(eng1, fins1)
    led1 = eng1.telemetry.ledger
    _leg_checks("overload", eng1, fins1, ref, failures)
    result["overload"] = {
        "n_shed": led1.n_shed, "n_expired": led1.n_expired,
        "n_cancelled": led1.n_cancelled, "n_ok": led1.n_retired,
        "shed_at_submit": shed_in_submit,
    }
    # negative controls, BY NAME
    if fins1[rids1[breach_idx]].status != "expired":
        failures.append(
            f"overload: seeded deadline breach (rid "
            f"{rids1[breach_idx]}) ended "
            f"{fins1[rids1[breach_idx]].status!r}, expected 'expired'")
    if led1.n_expired < 1:
        failures.append("overload: no deadline expiry despite the "
                        "seeded breach — the TTL plane is not firing")
    if led1.n_shed < 1:
        failures.append("overload: 4x storm against a bounded queue "
                        "shed nothing — overload control is not firing")
    if fins1[live_rid].status != "cancelled":
        failures.append(
            f"overload: mid-generation cancel ended "
            f"{fins1[live_rid].status!r}, expected 'cancelled'")
    if queued_rid is not None and fins1[queued_rid].status != "cancelled":
        failures.append("overload: queued cancel did not end "
                        "'cancelled'")
    # policy ordering: with shed-lowest-deadline, no unbounded-deadline
    # request may be shed while a sooner-deadline one sat in the queue
    # at the same shed decision — verify the victims carry the
    # smallest deadlines among their shed cohort
    shed_rids = {r for r, f in fins1.items() if f.status == "shed"}
    tight = {rids1[i] for i in range(n_requests)
             if deadlines[i] is not None and i != breach_idx}
    if shed_rids and not (shed_rids & tight) and (tight - shed_rids):
        # every shed victim was deadline-less while deadline-carrying
        # requests queued: the lowest-deadline policy did not order
        failures.append("overload: shed-lowest-deadline shed only "
                        "deadline-less requests while deadline-carrying "
                        "ones were queued")

    # ---------------- leg 2: stall → watchdog trip → restart ----------
    chaos.disarm_all()
    eng2 = build_flagship_engine(on_tpu, params=params)
    _workload(eng2, min(n_requests, 2 * n_slots), max_new)
    dog = EngineWatchdog(eng2, stall_timeout_s=0.05, snapshot_every=1)
    chaos.arm("serve.stall_step", 4)
    fins2 = {}
    tripped = None
    steps = 0
    while eng2.pending:
        if steps >= _MAX_STEPS:
            failures.append("stall: drive loop exceeded bound")
            break
        eng2.step()
        for f in eng2.poll():
            fins2[f.request_id] = f
        try:
            dog.check()
        except EngineStalledError as e:
            tripped = e
            eng2 = dog.restart()
        if eng2.stalled:
            time.sleep(0.02)
        steps += 1
    eng2._retire_finished()
    for f in eng2.poll():
        fins2[f.request_id] = f
    if tripped is None:
        failures.append("stall: watchdog never tripped on the wedged "
                        "engine — the stall negative control failed")
    elif "stalled" not in str(tripped) or tripped.step is None:
        failures.append(f"stall: trip does not name the stuck step: "
                        f"{tripped}")
    _leg_checks("stall", eng2, fins2, ref, failures)
    result["stall"] = {"tripped": tripped is not None,
                       "stalls": dog.stalls, "restarts": dog.restarts,
                       "snapshot_step": dog.snapshot_step}

    # ---------------- leg 3: poisoned logits → detect → recover -------
    chaos.disarm_all()
    eng3 = build_flagship_engine(on_tpu, params=params)
    _workload(eng3, min(n_requests, 2 * n_slots), max_new)
    dog3 = EngineWatchdog(eng3, stall_timeout_s=30.0, snapshot_every=1)
    chaos.arm("serve.poison_logits", 3)
    fins3 = {}
    poisoned = None
    steps = attempts = 0
    while eng3.pending:
        if steps >= _MAX_STEPS:
            failures.append("poison: drive loop exceeded bound")
            break
        try:
            eng3.step()
        except PoisonedOutputError as e:
            poisoned = e
            attempts += 1
            if attempts > 2:
                failures.append("poison: restart did not clear the "
                                "corruption (snapshot not known-good)")
                break
            eng3 = dog3.restart()
            continue
        for f in eng3.poll():
            fins3[f.request_id] = f
        dog3.check()
        steps += 1
    eng3._retire_finished()
    for f in eng3.poll():
        fins3[f.request_id] = f
    if poisoned is None:
        failures.append("poison: garbage token ids were never "
                        "detected at the retire poll")
    elif poisoned.slot is None or "token ids outside" not in str(poisoned):
        failures.append(f"poison: detection does not name the "
                        f"slot/range: {poisoned}")
    _leg_checks("poison", eng3, fins3, ref, failures)
    result["poison"] = {"detected": poisoned is not None,
                        "restarts": dog3.restarts}

    # ---------------- leg 4: kill mid-drain → snapshot recovery -------
    chaos.disarm_all()
    kill_ok = True
    for count in (1, 3):
        eng4 = build_flagship_engine(on_tpu, params=params)
        _workload(eng4, min(n_requests, 2 * n_slots), max_new)
        fins4 = {}
        for _ in range(2):
            eng4.step()
            for f in eng4.poll():
                fins4[f.request_id] = f
        chaos.arm("serve.kill_mid_drain", count)
        try:
            eng4.drain(max_steps=_MAX_STEPS)
            failures.append(f"kill-drain[{count}]: armed kill never "
                            "fired")
            kill_ok = False
            continue
        except chaos.SimulatedPreemption:
            pass
        # the deploy died mid-drain; the snapshot contract recovers —
        # drain the replacement, then finish its queued tail in a
        # third engine from the DRAINED snapshot
        snap = eng4.state_dict()
        for f in eng4.poll():
            fins4[f.request_id] = f
        eng5 = build_flagship_engine(on_tpu, params=params)
        eng5.load_state_dict(snap)
        drained = eng5.drain(max_steps=_MAX_STEPS)
        for f in eng5.poll():
            fins4[f.request_id] = f
        eng6 = build_flagship_engine(on_tpu, params=params)
        eng6.load_state_dict(drained)
        _drive(eng6, fins4)
        ok = _leg_checks(f"kill-drain[{count}]", eng6, fins4, ref,
                         failures)
        kill_ok = kill_ok and len(fins4) == min(n_requests, 2 * n_slots)
        if len(ok) != len(fins4):
            failures.append(f"kill-drain[{count}]: drain lost a live "
                            "request to a non-ok terminal")
    result["kill_drain_ok"] = kill_ok

    # the overload leg's report must be dump-valid
    try:
        rep = eng1.telemetry_report()
        validate_serve_report(rep)
        json.dumps(rep)
    except (ValueError, TypeError) as e:
        failures.append(f"telemetry_report invalid: {e}")

    chaos.disarm_all()
    result["ok"] = not failures
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        for k in sorted(result):
            print(f"  {k}: {result[k]}")
    if failures:
        for f in failures:
            print(f"serve_chaos_probe: FAIL — {f}", file=sys.stderr)
        return 1
    print("serve_chaos_probe: OK (survivors bitwise at every fail "
          "point, pool reconciled, ledger balanced, negative controls "
          "fired by name, zero steady-state recompiles)")
    return 0


# ---------------------------------------------------------------------------
# fixture (re)generation — run once, commit the result
# ---------------------------------------------------------------------------

def write_fixture() -> int:
    import time

    from apex_tpu.serve import build_flagship_engine

    # a small real chaos run so the committed report carries every
    # terminal state: bounded queue + a doomed deadline + a cancel
    eng = build_flagship_engine(
        False, serve_overrides={"max_queue_depth": 4,
                                "shed_policy": "shed-lowest-deadline"})
    n = 3 * eng.serve_cfg.n_slots
    deadlines = [None] * n
    # early, before the bounded queue fills: dies by EXPIRY at the
    # next submit's sweep, not by shed (the probe-leg convention)
    deadlines[2] = 0.002
    rids = _workload(eng, n, 6, deadlines=deadlines)
    eng.step()
    eng.cancel(next(iter(eng._live.values())).rid)
    time.sleep(0.01)
    fins = {}
    _drive(eng, fins)
    fixture = {
        "_comment": "serve_chaos_probe --selftest fixture: a real "
                    "chaos smoke-run telemetry report (schema drift "
                    "gate; carries every terminal state) + the seeded "
                    "negative controls.  Regenerate with `python "
                    "scripts/serve_chaos_probe.py --write-fixture`.",
        "report": eng.telemetry_report(),
        "seeded_deadline_breach": {"deadline_ms": 5.0},
        "seeded_shed": {
            "policy": "shed-lowest-deadline",
            "candidates": [
                {"rid": 10, "deadline_t": 9.0},
                {"rid": 11, "deadline_t": 2.5},
                {"rid": 12},
                {"rid": 13, "deadline_t": 7.0},
            ],
            "expect_victim": 11,
        },
        "seeded_watchdog": {"stall_timeout_s": 4.0, "overshoot_s": 0.5},
    }
    with open(FIXTURE, "w") as f:
        json.dump(fixture, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {FIXTURE}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="serving resilience chaos gate")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate + seeded negative "
                         "controls; exit 1 on drift")
    ap.add_argument("--write-fixture", action="store_true",
                    help="regenerate scripts/serve_chaos_fixture.json")
    ap.add_argument("--requests", type=int, default=None,
                    help="storm size (default 4x slots)")
    ap.add_argument("--max-new", type=int, default=None,
                    help="per-request token budget cap "
                         "(default 6 CPU / 8 TPU)")
    ap.add_argument("--slo-queue-wait-ms", type=float,
                    default=240_000.0,
                    help="max queue wait SLO for the proactive-shed "
                         "projection (default generous for CI)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result")
    ap.add_argument("--backend", default=None,
                    help="JAX_PLATFORMS override (resolved pre-import)")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if args.write_fixture:
        return write_fixture()
    return probe(args)


if __name__ == "__main__":
    sys.exit(main())
