"""A/B the RN50 train-step tail: grads-only vs tree-SGD vs flat FusedSGD."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD

B = 256
model = ResNet("resnet50", num_classes=1000, axis_name=None)
params, mstate = model.init(jax.random.PRNGKey(0))
params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
x16 = jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3),
                        jnp.bfloat16)
y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 1000)


def lf(p, ms):
    logits, nms = model.apply(p, ms, x16, training=True)
    return jnp.mean(softmax_cross_entropy_loss(
        logits.astype(jnp.float32), y)), nms


def timeit(jstep, args, iters=8, warmup=2):
    for _ in range(warmup):
        args = jstep(*args)
    _ = np.asarray(jax.tree.leaves(args)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        args = jstep(*args)
    _ = np.asarray(jax.tree.leaves(args)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


# A: grads only (no optimizer) — isolates the optimizer+unflatten cost
def step_a(p, ms):
    grads, nms = jax.grad(lf, has_aux=True)(p, ms)
    return grads, nms


t = timeit(jax.jit(step_a, donate_argnums=(0,)), (params16, mstate))
print(f"A grads-only:      {t*1e3:7.2f} ms ({B/t:.0f} img/s)", flush=True)

# B: tree SGD (per-leaf momentum fp32, params bf16, all donated)
mom = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params16)


def step_b(p, mom, ms):
    grads, nms = jax.grad(lf, has_aux=True)(p, ms)

    def upd(p, g, m):
        m = 0.9 * m + g.astype(jnp.float32) + 1e-4 * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - 0.1 * m).astype(p.dtype), m

    out = jax.tree.map(upd, p, grads, mom)
    newp = jax.tree.map(lambda o: o[0], out,
                        is_leaf=lambda t: isinstance(t, tuple))
    newm = jax.tree.map(lambda o: o[1], out,
                        is_leaf=lambda t: isinstance(t, tuple))
    return newp, newm, nms


t = timeit(jax.jit(step_b, donate_argnums=(0, 1)), (params16, mom, mstate))
print(f"B tree-SGD:        {t*1e3:7.2f} ms ({B/t:.0f} img/s)", flush=True)

# C: flat FusedSGD (the current bench path)
opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
state = opt.init(params16)
from apex_tpu.optimizers import flat as F


def step_c(state, ms):
    p = F.unflatten(state.params, opt.spec)
    grads, nms = jax.grad(lf, has_aux=True)(p, ms)
    _, new_state = opt.step(state, grads)
    return new_state, nms


t = timeit(jax.jit(step_c, donate_argnums=(0,)), (state, mstate))
print(f"C flat FusedSGD:   {t*1e3:7.2f} ms ({B/t:.0f} img/s)", flush=True)
