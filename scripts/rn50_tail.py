"""Decompose the ResNet bench-step tail: model fwd+bwd is ~94 ms but the
bench step is ~118 ms.  Times three variants of the full train step on
the real chip (dispatch-amortized: N calls back-to-back, one sync)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp
from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M

B = 256


def timeit(step_fn, args, iters=10, warmup=2):
    """step_fn(*args) -> new args tuple (donation-safe state threading)."""
    for _ in range(warmup):
        args = step_fn(*args)
    _ = np.asarray(jax.tree.leaves(args)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        args = step_fn(*args)
    _ = np.asarray(jax.tree.leaves(args)[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


def main():
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = ResNet("resnet50", num_classes=1000, axis_name="dp")
    params, mstate = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 1000)

    def loss_fn(p, ms, b):
        xb, yb = b
        logits, new_ms = model.apply(p, ms, xb, training=True)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), yb)), new_ms

    # variant 1: the bench step exactly (amp O1 + ddp.make_train_step)
    amp_state = amp.initialize(opt_level="O1")
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True)

    def run1(state, scaler, mstate):
        s, sc, ms, _ = step(state, scaler, mstate, (x, y))
        return s, sc, ms

    t = timeit(run1, (state, scaler, mstate))
    print(f"bench step (O1 + scaler + ddp):    {t*1e3:.2f} ms "
          f"({B/t:.0f} img/s)", flush=True)

    # variant 2: same builder, amp O1 but static loss scale (no dynamic
    # scaler state / no check_finite pass)
    amp_state2 = amp.initialize(opt_level="O1", loss_scale=1.0)
    step2 = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state2,
                                batch_spec=(P("dp"), P("dp")),
                                with_state=True)
    scaler2 = amp_state2.loss_scalers[0]
    state_b = opt.init(params)

    def run2(state, scaler, mstate):
        s, sc, ms, _ = step2(state, scaler, mstate, (x, y))
        return s, sc, ms

    t = timeit(run2, (state_b, scaler2, mstate))
    print(f"step (O1, static scale):           {t*1e3:.2f} ms "
          f"({B/t:.0f} img/s)", flush=True)

    # variant 3: minimal — bf16 params, plain jit, no shard_map/amp,
    # fused SGD on the flat buffer
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    opt3 = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state3 = opt3.init(params16)
    x16 = x.astype(jnp.bfloat16)

    def step3c(state, mstate):
        from apex_tpu.optimizers import flat as F
        p = F.unflatten(state.params, opt3.spec)

        def lf(p):
            logits, nms = model.apply(p, mstate, x16, training=True,
                                      axis_name=None)
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits.astype(jnp.float32), y))
            return loss, nms

        grads, nms = jax.grad(lf, has_aux=True)(p)
        new_p, new_state = opt3.step(state, grads)
        return new_state, nms

    jstep3 = jax.jit(step3c, donate_argnums=(0,))
    t = timeit(jstep3, (state3, mstate))
    print(f"minimal (bf16 params, no amp/ddp): {t*1e3:.2f} ms "
          f"({B/t:.0f} img/s)", flush=True)
    M.destroy_model_parallel()


if __name__ == "__main__":
    main()


def scan_variant():
    """K train steps inside ONE jitted scan call: if per-step time drops
    to the profiler's ~94 ms, the gap was host dispatch through the
    tunnel, not device work."""
    M.destroy_model_parallel()
    model = ResNet("resnet50", num_classes=1000, axis_name=None)
    params, mstate = model.init(jax.random.PRNGKey(0))
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params16)
    x16 = jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3),
                            jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 1000)
    K = 10

    def one(carry, _):
        state, mstate = carry
        from apex_tpu.optimizers import flat as F
        p = F.unflatten(state.params, opt.spec)

        def lf(p):
            logits, nms = model.apply(p, mstate, x16, training=True)
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits.astype(jnp.float32), y))
            return loss, nms

        grads, nms = jax.grad(lf, has_aux=True)(p)
        _, new_state = opt.step(state, grads)
        return (new_state, nms), None

    def many(state, mstate):
        (s, ms), _ = jax.lax.scan(one, (state, mstate), None, length=K)
        return s, ms

    jmany = jax.jit(many, donate_argnums=(0, 1))

    def run(state, mstate):
        return jmany(state, mstate)

    t = timeit(run, (state, mstate), iters=3, warmup=1)
    print(f"scan x{K} minimal:                  {t/K*1e3:.2f} ms/step "
          f"({B/(t/K):.0f} img/s)", flush=True)


if __name__ == "__main__":
    pass
