"""Comms/overlap CI gate for the flagship train steps (ISSUE 7).

usage:
  python scripts/comms_probe.py [targets...]   # default: gpt_zero2 gpt
  python scripts/comms_probe.py --selftest     # fixture schema-drift gate
  python scripts/comms_probe.py --report PATH  # gate a saved CommsReport JSON
  python scripts/comms_probe.py --json         # machine-readable reports

Builds each flagship step (the EXACT bench programs; on a CPU backend
the smoke configs substitute, same build path), AOT lowers+compiles it
WITHOUT executing, and runs `apex_tpu.monitor.comms`' collective
inventory + overlap analysis.  Exit is nonzero when a collective the
analyzer expects to overlap (async, >= 1 MiB, all-reduce/all-gather/
reduce-scatter) SERIALIZED — its start→done window held zero dot
flops — and is not accepted by the committed allowlist
(scripts/comms_allowlist.txt, COMMITTED EMPTY).  This is the standing
gate the ZeRO-3 and TP-overlap work (ROADMAP items 1-2) are developed
against: a chunked-overlap regression shows up here before it shows up
as a flat tokens/s round.

On backends that emit no async collectives (CPU: XLA lowers sync
all-reduces only) the overlap plane is unmeasurable and the gate
passes with a note — the inventory and roofline still print.  The
`--report` mode gates a SAVED report JSON instead (e.g. one produced
on real hardware, or the committed fixture — which contains a seeded
serialized collective and therefore exits nonzero, the gate's own
negative control).

`--selftest` validates + renders the committed fixture
(scripts/comms_fixture.json) and exits nonzero when the schema
drifted, the rendering lost its load-bearing markers, or the seeded
serialized collective is NOT flagged (mirrors `lint_step.py
--selftest`); run from the tier-1 suite (tests/test_comms.py).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# scripts/ itself, for the shared gpt_anatomy._build_bench_step builder
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

# the audit is AOT; never let a pinned TPU tunnel stall the gate unless
# the operator explicitly asked for device truth.  `--backend tpu` (or
# an explicit JAX_PLATFORMS) IS that ask — the overlap plane only
# exists in a TPU schedule, so the on-hardware runbook needs a spelled
# way in; must be resolved before the first jax import, hence argv
# peeking rather than argparse
if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the ZeRO-2 target needs a dp axis: on the CPU backend force a 2-way
# virtual mesh (must precede the first jax import, conftest-style)
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
ALLOWLIST = os.path.join(_HERE, "comms_allowlist.txt")
FIXTURE = os.path.join(_HERE, "comms_fixture.json")

# markers the fixture rendering must contain; losing one means the
# renderer no longer tells the story the fixture encodes
_FIXTURE_MARKERS = (
    "=== comms: fixture-step ===",
    "| all-reduce",
    "| reduce-scatter",
    "| all-to-all",
    "| collective-permute",
    "| ep ",
    "| tp ",
    "**SER**",
    "SERIALIZED collective(s)",
    "roofline: predicted comm",
)

# the seeded serialized-chunk negative control (ISSUE 18): one chunk
# of the fixture's chunked-TP ring pair is seeded serialized and must
# stay flagged BY NAME, or the gate is blind to ring-hop regressions
_SEEDED_SERIALIZED_CHUNK = "collective-permute-start.8"


def selftest() -> int:
    from apex_tpu.monitor import comms

    with open(FIXTURE) as f:
        rep = json.load(f)
    try:
        comms.validate_comms_report(rep)
        text = comms.render_comms_table(rep, label="fixture-step")
    except ValueError as e:
        print(f"comms_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? update scripts/comms_fixture.json to "
              "the new schema)", file=sys.stderr)
        return 1
    missing = [m for m in _FIXTURE_MARKERS if m not in text]
    if missing:
        print(text)
        print(f"comms_probe --selftest: rendering lost expected "
              f"markers: {missing}", file=sys.stderr)
        return 1
    ser = comms.serialized_collectives(rep)
    if not ser:
        print("comms_probe --selftest: the fixture's seeded serialized "
              "collective is no longer flagged — the gate is blind",
              file=sys.stderr)
        return 1
    if _SEEDED_SERIALIZED_CHUNK not in {c["name"] for c in ser}:
        print("comms_probe --selftest: the seeded serialized ring "
              f"CHUNK ({_SEEDED_SERIALIZED_CHUNK}) is no longer "
              "flagged — the gate is blind to chunked-overlap "
              "regressions", file=sys.stderr)
        return 1
    # the chunked-shape pin: the fixture's ring pair must stay
    # chunk-count-many EQUAL-payload hops (2 x 2 MiB = the displaced
    # monolithic all-gather shard) — the inventory shape the live
    # gpt_tp_overlap gate pins against the chunks=1 spelling
    chunk_pool = [c for c in rep["collectives"]
                  if c["kind"] == "collective-permute"]
    payloads = {c["operand_bytes"] for c in chunk_pool}
    if len(chunk_pool) != 2 or payloads != {2097152}:
        print("comms_probe --selftest: the fixture's chunked ring "
              f"pair drifted (n={len(chunk_pool)}, "
              f"payloads={sorted(payloads)}; want 2 x 2097152 B)",
              file=sys.stderr)
        return 1
    print(text)
    print("comms_probe --selftest: OK")
    return 0


def _build_gpt_zero2(on_tpu):
    """The flagship ZeRO-2 data-parallel GPT step: DistributedFusedAdam
    (n_buckets=4, per-bucket psum_scatter grad sync) through
    `ddp.make_train_step` — the program whose per-bucket reduce-scatter
    / backward overlap this gate exists to hold.  dp = every visible
    device (the CPU backend is forced to a 2-way virtual mesh above);
    on TPU the real 350M bench config, on CPU the smoke config."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M

    if on_tpu:
        batch, seq = 12, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=24, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False, use_flash_attention=True)
    else:
        seq = 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    dp = mesh.devices.size
    if not on_tpu:
        # the batch must shard over however many virtual devices the
        # caller's env forced (the tier-1 conftest pins 8)
        batch = max(4, dp)
    # ddp.make_train_step shard_maps the batch over dp (P("dp")) —
    # round up so the gate runs on any topology, not just ones that
    # happen to divide the bench batch
    batch = -(-batch // dp) * dp
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(
        num_shards=dp, lr=1e-4, n_buckets=4, use_pallas=on_tpu or None,
        master_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)

    def loss_fn(p, b):
        return model.loss(p, b[0], b[1])

    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")))
    del params
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    labels = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    return step, (state, None, (tokens, labels))


def _build_anatomy(target):
    """A tp_dp flagship step via gpt_anatomy's shared bench builder."""
    import jax

    import gpt_anatomy

    on_tpu = jax.default_backend() not in ("cpu",)
    _, step, args, _ = gpt_anatomy._build_bench_step(
        target, on_tpu, mode="comms")
    return step, args


def _build_gpt_tp_overlap(on_tpu, chunks=2):
    """The flagship CHUNKED-TP GPT step (ISSUE 18): tp=2
    sequence-parallel GPT with `overlap_chunks` forced (bypassing the
    tuner so the inventory is deterministic on untuned machines) —
    the column-parallel all-gather+GEMM decomposed into a ppermute
    ring interleaved with partial GEMMs, the row-parallel
    reduce-scatter chunked along the sequence.  The gate pins the
    chunked program's collective inventory against the monolithic
    (chunks=1) spelling of the SAME model: chunk-count-many smaller
    collectives, displaced all-gather bytes reappearing as equal ring
    ppermute traffic.  dp takes the remaining devices; on TPU the
    350M bench config, on CPU the smoke config."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    if on_tpu:
        batch, seq = 12, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=24, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False, use_flash_attention=True,
                        sequence_parallel=True,
                        overlap_chunks=chunks)
    else:
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0,
                        sequence_parallel=True,
                        overlap_chunks=chunks)
    _build_gpt_tp_overlap.layers = cfg.num_layers
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
    dp = mesh.devices.size // 2
    batch = -(-batch // max(1, dp)) * max(1, dp)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu,
                    master_dtype=jnp.bfloat16 if on_tpu
                    else jnp.float32)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    labels = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    return step, (opt_state, tokens, labels)


def _build_serve():
    """The flagship serving DECODE step (apex_tpu.serve, ISSUE 8).
    Single-chip serving emits ZERO collectives — this target is the
    standing negative control: any collective appearing in the decode
    inventory is a regression (an accidental cross-slot reduction
    would serialize every concurrent stream), and a future
    tensor-parallel serving path must move it OFF this gate into an
    allowlist-reviewed pattern, the PR 7 NOTE workflow."""
    import jax

    from apex_tpu.serve import build_flagship_engine

    on_tpu = jax.default_backend() not in ("cpu",)
    eng = build_flagship_engine(on_tpu)
    return eng.decode_step, (eng.params, eng.kv, eng.state)


def _build_moe():
    """The flagship expert-parallel MoE-GPT step (apex_tpu.moe, ISSUE
    13): meshed over ALL visible devices (ep = 2 on any even device
    count, dp = world/ep; batch rounded to a dp x ep multiple by the
    builder), ZeRO-2 state over the combined data axes.  The
    inventory must show the dispatch/combine all-to-alls over ['ep']
    priced by the ring formula ((n-1)/n * D / bw) — the seeded
    pattern in scripts/comms_fixture.json — next to the per-bucket
    reduce-scatters over the combined grad-sync axes."""
    import jax

    from apex_tpu.models.moe_gpt import build_moe_train_step

    on_tpu = jax.default_backend() not in ("cpu",)
    _, step, args, _ = build_moe_train_step(on_tpu)
    return step, args


BUILDERS = {
    "gpt_zero2": lambda: _build_gpt_zero2(
        __import__("jax").default_backend() not in ("cpu",)),
    "gpt": lambda: _build_anatomy("350m"),
    "bert": lambda: _build_anatomy("bert"),
    "serve": _build_serve,
    "moe": _build_moe,
    "gpt_tp_overlap": lambda: _build_gpt_tp_overlap(
        __import__("jax").default_backend() not in ("cpu",)),
}
DEFAULT_TARGETS = ("gpt_zero2", "gpt", "serve", "moe",
                   "gpt_tp_overlap")

# the chunked-TP flagship's shape knobs, shared with the inventory pin
# (kept in one place so the expected-count formula and the builder
# can't drift apart)
_TP_OVERLAP_TP = 2
_TP_OVERLAP_CHUNKS = 2


def _pin_tp_overlap_inventory(chunked, mono, layers, as_json) -> int:
    """Pin the chunked-TP program's collective inventory against the
    monolithic (chunks=1) spelling of the SAME model — the ISSUE 18
    contract: chunk-count-many smaller collectives, same total bytes
    (± padding).  Measured invariants (tp=p, c=chunks, L layers):

      * the monolithic program emits ZERO collective-permutes; the
        chunked one emits exactly 2·(2L)·(p−1)·c ring hops — (fwd
        ring + wgrad ring) × (qkv, fc1 per layer) × (p−1) hops ×
        c chunks — all carrying the SAME per-hop payload (every ring
        moves x-chunks, so hop sizes are uniform),
      * reduce-scatter bytes are conserved (c× more, each c× smaller),
      * the displaced all-gather bytes reappear as ring traffic:
        cp_bytes == 2 × (ag_bytes_mono − ag_bytes_chunked) — the
        factor 2 is the wgrad ring re-moving what the fwd ring moved
        (the monolithic spelling saves gathered x as a residual
        instead; chunking trades those bytes for overlap + memory),
      * the dp grad-sync plane (all-reduce) is byte-identical —
        chunking must not leak into the data-parallel collectives.
    """
    p, c = _TP_OVERLAP_TP, _TP_OVERLAP_CHUNKS
    fails = []
    cp = [x for x in chunked["collectives"]
          if x["kind"] == "collective-permute"]
    if mono["counts"].get("collective-permute", 0):
        fails.append("monolithic (chunks=1) spelling emits "
                     "collective-permute — the chunks=1 path is no "
                     "longer the pre-overlap program")
    want = 2 * (2 * layers) * (p - 1) * c
    if len(cp) != want:
        fails.append(f"ring ppermute count {len(cp)} != expected "
                     f"{want} (= 2 rings x {2 * layers} col sites x "
                     f"{p - 1} hops x {c} chunks)")
    sizes = sorted({x["operand_bytes"] for x in cp})
    if len(sizes) > 1:
        fails.append(f"ring hop payloads not uniform: {sizes}")
    ag_m = mono["bytes_by_kind"].get("all-gather", 0)
    ag_c = chunked["bytes_by_kind"].get("all-gather", 0)
    cp_b = chunked["bytes_by_kind"].get("collective-permute", 0)
    displaced = ag_m - ag_c
    if displaced <= 0 or cp_b <= 0 or \
            abs(cp_b - 2 * displaced) > 0.05 * max(cp_b, 1):
        fails.append(f"displaced all-gather bytes ({displaced}) != "
                     f"ring bytes/2 ({cp_b}/2) beyond padding")
    rs_m = mono["bytes_by_kind"].get("reduce-scatter", 0)
    rs_c = chunked["bytes_by_kind"].get("reduce-scatter", 0)
    if abs(rs_c - rs_m) > 0.05 * max(rs_m, 1):
        fails.append(f"reduce-scatter bytes not conserved: "
                     f"{rs_m} -> {rs_c}")
    if chunked["bytes_by_kind"].get("all-reduce", 0) != \
            mono["bytes_by_kind"].get("all-reduce", 0):
        fails.append("chunking leaked into the dp all-reduce plane")
    if as_json:
        print(json.dumps({"target": "gpt_tp_overlap_inventory_pin",
                          "n_ring_hops": len(cp),
                          "expected_ring_hops": want,
                          "ring_bytes": cp_b,
                          "displaced_all_gather_bytes": displaced,
                          "fails": fails, "ok": not fails}))
    else:
        print(f"inventory pin (chunks={c} vs monolithic): "
              f"{len(cp)} ring hop(s) of {sizes[0] if sizes else 0} B "
              f"replace {displaced} displaced all-gather byte(s)")
        for f in fails:
            print(f"inventory pin: FAIL — {f}")
        print(f"inventory pin: {'FAIL' if fails else 'PASS'}")
        print()
    return 1 if fails else 0


def _gate_report(rep_dict, target, allowlist, as_json) -> int:
    from apex_tpu.monitor import comms

    ser = comms.serialized_collectives(rep_dict)
    new, allowed = comms.apply_allowlist(ser, allowlist, target)
    if as_json:
        print(json.dumps({"target": target, "report": rep_dict,
                          "new": new, "allowlisted": allowed}))
    else:
        print(comms.render_comms_table(rep_dict, label=target))
        if allowed:
            print(f"({len(allowed)} allowlisted serialized "
                  f"collective(s) accepted)")
        if not rep_dict.get("async_supported"):
            print("gate: PASS (overlap not measurable on this backend)")
        elif new:
            print(f"gate: FAIL — {len(new)} serialized collective(s) "
                  "not in scripts/comms_allowlist.txt")
        else:
            print("gate: PASS")
        print()
    return 1 if new else 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="comms/overlap CI gate for the flagship train steps")
    ap.add_argument("targets", nargs="*",
                    help=f"subset of {sorted(BUILDERS)} "
                         f"(default: {list(DEFAULT_TARGETS)})")
    ap.add_argument("--selftest", action="store_true",
                    help="validate + render the committed fixture; "
                         "exit 1 on schema drift")
    ap.add_argument("--report", metavar="PATH", default=None,
                    help="gate a saved CommsReport JSON instead of "
                         "building steps")
    ap.add_argument("--backend", metavar="NAME", default=None,
                    help="JAX_PLATFORMS for the build (e.g. tpu); "
                         "consumed before the first jax import by the "
                         "argv peek above — registered here so argparse "
                         "accepts it")
    ap.add_argument("--allowlist", default=ALLOWLIST,
                    help="allowlist file (default: the committed one)")
    ap.add_argument("--json", action="store_true",
                    help="print JSON instead of tables")
    args = ap.parse_args()
    if args.selftest:
        return selftest()

    from apex_tpu.monitor import comms

    allowlist = []
    if os.path.exists(args.allowlist):
        with open(args.allowlist) as f:
            allowlist = comms.parse_allowlist(f.read())

    if args.report is not None:
        with open(args.report) as f:
            rep = json.load(f)
        comms.validate_comms_report(rep)
        return _gate_report(
            rep, os.path.basename(args.report), allowlist, args.json)

    targets = args.targets or list(DEFAULT_TARGETS)
    bad = [t for t in targets if t not in BUILDERS]
    if bad:
        ap.error(f"unknown target(s) {bad}; choices: {sorted(BUILDERS)}")

    from apex_tpu.parallel import mesh as M

    rc = 0
    for t in targets:
        step, step_args = BUILDERS[t]()
        rep = comms.comms_report(step, step_args)
        rc |= _gate_report(rep.to_dict(), t, allowlist, args.json)
        if t == "gpt_tp_overlap":
            # the chunked target carries a second gate: its inventory
            # pinned against the monolithic spelling of the same model
            import jax

            on_tpu = jax.default_backend() not in ("cpu",)
            mono_step, mono_args = _build_gpt_tp_overlap(
                on_tpu, chunks=1)
            mono = comms.comms_report(mono_step, mono_args)
            rc |= _pin_tp_overlap_inventory(
                rep.to_dict(), mono.to_dict(),
                _build_gpt_tp_overlap.layers, args.json)
        M.destroy_model_parallel()
    if not args.json:
        verdict = "CLEAN" if rc == 0 else "SERIALIZED — gate fails"
        print(f"comms_probe: {len(targets)} target(s), {verdict}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
