"""Scan-slope (dispatch-amortized) decomposition of the GPT attention
sublayer at the 350M bench shape: how much of the 4.75 ms/layer is the
flash kernel, the two projections, and layout glue (qkv split +
(b,s,h,d)<->(b,h,s,d) transposes)?  Decides whether killing the
transposes can close the 48.9k -> 50k tok/s gap.

MEASURED CONCLUSION (round 5, real chip): no.  einsum variants whose
projection output is already kernel-layout (b,h,s,d) — one packed
'bsh,hknd->kbnsd' or three separate — time WITHIN NOISE of the
split+transpose sublayer (4.51-4.85 vs 4.53 ms/layer), and the
standalone split+transpose loop measures at the slope-timing noise
floor.  XLA already schedules the relayouts at negligible marginal
cost; the attention plateau is the d=64 score-contraction shape bound
(docs/PERF.md anatomy), not layout glue.  Kept as the record of the
negative result."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PEAK = 197e12
B, H, S, Dh = 12, 16, 1024, 64
HID = H * Dh


def _scan_time(fn, args, iters=20, reps=3):
    def make(length):
        def many(*a):
            def body(carry, _):
                out = fn(*((a[0] + carry.astype(a[0].dtype),) + a[1:]))
                return sum(jnp.sum(l.astype(jnp.float32))
                           for l in jax.tree.leaves(out)) * 1e-30, None
            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = max(1, iters // 5), iters
    return (total(make(hi)) - total(make(lo))) / (hi - lo)


def fb(fn):
    def run(*args):
        out, vjp = jax.vjp(fn, *args)
        return (out,) + vjp(out)
    return run


def main():
    key = jax.random.PRNGKey(0)
    from apex_tpu.ops.flash_attention import flash_attention

    x = jax.random.normal(key, (B, S, HID), jnp.bfloat16)
    wqkv = jax.random.normal(key, (HID, 3 * HID), jnp.bfloat16) * 0.02
    wo = jax.random.normal(key, (HID, HID), jnp.bfloat16) * 0.02
    q = jax.random.normal(key, (B, H, S, Dh), jnp.bfloat16)
    k = jax.random.normal(key, (B, H, S, Dh), jnp.bfloat16) * 0.5
    v = jax.random.normal(key, (B, H, S, Dh), jnp.bfloat16) * 0.5

    def attn(x, wqkv, wo):
        qkv = x @ wqkv
        qq, kk, vv = jnp.split(qkv, 3, axis=-1)

        def heads_of(t):
            return t.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)

        o = flash_attention(heads_of(qq), heads_of(kk), heads_of(vv),
                            causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, HID)
        return o @ wo

    def kernel_only(q, k, v):
        return flash_attention(q, k, v, causal=True)

    def projs_only(x, wqkv, wo):
        qkv = x @ wqkv
        # consume qkv without the head transposes; same matmul shapes
        o = qkv[..., :HID] + qkv[..., HID:2 * HID] + qkv[..., 2 * HID:]
        return o @ wo

    def glue_only(x3):
        # the pure layout work: split + head transposes + merge back
        qq, kk, vv = jnp.split(x3, 3, axis=-1)

        def heads_of(t):
            return t.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)

        a, b, c = heads_of(qq), heads_of(kk), heads_of(vv)
        o = (a + b + c).transpose(0, 2, 1, 3).reshape(B, S, HID)
        return o

    x3 = jax.random.normal(key, (B, S, 3 * HID), jnp.bfloat16)

    def attn_einsum(x, wqkv, wo):
        # projection output ALREADY in kernel layout: XLA folds the
        # (b,s,h,d)->(b,h,s,d) relayout into the dot epilogue (or a
        # cheaper fused copy) instead of separate transpose passes
        w4 = wqkv.reshape(HID, 3, H, Dh)
        qkv = jnp.einsum("bsh,hknd->kbnsd", x, w4,
                         preferred_element_type=jnp.float32
                         ).astype(x.dtype)
        o = flash_attention(qkv[0], qkv[1], qkv[2], causal=True)
        w2 = wo.reshape(H, Dh, HID)
        return jnp.einsum("bnsd,ndh->bsh", o, w2,
                          preferred_element_type=jnp.float32
                          ).astype(x.dtype)

    def attn_einsum3(x, wqkv, wo):
        w4 = wqkv.reshape(HID, 3, H, Dh)
        q = jnp.einsum("bsh,hnd->bnsd", x, w4[:, 0],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        k = jnp.einsum("bsh,hnd->bnsd", x, w4[:, 1],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        v = jnp.einsum("bsh,hnd->bnsd", x, w4[:, 2],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        o = flash_attention(q, k, v, causal=True)
        w2 = wo.reshape(H, Dh, HID)
        return jnp.einsum("bnsd,ndh->bsh", o, w2,
                          preferred_element_type=jnp.float32
                          ).astype(x.dtype)

    t_attn = _scan_time(fb(attn), (x, wqkv, wo))
    t_e1 = _scan_time(fb(attn_einsum), (x, wqkv, wo))
    t_e3 = _scan_time(fb(attn_einsum3), (x, wqkv, wo))
    t_kern = _scan_time(fb(kernel_only), (q, k, v))
    t_proj = _scan_time(fb(projs_only), (x, wqkv, wo))
    t_glue = _scan_time(fb(glue_only), (x3,))

    fl_proj = 2 * B * S * HID * 4 * HID * 3
    print(f"sublayer  {t_attn*1e3:7.3f} ms/layer  x24 {24*t_attn*1e3:6.1f} ms")
    print(f"einsum-1  {t_e1*1e3:7.3f} ms/layer  x24 {24*t_e1*1e3:6.1f} ms")
    print(f"einsum-3  {t_e3*1e3:7.3f} ms/layer  x24 {24*t_e3*1e3:6.1f} ms")
    print(f"kernel    {t_kern*1e3:7.3f} ms/layer")
    print(f"projs     {t_proj*1e3:7.3f} ms/layer "
          f"({fl_proj/t_proj/1e12:.0f} TF/s {100*fl_proj/t_proj/PEAK:.0f}%pk)")
    print(f"glue-only {t_glue*1e3:7.3f} ms/layer (split+transposes std-alone)")
    resid = t_attn - t_kern - t_proj
    print(f"sublayer - kernel - projs = {resid*1e3:7.3f} ms/layer "
          f"-> x24 = {24*resid*1e3:.1f} ms of removable glue?")


if __name__ == "__main__":
    main()
