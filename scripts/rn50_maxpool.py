"""True maxpool fwd+bwd cost (random cotangent) and a candidate
equality-routed custom-vjp alternative to SelectAndScatter."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

B = 256
x = jax.random.normal(jax.random.PRNGKey(0), (B, 112, 112, 64),
                      jnp.bfloat16)
dy = jax.random.normal(jax.random.PRNGKey(1), (B, 56, 56, 64),
                       jnp.bfloat16)


def timeit(f, *args, iters=8, warmup=2):
    for _ in range(warmup):
        out = f(*args)
    _ = np.asarray(jax.tree.leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    outs = [f(*args) for _ in range(iters)]
    _ = np.asarray(jax.tree.leaves(outs[-1])[0].ravel()[:1])
    return (time.perf_counter() - t0) / iters


def mp(x):
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                             (1, 2, 2, 1), "SAME")


def fb_ref(x, dy):
    y, vjp = jax.vjp(mp, x)
    return y, vjp(dy)[0]


t = timeit(jax.jit(fb_ref), x, dy)
print(f"reduce_window+SelectAndScatter fwd+bwd: {t*1e3:.3f} ms",
      flush=True)


# candidate: equality-routed backward — dx[p] = sum over the <=4
# windows containing p of dy[w] * (x[p] == y[w]) / ties(w).
# Gradient differs from select-and-scatter ONLY on exact fp ties
# (routes split instead of first-wins).
def mp_eq(x):
    return mp(x)


def mp_eq_fwd(x):
    y = mp(x)
    return y, (x, y)


def _win_sum(a):
    """sum over 3x3/s2 windows transposed back to input positions."""
    # dilate dy to input grid: conv_transpose-like via reduce_window's
    # transpose = pad + gather; use lax.pad + conv with ones? simplest:
    # scatter-free: upsample dy to the padded input grid then 3x3 sum
    raise NotImplementedError


def mp_eq_bwd(res, dy):
    x, y = res
    # route dy[w] to every input position equal to the window max,
    # normalized by tie count.  Windows overlap (k3 s2), so express as:
    # for each of the 9 (di, dj) offsets, the window at output (i, j)
    # touches input (2i+di-1, 2j+dj-1); accumulate via dynamic slicing
    # on the padded grid — all dense vector ops, no SelectAndScatter.
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    bb, hh, ww, cc = x.shape
    pad = [(0, 0), (1, 2), (1, 2), (0, 0)]
    xp = jnp.pad(xf, pad, constant_values=-jnp.inf)
    # tie count per window
    ties = jnp.zeros_like(yf)
    for di in range(3):
        for dj in range(3):
            xs = lax.slice(xp, (0, di, dj, 0),
                           (bb, di + 2 * y.shape[1], dj + 2 * y.shape[2],
                            cc), (1, 2, 2, 1))
            ties = ties + (xs == yf).astype(jnp.float32)
    contrib = dyf / ties
    dxp = jnp.zeros(xp.shape, jnp.float32)
    for di in range(3):
        for dj in range(3):
            xs = lax.slice(xp, (0, di, dj, 0),
                           (bb, di + 2 * y.shape[1], dj + 2 * y.shape[2],
                            cc), (1, 2, 2, 1))
            upd = jnp.where(xs == yf, contrib, 0.0)
            # scatter-add back at stride 2 — as a dynamic_update via
            # strided "dilation": build with lax.pad(interior=1)
            upd_dil = lax.pad(upd, jnp.float32(0),
                              [(0, 0, 0), (di, xp.shape[1] - di - 1 -
                                           2 * (y.shape[1] - 1), 1),
                               (dj, xp.shape[2] - dj - 1 -
                                2 * (y.shape[2] - 1), 1), (0, 0, 0)])
            dxp = dxp + upd_dil
    dx = lax.slice(dxp, (0, 1, 1, 0), (bb, 1 + hh, 1 + ww, cc))
    return (dx.astype(x.dtype),)


mp_eq = jax.custom_vjp(mp_eq)
mp_eq.defvjp(mp_eq_fwd, mp_eq_bwd)


def fb_eq(x, dy):
    y, vjp = jax.vjp(mp_eq, x)
    return y, vjp(dy)[0]


t = timeit(jax.jit(fb_eq), x, dy)
print(f"equality-routed custom vjp fwd+bwd:     {t*1e3:.3f} ms",
      flush=True)

# sanity: grads agree where no ties (random floats -> ties improbable)
a, ga = jax.jit(fb_ref)(x, dy)
b, gb = jax.jit(fb_eq)(x, dy)
print("fwd equal:", bool(jnp.all(a == b)),
      " bwd max diff:", float(jnp.max(jnp.abs(
          ga.astype(jnp.float32) - gb.astype(jnp.float32)))))
