"""Does widening the flat-buffer 2D view lift the bf16 Adam kernel's
HBM bandwidth?  docs/PERF.md: bf16-state Adam runs ~500 GB/s vs the
fp32 kernel's 721 GB/s because a (512, 128)-bf16 block row is a
256-byte burst (fp32 rows are 512 B).  A (rows, 256) or (rows, 512)
bf16 view doubles/quadruples the row burst with the same elementwise
kernel.  Measures the full Adam update for lane widths 128/256/512 and
block rows 256/512/1024.

MEASURED CONCLUSION (round 5, real chip, 0.5 Gi elements): widening
lanes makes it WORSE — 128 lanes 20.8-23.7 ms, 256 lanes ~49 ms, 512
lanes ~47 ms (Mosaic handles >128-lane tiles as multi-register values
and the emitted code slows 2.3x); rows=512 is the knee.  So the bf16
Adam pass is VPU-bound as docs/PERF.md says, not DMA-burst-bound, and
the (512, 128) flat view stands.  Kept as the record of the negative
result."""
import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from apex_tpu.ops.optimizer_kernels import _adam_kernel, _adam_fold_scalars

N = 536_870_912  # 0.5 Gi elements, divisible by 1024*512


def adam_lanes(p, m, v, g, scalars, lanes, rows):
    shape = (N // lanes, lanes)
    p2, m2, v2, g2 = (a.reshape(shape) for a in (p, m, v, g))
    grid = shape[0] // rows
    spec = pl.BlockSpec((rows, lanes), lambda i: (i, 0))
    sspec = pl.BlockSpec((9, 1), lambda i: (0, 0))
    kernel = functools.partial(_adam_kernel, eps=1e-8,
                               weight_decay=0.0, adam_w_mode=True)
    pn, mn, vn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(shape, x.dtype)
                   for x in (p2, m2, v2)],
        input_output_aliases={0: 0, 1: 1, 2: 2},
    )(p2, m2, v2, g2, scalars)
    return pn.reshape(-1), mn.reshape(-1), vn.reshape(-1)


def main():
    dt = jnp.bfloat16
    p = jnp.zeros((N,), dt)
    m = jnp.zeros((N,), dt)
    v = jnp.zeros((N,), dt)
    g = jnp.full((N,), 1e-3, dt)
    scalars = np.asarray(_adam_fold_scalars(1e-3, 10, 0.9, 0.999, True,
                                            1.0, False))
    scalars = jnp.asarray(scalars)
    nbytes = N * 2 * 7  # r/w p,m,v + r g

    for lanes in (128, 256, 512):
        for rows in (256, 512, 1024):
            # deliberate jit-per-candidate: each (lanes, rows) point is
            # a different kernel; the probe pays one compile per point
            step = jax.jit(functools.partial(adam_lanes, lanes=lanes,  # lint: disable=HS405
                                             rows=rows),
                           donate_argnums=(0, 1, 2))
            try:
                pp, mm, vv = step(p, m, v, g, scalars)
                np.asarray(pp[:1])
                t0 = time.perf_counter()
                iters = 10
                for _ in range(iters):
                    pp, mm, vv = step(pp, mm, vv, g, scalars)
                np.asarray(pp[:1])
                dtms = (time.perf_counter() - t0) / iters * 1e3
                print(f"lanes={lanes:4d} rows={rows:5d}: {dtms:6.2f} ms "
                      f"{nbytes/dtms*1e3/1e9:6.0f} GB/s")
                p, m, v = pp, mm, vv
            except Exception as e:
                print(f"lanes={lanes:4d} rows={rows:5d}: FAILED "
                      f"{repr(e)[:90]}")
                p = jnp.zeros((N,), dt)
                m = jnp.zeros((N,), dt)
                v = jnp.zeros((N,), dt)


if __name__ == "__main__":
    main()
