"""Context-parallelism perf measurements (VERDICT r4 next-#4).

Modes:
  chip — real-TPU, single chip: monolithic 32k flash fwd+bwd vs the
    same work issued as ring-style (s_local x s_local) chunk calls —
    quantifies the per-chunk overhead of the ring's repeated _fwd_impl
    invocations and the block-skipping efficiency lost to chunking.
  mesh — 8-device virtual CPU mesh: contiguous vs zigzag causal ring
    step time (the load-balance claim; each virtual device is an XLA
    host thread, so the imbalanced contiguous ring's straggler shows
    up in wall-clock).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def chip():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.flash_attention import flash_attention

    B, H, S, D = 1, 8, 32768, 64
    n = 8
    s_local = S // n
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.bfloat16)
               for kk in ks)

    def timeit(f, *args, iters=5):
        out = f(*args)
        _ = np.asarray(jax.tree.leaves(out)[0].ravel()[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(*args)
        _ = np.asarray(jax.tree.leaves(out)[0].ravel()[0])
        return (time.perf_counter() - t0) / iters

    mono = jax.jit(jax.grad(
        lambda q, k, v: flash_attention(q, k, v, causal=True).astype(
            jnp.float32).mean(), argnums=(0, 1, 2)))
    t_mono = timeit(mono, q, k, v)
    print(f"monolithic 32k causal flash fwd+bwd: {t_mono*1e3:8.1f} ms",
          flush=True)

    # ring-style chunking on ONE chip: every (rank, src) chunk pair a
    # causal n=8 ring would run — (n²+n)/2 chunk calls of
    # (s_local x s_local), diagonal ones causal — then summed grads.
    # Matches the ring's total chunk work (spread over n devices).
    def chunked(q, k, v):
        def loss(q, k, v):
            total = 0.0
            for r in range(n):
                qs = jax.lax.dynamic_slice_in_dim(q, r * s_local,
                                                  s_local, 2)
                for src in range(r + 1):
                    kss = jax.lax.dynamic_slice_in_dim(k, src * s_local,
                                                       s_local, 2)
                    vs = jax.lax.dynamic_slice_in_dim(v, src * s_local,
                                                      s_local, 2)
                    o = flash_attention(qs, kss, vs, causal=(src == r))
                    total = total + o.astype(jnp.float32).mean()
            return total

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    t_chunk = timeit(jax.jit(chunked), q, k, v, iters=3)
    n_calls = n * (n + 1) // 2
    print(f"chunked ({n_calls} ring-chunk calls):  {t_chunk*1e3:8.1f} ms"
          f"  ({(t_chunk-t_mono)/n_calls*1e3:+.2f} ms/chunk overhead vs "
          "monolithic)", flush=True)
    print(f"per-device ring critical path ~ {t_chunk/n*1e3:.1f} ms "
          f"(contiguous worst rank ~ {t_chunk*2/n*1e3:.1f})", flush=True)


def mesh():
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel import mesh as M
    from apex_tpu.parallel.context_parallel import (
        ring_attention,
        zigzag_shard,
    )

    N = 8
    msh = M.initialize_model_parallel(tensor_model_parallel_size=N)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    S = 8192
    q, k, v = (jax.random.normal(kk, (1, 2, S, 64), jnp.float32)
               for kk in ks)

    def run(layout):
        args = (tuple(zigzag_shard(x, N) for x in (q, k, v))
                if layout == "zigzag" else (q, k, v))
        f = jax.jit(shard_map(
            lambda q, k, v: ring_attention(q, k, v, "tp", causal=True,
                                           layout=layout),
            mesh=msh, in_specs=(P(None, None, "tp"),) * 3,
            out_specs=P(None, None, "tp"), check_vma=False))
        out = f(*args)
        _ = np.asarray(out.ravel()[0])
        t0 = time.perf_counter()
        for _ in range(5):
            out = f(*args)
        _ = np.asarray(out.ravel()[0])
        return (time.perf_counter() - t0) / 5

    t_c = run("contiguous")
    t_z = run("zigzag")
    print(f"8-way virtual mesh, {S}-token causal ring fwd: "
          f"contiguous {t_c*1e3:.1f} ms vs zigzag {t_z*1e3:.1f} ms "
          f"({t_c/t_z:.2f}x)", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "chip"
    if which == "chip":
        chip()
    else:
        mesh()
