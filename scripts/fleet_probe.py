"""Fleet fault-tolerance CI gate (ISSUE 11): multi-host checkpoint
commit kill matrix + elastic-resume orchestration, end to end.

usage:
  python scripts/fleet_probe.py             # full probe (kill matrix)
  python scripts/fleet_probe.py --smoke     # tier-1 subset (bounded)
  python scripts/fleet_probe.py --selftest  # fixture drift gate
  python scripts/fleet_probe.py --json      # machine-readable result

The full probe launches REAL multi-process fleets through
`apex_tpu.parallel.multiproc` (2 controller processes × 4 emulated CPU
devices), kills a child AT each chaos fail point, and asserts the
commit protocol + orchestrator hold their contracts:

  1. BASELINE   — a 2-host fleet trains `--steps` steps (every host
                  computes the identical deterministic dp=4 step; each
                  host WRITES only its own ranks' shards), committing
                  a multi-host checkpoint at `--save-at` through the
                  sub-manifest → rank-0 barrier protocol.  The two
                  hosts' loss/canonical results must agree BITWISE —
                  the free cross-host consistency check.
  2. KILL MATRIX — one fleet per fail point (`ckpt.mid_shards` = shard
                  write, `host.before_submanifest`,
                  `host.before_barrier`, `rank.lost_at_step`): a
                  specific host really dies (os._exit, no cleanup) at
                  that point during a LATER save.  Afterward the
                  shared directory's `latest_committed_step` must
                  still be `--save-at` on every survivor, the commit
                  must `verify_shards`-load, and a surviving process 0
                  must have REFUSED the torn commit with the dead host
                  named (the barrier timeout path).
  3. RESUME     — `ElasticOrchestrator` resumes the baseline commit:
                  equal topology (dp=4) is BITWISE on losses and the
                  canonical master flat; a watchdog-driven lost rank
                  mid-segment triggers the full detect → dump →
                  rebuild at dp=2 → re-shard restore → resume cycle,
                  allclose at the resume_probe tolerances, with the
                  flight dump naming the last committed step,
                  `fleet_resumes == 1`, and ZERO steady-state
                  recompiles after either resume (RecompileSentry).
  4. NEGATIVE   — a seeded truncated shard inside the committed step
                  must be refused with the damaged rank NAMED (the
                  gate's own teeth), and the orchestrator on a
                  checkpoint-free directory must ESCALATE by name.

CPU-backend honesty: jax cannot run cross-process collectives on the
CPU backend (XLA: "Multiprocess computations aren't implemented"), so
each emulated host replicates the identical deterministic compute and
the probe distributes the STORAGE plane — per-host shard writes,
sub-manifests, the rank-0 commit barrier, and real process deaths —
which is exactly the layer `checkpoint.multihost` owns and a real TPU
pod would exercise with sharded compute.  On TPU hardware run the
probe with `--backend tpu` on a multi-host slice.

`--selftest` is the tier-1 fixture-drift gate (mirrors
`resume_probe.py --selftest`): the committed fixture
(scripts/fleet_fixture.json: a global manifest + the two sub-manifests
it was merged from) must still validate and re-merge to the same
global fields, and a one-host-missing barrier must be REFUSED with the
absent host named — the selftest's negative control.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the orchestrator half needs dp up to 4 in THIS process: force an
# 8-way virtual mesh on CPU (must precede the first jax import)
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fleet_fixture.json")
KILLED_RC = 77          # a chaos-killed worker's exit code


class _SkipToReport(Exception):
    """Abandon the remaining probe sections but still print the
    collected failures (a missing prerequisite, not a new finding)."""


# ---------------------------------------------------------------------------
# selftest (tier-1, no jax import)
# ---------------------------------------------------------------------------

def selftest() -> int:
    import shutil
    import tempfile

    import numpy as np

    from apex_tpu.checkpoint import multihost as MH
    from apex_tpu.checkpoint import validate_manifest
    from apex_tpu.checkpoint import sharded as S

    with open(FIXTURE) as f:
        fixture = json.load(f)
    try:
        validate_manifest(fixture["global"])
    except S.CheckpointError as e:
        print(f"fleet_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? regenerate scripts/fleet_fixture.json "
              "with the new manifest schema)", file=sys.stderr)
        return 1

    # merge math: the committed sub-manifests must still merge to the
    # committed global manifest's fields (rank coverage, dtypes, files)
    merged = MH.merge_submanifests(
        fixture["submanifests"], step=fixture["global"]["step"],
        flat_layout=fixture["global"]["flat_layout"],
        scaler=fixture["global"]["scaler"])
    if merged["fields"] != fixture["global"]["fields"]:
        print("fleet_probe --selftest: sub-manifest merge no longer "
              "reproduces the committed global manifest's fields",
              file=sys.stderr)
        return 1

    # rank-coverage teeth: dropping one host must be refused naming
    # the missing ranks
    try:
        MH.merge_submanifests(fixture["submanifests"][:1],
                              step=fixture["global"]["step"],
                              flat_layout=fixture["global"]["flat_layout"])
    except MH.MultihostCommitError as e:
        if "missing" not in str(e):
            print("fleet_probe --selftest: one-host merge refusal lost "
                  f"its missing-rank naming: {e}", file=sys.stderr)
            return 1
    else:
        print("fleet_probe --selftest: merging HALF the fleet was NOT "
              "refused — rank coverage lost its teeth", file=sys.stderr)
        return 1

    # negative control: a barrier over a directory where host 1 never
    # published must time out REFUSING, with host 1 named
    tmp = tempfile.mkdtemp(prefix="fleet_probe_selftest_")
    try:
        d = S.step_dir(tmp, 3)
        sub = MH.write_host_shards(
            d, 3,
            {"params_shard": ("sharded",
                              {0: np.arange(4, dtype=np.float32)})},
            host=0, num_processes=2)
        MH.publish_submanifest(d, sub)
        try:
            MH.gather_submanifests(d, 2, step=3, timeout_s=0.2,
                                   poll_s=0.02)
        except MH.MultihostCommitError as e:
            if "host 1" not in str(e) or "refusing to commit" not in str(e):
                print("fleet_probe --selftest: barrier refusal lost its "
                      f"host naming: {e}", file=sys.stderr)
                return 1
        else:
            print("fleet_probe --selftest: a HALF-PUBLISHED step was "
                  "committed — the barrier lost its teeth",
                  file=sys.stderr)
            return 1
        if os.path.exists(os.path.join(d, S.MANIFEST)):
            print("fleet_probe --selftest: refusal left a manifest "
                  "behind", file=sys.stderr)
            return 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("fleet_probe --selftest: OK")
    return 0


# ---------------------------------------------------------------------------
# shared training segment (worker fleet AND in-process orchestrator)
# ---------------------------------------------------------------------------

def _make_batches(n_steps, batch, seq, vocab):
    import numpy as np
    rng = np.random.RandomState(4321)
    out = []
    for _ in range(n_steps):
        t = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
        out.append((t, np.roll(t, -1, axis=1)))
    return out


def _config():
    from apex_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=64, seq_len=16, hidden=32,
                     num_layers=2, num_heads=2, dropout=0.0), 8


def _build_segment(dp, ckpt_dir, *, resume_step=None, manager_kw=None):
    """Fresh dp-way ZeRO-2 GPT train step + CheckpointManager (resumed
    from `resume_step` when given).  Returns a dict of live pieces —
    the worker and the orchestrator sessions drive it differently."""
    import jax
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.checkpoint import CheckpointManager
    from apex_tpu.monitor.compile import RecompileSentry
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M
    from apex_tpu.models.gpt import GPT
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    cfg, batch = _config()
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:dp])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    amp_state = amp.initialize(opt_level="O0", loss_scale="dynamic")
    scaler = amp_state.loss_scalers[0]
    opt = DistributedFusedAdam(num_shards=dp, lr=1e-2, n_buckets=2,
                               use_pallas=False)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    manager = CheckpointManager(ckpt_dir, opt, every_n_steps=1, keep=8,
                                **(manager_kw or {}))
    if resume_step is not None:
        state, restored_scaler, _ = manager.restore(mesh,
                                                    step=resume_step)
        if restored_scaler is not None:
            scaler = restored_scaler
    step = ddp.make_train_step(
        lambda p, b: model.loss(p, b[0], b[1]), opt, mesh,
        amp_state=amp_state, batch_spec=(P("dp"), P("dp")))
    sentry = RecompileSentry(step, name=f"fleet_probe_dp{dp}",
                             warn=False)
    return {"mesh": mesh, "opt": opt, "manager": manager,
            "sentry": sentry, "state": state, "scaler": scaler,
            "batch": batch, "cfg": cfg, "np": np}


def _canonical(seg):
    import numpy as np

    from apex_tpu.checkpoint import sharded as S
    glob = np.asarray(seg["state"].params_shard)
    return S.canonical_flat(list(np.split(glob, seg["opt"].num_shards)),
                            seg["opt"].shard_layout())


def _drive(seg, batches, start, stop, *, save_at=(), kill_save=None,
           on_step=None):
    """Run steps [start, stop); save (multihost-aware) on the listed
    steps.  `kill_save`: arm APEX_TPU_CHAOS_SAVE's fail points right
    before saving that step.  `on_step(i)` runs before each step (the
    orchestrator feeds its watchdog there).  Returns (losses,
    steady_recompiles, refusal-or-None)."""
    import numpy as np

    from apex_tpu.checkpoint import MultihostCommitError, chaos

    sentry, manager = seg["sentry"], seg["manager"]
    state, scaler = seg["state"], seg["scaler"]
    losses, calls, refusal = [], 0, None
    for i in range(start, stop):
        if on_step is not None:
            on_step(i)
        chaos.check("rank.lost_at_step")
        t, l = batches[i]
        state, scaler, loss = sentry(state, scaler, (t, l))
        calls += 1
        if calls == 2:
            _ = np.asarray(loss)
            sentry.mark_steady()
        losses.append(float(np.asarray(loss, np.float32)))
        if (i + 1) in save_at or (i + 1) == kill_save:
            if (i + 1) == kill_save:
                chaos.arm_from_env(var="APEX_TPU_CHAOS_SAVE")
            try:
                manager.save(i + 1, state, scaler,
                             model_state={"rng_key": np.asarray(
                                 [7, i + 1], np.uint32)})
                manager.wait()
            except MultihostCommitError as e:
                refusal = str(e)  # survivor refused a torn commit —
                # correct behavior; training would continue
    if calls == 1:
        sentry.mark_steady()
    seg["state"], seg["scaler"] = state, scaler
    return losses, int(sentry.steady_recompiles), refusal


# ---------------------------------------------------------------------------
# worker mode (one emulated host, spawned via parallel/multiproc)
# ---------------------------------------------------------------------------

def worker(args) -> int:
    import numpy as np

    from apex_tpu.checkpoint import chaos
    from apex_tpu.checkpoint.chaos import SimulatedPreemption
    from apex_tpu.parallel import mesh as M

    pid = int(os.environ.get("APEX_TPU_PROCESS_ID", "0"))
    nproc = int(os.environ.get("APEX_TPU_NUM_PROCESSES", "1"))
    chaos.arm_from_env()  # rank.lost_at_step fires mid-training
    cfg, batch = _config()
    batches = _make_batches(args.steps, batch, cfg.seq_len,
                            cfg.vocab_size)
    result = {"proc": pid, "nproc": nproc}
    try:
        seg = _build_segment(
            args.dp, args.ckpt_dir,
            manager_kw=dict(process_id=pid, num_processes=nproc,
                            async_write=False,
                            attempt=args.attempt,
                            barrier_timeout_s=args.barrier_timeout))
        losses, retraces, refusal = _drive(
            seg, batches, 0, args.steps, save_at=(args.save_at,),
            kill_save=args.kill_at)
        M.destroy_model_parallel()
    except SimulatedPreemption:
        # the SIGKILL stand-in: die HARD, no cleanup, no result file —
        # exactly what a preempted host leaves behind
        os._exit(KILLED_RC)
    result.update(
        losses=losses, steady_recompiles=retraces,
        refusal=refusal,
        last_committed=seg["manager"].last_committed_step,
        stats=seg["manager"].stats())
    np.save(os.path.join(args.result_dir, f"canonical{pid}.npy"),
            _canonical(seg))
    with open(os.path.join(args.result_dir, f"proc{pid}.json"),
              "w") as f:
        json.dump(result, f, sort_keys=True)
    return 0


# ---------------------------------------------------------------------------
# fleet driver
# ---------------------------------------------------------------------------

def _launch_fleet(ckpt_dir, result_dir, *, steps, save_at, kill_at=None,
                  chaos_env=None, port=12411, timeout=300.0):
    """One 2-host × 4-device fleet through parallel/multiproc.  Chaos
    env vars are injected for the children and scrubbed after."""
    from apex_tpu.parallel import multiproc

    os.makedirs(result_dir, exist_ok=True)
    saved = {}
    for k, v in (chaos_env or {}).items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    try:
        argv = ["--nproc", "2", "--devices-per-proc", "4",
                "--coordinator", f"127.0.0.1:{port}",
                "--timeout", str(timeout), "--grace", "120",
                os.path.abspath(__file__), "--worker",
                "--ckpt-dir", ckpt_dir, "--result-dir", result_dir,
                "--steps", str(steps), "--save-at", str(save_at),
                "--dp", "4", "--barrier-timeout", "6"]
        if kill_at is not None:
            argv += ["--kill-at", str(kill_at)]
        return multiproc.main(argv)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _read_results(result_dir):
    import numpy as np
    out = {}
    for p in (0, 1):
        j = os.path.join(result_dir, f"proc{p}.json")
        if os.path.exists(j):
            with open(j) as f:
                out[p] = json.load(f)
            c = os.path.join(result_dir, f"canonical{p}.npy")
            if os.path.exists(c):
                out[p]["canonical"] = np.load(c)
    return out


def probe(steps: int, save_at: int, as_json: bool, smoke: bool) -> int:
    import shutil
    import tempfile

    import numpy as np

    from apex_tpu.checkpoint import (
        ElasticOrchestrator, EscalationError, IncompleteCheckpointError,
        chaos, latest_committed_step, load_model_state, verify_shards)
    from apex_tpu.checkpoint import sharded as S
    from apex_tpu.checkpoint.chaos import LostRankWatchdog
    from apex_tpu.monitor.trace.straggler import StragglerDetector
    from apex_tpu.parallel import mesh as M

    root = tempfile.mkdtemp(prefix="fleet_probe_")
    result = {"steps": steps, "save_at": save_at, "smoke": smoke,
              "dp_fleet": 4, "n_hosts": 2}
    failures = []
    port = [12431]

    def fleet(tag, **kw):
        port[0] += 1
        d = os.path.join(root, tag, "ckpt")
        r = os.path.join(root, tag, "results")
        os.makedirs(d, exist_ok=True)
        rc = _launch_fleet(d, r, steps=steps, save_at=save_at,
                           port=port[0], **kw)
        return d, _read_results(r), rc

    try:
        # 1. BASELINE fleet: both hosts finish, commit at save_at,
        # agree bitwise (the free cross-host consistency check)
        base_dir, base, rc = fleet("baseline")
        if rc != 0:
            failures.append(f"baseline fleet exited {rc}")
        if sorted(base) != [0, 1]:
            failures.append(f"baseline: missing host results "
                            f"{sorted(base)}")
        else:
            if base[0]["losses"] != base[1]["losses"] or not \
                    np.array_equal(base[0]["canonical"],
                                   base[1]["canonical"]):
                failures.append(
                    "baseline: the two hosts' trajectories are NOT "
                    "bitwise identical — deterministic replication "
                    "broke, every downstream claim is void")
            for p, r in base.items():
                if r["steady_recompiles"]:
                    failures.append(f"baseline host {p}: "
                                    f"{r['steady_recompiles']} steady "
                                    "recompiles")
        lc = latest_committed_step(base_dir)
        result["baseline_committed"] = lc
        if lc != save_at:
            failures.append(f"baseline: latest committed {lc}, "
                            f"expected {save_at}")
        barrier = base.get(0, {}).get("stats", {}).get(
            "ckpt_commit_barrier_s")
        result["ckpt_commit_barrier_s"] = barrier
        if barrier is None:
            failures.append("baseline: process 0 never stamped "
                            "ckpt_commit_barrier_s")
        ms = load_model_state(base_dir, save_at)
        if "rng_key" not in ms:
            failures.append("baseline: model state (rng_key) missing "
                            "from the committed manifest")

        # 2. KILL MATRIX: one fleet per fail point; killing any one
        # host leaves save_at committed + loadable and never a torn
        # newer commit.  Process-0 survivors must REFUSE by name.
        matrix = [
            # (tag, chaos env, which host dies, survivor-refuses?)
            ("kill_submanifest",
             {"APEX_TPU_CHAOS_SAVE": "host.before_submanifest",
              "APEX_TPU_CHAOS_PROC": "1"}, 1, True),
        ] if smoke else [
            ("kill_shard_write",
             {"APEX_TPU_CHAOS_SAVE": "ckpt.mid_shards:2",
              "APEX_TPU_CHAOS_PROC": "1"}, 1, True),
            ("kill_submanifest",
             {"APEX_TPU_CHAOS_SAVE": "host.before_submanifest",
              "APEX_TPU_CHAOS_PROC": "1"}, 1, True),
            ("kill_before_barrier",
             {"APEX_TPU_CHAOS_SAVE": "host.before_barrier",
              "APEX_TPU_CHAOS_PROC": "0"}, 0, False),
            # host 1 dies mid-STEP (not mid-save): the surviving
            # process 0 reaches the kill-step save alone and its
            # barrier must refuse the half-fleet commit
            ("kill_rank_lost",
             {"APEX_TPU_CHAOS": f"rank.lost_at_step:{save_at + 2}",
              "APEX_TPU_CHAOS_PROC": "1"}, 1, True),
        ]
        for tag, env, dead, expect_refusal in matrix:
            d, res, rc = fleet(tag, kill_at=steps, chaos_env=env)
            lc = latest_committed_step(d)
            result[f"{tag}_committed"] = lc
            if lc != save_at:
                failures.append(
                    f"{tag}: latest committed is {lc}, expected "
                    f"{save_at} — a torn commit became visible")
            else:
                try:
                    verify_shards(S.step_dir(d, save_at))
                except Exception as e:
                    failures.append(f"{tag}: committed step no longer "
                                    f"loads: {e}")
            if dead in res:
                failures.append(f"{tag}: host {dead} wrote a result "
                                "after being killed?")
            survivor = 1 - dead
            if survivor not in res:
                failures.append(f"{tag}: surviving host {survivor} "
                                "never finished (hung on the dead "
                                "sibling?)")
            elif expect_refusal and survivor == 0:
                refusal = res[0].get("refusal")
                if not refusal or f"host {dead}" not in refusal:
                    failures.append(
                        f"{tag}: process 0 survived but did not refuse "
                        f"the torn commit naming host {dead} "
                        f"(refusal={refusal!r})")
            result[f"{tag}_ok"] = not any(
                f.startswith(tag) for f in failures)

        # 3. ORCHESTRATOR RESUME off the baseline commit.
        base_losses = base.get(0, {}).get("losses")
        base_canon = base.get(0, {}).get("canonical")
        if base_canon is None or base_losses is None:
            # the baseline failure above is the real story — don't let
            # a None-armed np.allclose bury it under a TypeError
            failures.append(
                "orchestrator sections skipped: no baseline host-0 "
                "result to compare against")
            raise _SkipToReport()

        def build(dp, resume_step, attempt):
            seg = _build_segment(dp, base_dir, resume_step=resume_step,
                                 manager_kw=dict(attempt=attempt))

            def session(on_step=None):
                losses, retraces, _ = _drive(
                    seg, _make_batches(steps, seg["batch"],
                                       seg["cfg"].seq_len,
                                       seg["cfg"].vocab_size),
                    resume_step or 0, steps, on_step=on_step)
                M.destroy_model_parallel()
                return {"losses": losses, "retraces": retraces,
                        "canonical": _canonical(seg)}
            return session

        # 3a. equal topology: bitwise
        out = ElasticOrchestrator(base_dir, build, initial_dp=4).run()
        eq = (base_losses is not None
              and out["losses"] == base_losses[save_at:]
              and np.array_equal(out["canonical"], base_canon))
        result["equal_topology_bitwise"] = bool(eq)
        if not eq:
            failures.append("equal-topology orchestrator resume NOT "
                            "bitwise vs the fleet baseline")
        if out["retraces"]:
            failures.append(f"equal-topology resume: {out['retraces']} "
                            "steady recompiles")

        # 3b. lost rank mid-segment → dump → rebuild dp=4→2 →
        # re-shard restore → resume (allclose, resume_probe's
        # calibrated tolerances)
        det = StragglerDetector(threshold=1.5, patience=2)
        wd = LostRankWatchdog(det, deadline=2)
        dump_path = os.path.join(root, "fleet_flight.json")
        from apex_tpu.monitor import FlightRecorder
        recorder = FlightRecorder(dump_path, capacity=4)

        def build_elastic(dp, resume_step, attempt):
            session = build(dp, resume_step, attempt)

            def on_step(i):
                if dp == 4 and i >= save_at + 1:
                    # rank 2 goes 3x median: flagged, then lost
                    t = np.full((dp, 1), 0.1)
                    t[2, 0] = 0.3
                    wd.check(t)

            return lambda: session(on_step=on_step)

        orch = ElasticOrchestrator(
            base_dir, build_elastic, initial_dp=4,
            choose_dp=lambda dp, e: 2, recorder=recorder, watchdog=wd)
        out2 = orch.run()
        close = bool(np.allclose(base_canon, out2["canonical"],
                                 rtol=1e-3, atol=5e-4))
        result["elastic_allclose"] = close
        result["elastic_max_abs_diff"] = float(
            np.abs(base_canon - out2["canonical"]).max())
        result["fleet_resumes"] = orch.stats()["fleet_resumes"]
        result["fleet_dp"] = orch.stats()["fleet_dp"]
        if not close:
            failures.append(
                f"elastic dp=4→2 resume diverged (max abs diff "
                f"{result['elastic_max_abs_diff']:.3e})")
        if out2["retraces"]:
            failures.append(f"elastic resume: {out2['retraces']} "
                            "steady recompiles")
        if orch.stats() != {"fleet_resumes": 1, "fleet_dp": 2}:
            failures.append(f"orchestrator stats {orch.stats()} != "
                            "one resume at dp=2")
        if not os.path.exists(dump_path):
            failures.append("lost-rank recovery never dumped a flight "
                            "report")
        else:
            with open(dump_path) as f:
                reason = json.load(f).get("reason", "")
            if f"last committed checkpoint: step {save_at}" not in reason:
                failures.append(
                    "flight dump reason does not name the resume "
                    f"point: {reason!r}")

        # 4a. negative control, asserted BY NAME: damage the committed
        # step and the completeness sweep must refuse naming the rank
        chaos.truncate_shard(S.step_dir(base_dir, save_at),
                             "params_shard", rank=3)
        try:
            verify_shards(S.step_dir(base_dir, save_at))
            failures.append("negative control: truncated shard was "
                            "NOT refused")
        except IncompleteCheckpointError as e:
            if "rank 3" not in str(e):
                failures.append("negative control: refusal lost its "
                                f"rank naming: {e}")
        result["negative_control_ok"] = not any(
            "negative control" in f for f in failures)

        # 4b. hard escalation: no committed checkpoint → EscalationError
        empty = os.path.join(root, "empty_ckpt")
        os.makedirs(empty, exist_ok=True)

        def build_doomed(dp, resume_step, attempt):
            def session():
                from apex_tpu.checkpoint.chaos import RankLostError
                raise RankLostError("rank 1 lost (seeded)", rank=1)
            return session

        try:
            ElasticOrchestrator(empty, build_doomed, initial_dp=2).run()
            failures.append("escalation: orchestrator resumed with NO "
                            "committed checkpoint")
        except EscalationError as e:
            if "NO committed checkpoint" not in str(e):
                failures.append(f"escalation lost its naming: {e}")
        result["escalation_ok"] = not any(
            "escalation" in f for f in failures)
    except _SkipToReport:
        pass
    finally:
        shutil.rmtree(root, ignore_errors=True)

    result["ok"] = not failures
    if as_json:
        # ONE line so callers can reverse-scan stdout past plugin noise
        print(json.dumps(result, sort_keys=True))
    else:
        for k in sorted(result):
            print(f"  {k}: {result[k]}")
    if failures:
        for f in failures:
            print(f"fleet_probe: FAIL — {f}", file=sys.stderr)
        return 1
    print("fleet_probe: OK (kill matrix green, multi-host commit "
          "barrier held, orchestrator resumed bitwise/allclose, zero "
          "steady recompiles after resume)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="multi-host commit kill matrix + elastic-resume "
                    "orchestration CI gate")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate; exit 1 on drift")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 subset: one kill point + resume")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--save-at", type=int, default=4,
                    help="commit a checkpoint after this step")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result")
    ap.add_argument("--backend", default=None,
                    help="JAX_PLATFORMS override (resolved pre-import)")
    # worker mode (internal; spawned via parallel/multiproc)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--result-dir", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--dp", type=int, default=4, help=argparse.SUPPRESS)
    ap.add_argument("--kill-at", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--attempt", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--barrier-timeout", type=float, default=6.0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if args.worker:
        if not (args.ckpt_dir and args.result_dir):
            ap.error("--worker needs --ckpt-dir and --result-dir")
        return worker(args)
    if not 0 < args.save_at < args.steps:
        ap.error(f"--save-at must be in (0, {args.steps})")
    return probe(args.steps, args.save_at, args.json, args.smoke)


if __name__ == "__main__":
    sys.exit(main())
