"""Per-layer ResNet-50 roofline profiler (VERDICT r4 next-#1).

Times, on the real chip at the bench config (b256, 224x224, bf16):
  * every unique conv shape in RN50 — fwd and fwd+bwd, TFLOP/s and %peak
  * the BN stack cost (pallas welford vs jnp stats A/B)
  * maxpool fwd/bwd
  * full train step decomposition (fwd-only / fwd+bwd / full step)

Per-call dispatch through the remote tunnel is ~10 ms, so every
measurement loops K iterations INSIDE one jitted program via lax.scan
with a scalar feedback chain (carry + tiny epsilon into the input) that
defeats CSE/hoisting without meaningfully changing the op's traffic.

Usage:  python scripts/resnet_profile.py [conv|bn|pool|step|all]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PEAK_TFLOPS = 197.0  # v5e bf16
HBM_GBPS = 819.0     # v5e

B = 256
K_INNER = 100  # iterations inside one jit call (per-call overhead ~20ms)


def _scan_time(op, out_to_scalar, *args, iters=K_INNER, reps=5):
    """Time `op(*args)` by running `iters` copies inside one jitted scan,
    chaining a tiny scalar from each output into the next input so XLA
    cannot hoist or CSE the body.  Returns seconds per op.

    Per-call dispatch through the remote tunnel is ~80-90 ms, so `reps`
    calls are issued back-to-back and synced ONCE — dispatch overlaps
    device execution exactly as in bench.py's timing loops."""

    def make(length):
        def many(*a):
            def body(carry, _):
                perturbed = (a[0] + carry.astype(a[0].dtype),) + a[1:]
                out = op(*perturbed)
                return out_to_scalar(out) * 1e-30, None

            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))  # compile
        _ = np.asarray(f(*args))  # warm
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    # two-point slope cancels the flat per-call overhead exactly
    lo, hi = max(1, iters // 5), iters
    if hi == lo:
        return total(make(hi)) / hi  # overhead-inclusive single point
    t_lo = total(make(lo))
    t_hi = total(make(hi))
    return max((t_hi - t_lo) / (hi - lo), 1e-9)


def _first_scalar(out):
    # sum over EVERY leaf: a single element would let XLA slice-sink
    # through the op and compute one output pixel (measured: "conv"
    # above peak FLOPs); a full ravel()[0] would force a 1-D relayout.
    # The fused sum costs one read of each output — the roofline floor.
    return sum(jnp.sum(leaf.astype(jnp.float32))
               for leaf in jax.tree.leaves(out))


# (name, H, W, Cin, Cout, k, stride, multiplicity) — every unique conv
# shape in RN50.
RN50_CONVS = [
    ("stem7x7s2", 224, 224, 3, 64, 7, 2, 1),
    ("s1_c1_first", 56, 56, 64, 64, 1, 1, 1),
    ("s1_c1", 56, 56, 256, 64, 1, 1, 2),
    ("s1_c2", 56, 56, 64, 64, 3, 1, 3),
    ("s1_c3", 56, 56, 64, 256, 1, 1, 3),
    ("s1_ds", 56, 56, 64, 256, 1, 1, 1),
    ("s2_c1_first", 56, 56, 256, 128, 1, 1, 1),
    ("s2_c2_s2", 56, 56, 128, 128, 3, 2, 1),
    ("s2_ds_s2", 56, 56, 256, 512, 1, 2, 1),
    ("s2_c1", 28, 28, 512, 128, 1, 1, 3),
    ("s2_c2", 28, 28, 128, 128, 3, 1, 3),
    ("s2_c3", 28, 28, 128, 512, 1, 1, 4),
    ("s3_c1_first", 28, 28, 512, 256, 1, 1, 1),
    ("s3_c2_s2", 28, 28, 256, 256, 3, 2, 1),
    ("s3_ds_s2", 28, 28, 512, 1024, 1, 2, 1),
    ("s3_c1", 14, 14, 1024, 256, 1, 1, 5),
    ("s3_c2", 14, 14, 256, 256, 3, 1, 5),
    ("s3_c3", 14, 14, 256, 1024, 1, 1, 6),
    ("s4_c1_first", 14, 14, 1024, 512, 1, 1, 1),
    ("s4_c2_s2", 14, 14, 512, 512, 3, 2, 1),
    ("s4_ds_s2", 14, 14, 1024, 2048, 1, 2, 1),
    ("s4_c1", 7, 7, 2048, 512, 1, 1, 2),
    ("s4_c2", 7, 7, 512, 512, 3, 1, 2),
    ("s4_c3", 7, 7, 512, 2048, 1, 1, 3),
]


def conv_roofline():
    print(f"{'conv':<14}{'n':>3}{'fb_ms':>9}{'TF/s':>7}{'%pk':>6}"
          f"{'GB/s':>7}{'n*fb_ms':>9}", flush=True)
    tot_fb = 0.0
    rows = []
    for name, h, w, cin, cout, k, s, mult in RN50_CONVS:
        x = jax.random.normal(jax.random.PRNGKey(0), (B, h, w, cin),
                              jnp.bfloat16)
        wgt = jax.random.normal(jax.random.PRNGKey(1), (k, k, cin, cout),
                                jnp.bfloat16) * 0.05

        def conv(x, wgt):
            return lax.conv_general_dilated(
                x, wgt, (s, s), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        ho_, wo_ = -(-h // s), -(-w // s)
        dy = jax.random.normal(jax.random.PRNGKey(2), (B, ho_, wo_, cout),
                               jnp.bfloat16)

        def fb(x, wgt, dy):
            # random cotangent through jax.vjp: grad of .sum() has a
            # constant dy that XLA folds into near-free backward convs
            # (measured 2x "above peak")
            out, vjp = jax.vjp(conv, x, wgt)
            dx, dw = vjp(dy)
            return out, dx, dw

        t_fb = _scan_time(fb, _first_scalar, x, wgt, dy)
        ho, wo = ho_, wo_
        flops = 2 * B * ho * wo * cin * cout * k * k
        # fwd+bwd traffic ~ 3 passes x (in + out) at bf16
        traffic = 3 * 2 * B * (h * w * cin + ho * wo * cout)
        tf_fb = 3 * flops / t_fb / 1e12
        tot_fb += mult * t_fb
        rows.append((name, mult, t_fb))
        print(f"{name:<14}{mult:>3}{t_fb*1e3:>9.3f}{tf_fb:>7.1f}"
              f"{100*tf_fb/PEAK_TFLOPS:>6.1f}{traffic/t_fb/1e9:>7.0f}"
              f"{mult*t_fb*1e3:>9.2f}", flush=True)
    print(f"sum over net: fwd+bwd {tot_fb*1e3:.1f} ms "
          f"({B/tot_fb:.0f} img/s if conv-only)")
    for name, mult, t in sorted(rows, key=lambda r: -r[1] * r[2])[:6]:
        print(f"  top cost: {name} x{mult} = {mult*t*1e3:.2f} ms")


def bn_cost():
    """BN stack cost: pallas welford vs jnp stats, per stage shape."""
    from apex_tpu.parallel.sync_batchnorm import sync_batch_norm

    shapes = [  # (H, W, C, count in RN50)
        (112, 112, 64, 1), (56, 56, 64, 6), (56, 56, 256, 4),
        (28, 28, 128, 7), (28, 28, 512, 5), (14, 14, 256, 11),
        (14, 14, 1024, 7), (7, 7, 512, 4), (7, 7, 2048, 4),
    ]
    import apex_tpu.ops._common as C
    for force in ("1", "0"):
        C._FORCE = force
        tot = 0.0
        for h, w, c, mult in shapes:
            x = jax.random.normal(jax.random.PRNGKey(0), (B, h, w, c),
                                  jnp.bfloat16)
            dy = jax.random.normal(jax.random.PRNGKey(1), (B, h, w, c),
                                   jnp.bfloat16)
            scale = jnp.ones((c,))
            bias = jnp.zeros((c,))
            rm = jnp.zeros((c,))
            rv = jnp.ones((c,))

            def fb(x, scale, bias, dy):
                def f(x, scale, bias):
                    y, _, _ = sync_batch_norm(x, scale, bias, rm, rv,
                                              training=True)
                    return y
                y, vjp = jax.vjp(f, x, scale, bias)
                return (y,) + vjp(dy)

            t = _scan_time(fb, _first_scalar, x, scale, bias, dy)
            tot += mult * t
            gb = (B * h * w * c * 2) / 1e9
            print(f"  pallas={force} bn {h}x{w}x{c:<5} x{mult:>2} "
                  f"{t*1e3:8.3f} ms  ({gb/t:.0f} GB/s per-pass)")
        print(f"pallas={force}: BN stack fwd+bwd total {tot*1e3:.1f} ms")
    C._FORCE = ""


def maxpool_cost():
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 112, 112, 64),
                          jnp.bfloat16)

    def mp(x):
        return lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                                 (1, 2, 2, 1), "SAME")

    def fb(x):
        return jax.grad(lambda x: mp(x).astype(jnp.float32).sum())(x)

    t_f = _scan_time(mp, _first_scalar, x)
    t_fb = _scan_time(fb, _first_scalar, x)
    print(f"maxpool fwd {t_f*1e3:.3f} ms  fwd+bwd {t_fb*1e3:.3f} ms")


def step_decomp():
    """Full-model decomposition at the bench config (in-jit scan)."""
    from apex_tpu.models.resnet import ResNet
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

    model = ResNet("resnet50", num_classes=1000, axis_name=None)
    params, mstate = model.init(jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda p: p.astype(jnp.bfloat16)
        if p.dtype == jnp.float32 else p, params)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3),
                          jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 1000)

    def fwd_inf(x):
        return model.apply(params, mstate, x, training=False)[0]

    def fwd_tr(x):
        return model.apply(params, mstate, x, training=True)[0]

    def loss_fn(p, x):
        logits, nms = model.apply(p, mstate, x, training=True)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y)), nms

    def fb(x):
        g, _ = jax.grad(loss_fn, has_aux=True)(params, x)
        return g

    import apex_tpu.ops._common as C
    for force in ("1", "0"):
        C._FORCE = force
        t1 = _scan_time(fwd_inf, _first_scalar, x, iters=5)
        t2 = _scan_time(fwd_tr, _first_scalar, x, iters=5)
        t3 = _scan_time(fb, _first_scalar, x, iters=5)
        print(f"pallas={force}: fwd(eval) {t1*1e3:.2f} ms | fwd(train) "
              f"{t2*1e3:.2f} ms | fwd+bwd {t3*1e3:.2f} ms "
              f"({B/t3:.0f} img/s)")
    C._FORCE = ""


def calibrate():
    """Per-call overhead vs per-iteration cost: time one mid-size conv
    at different inner iteration counts; the slope is the true per-op
    cost."""
    x = jax.random.normal(jax.random.PRNGKey(0), (B, 28, 28, 128),
                          jnp.bfloat16)
    wgt = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 128, 128),
                            jnp.bfloat16) * 0.05

    def conv(x, wgt):
        return lax.conv_general_dilated(
            x, wgt, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    for it in (1, 10, 40, 100, 200):
        t = _scan_time(conv, _first_scalar, x, wgt, iters=it)
        print(f"iters={it:>3}: {t*1e3:.3f} ms/op (total {t*it*1e3:.1f})")


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")
    if which == "calib":
        calibrate()
        return
    if which in ("conv", "all"):
        conv_roofline()
    if which in ("bn", "all"):
        bn_cost()
    if which in ("pool", "all"):
        maxpool_cost()
    if which in ("step", "all"):
        step_decomp()


if __name__ == "__main__":
    main()
