"""Can a Pallas kernel read per-head (bq, d) blocks STRIDED from the
packed qkv activation ((S, B, 3, H, D) / (B, S, 3, H, D)) at useful
bandwidth, or does the 128-byte row granularity kill it?  Decides
whether flash attention can consume projection-layout qkv directly
(zero transpose glue) instead of requiring (B, H, S, D) copies.

MEASURED NOTES (round 5): single-head 5D blocks are rejected by the
Pallas TPU lowering (second-minor block dim must divide 8 or equal the
array dim), so packed reads must take head PAIRS (1, S, 128).  The
strided head-pair gather does run at usable bandwidth, but the sibling
attn_glue_probe.py showed the transposes this would eliminate cost ~0
in context, so the kernel keeps its (B, H, S, D) contract.  Kept as
the record of the negative result."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

B, H, S, D = 12, 16, 1024, 64


def _scan_time(fn, args, iters=100, reps=5):
    def make(length):
        def many(*a):
            def body(carry, _):
                out = fn(a[0] + carry.astype(a[0].dtype), *a[1:])
                return carry + jnp.sum(out[0, 0].astype(jnp.float32)) * 1e-30, None
            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = max(1, iters // 5), iters
    return (total(make(hi)) - total(make(lo))) / (hi - lo)


def _copy_kernel(src_ref, dst_ref):
    dst_ref[...] = src_ref[...]


def main():
    key = jax.random.PRNGKey(0)
    # packed qkv activation, model layout (B, S, 3, H, D)
    qkv = jax.random.normal(key, (B, S, 3, H, D), jnp.bfloat16)

    # 1. contiguous baseline: copy already-transposed (B,H,S,D) q
    qt = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)

    def copy_contig(q):
        return pl.pallas_call(
            _copy_kernel,
            grid=(B * H,),
            in_specs=[pl.BlockSpec((1, 1, S, D),
                                   lambda i: (i // H, i % H, 0, 0))],
            out_specs=pl.BlockSpec((1, 1, S, D),
                                   lambda i: (i // H, i % H, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        )(q)

    # 2. strided: gather q HEAD-PAIR planes straight out of packed qkv
    # flattened to (B, S, 3*H*D); a (1, S, 128)-lane block = heads
    # (2j, 2j+1) of q with 256-byte rows strided 6 KB apart
    def copy_strided(qkv):
        flat = qkv.reshape(B, S, 3 * H * D)

        def kern(src_ref, dst_ref):
            dst_ref[...] = src_ref[...].reshape(1, 1, S, 2 * D)
        return pl.pallas_call(
            kern,
            grid=(B * H // 2,),
            in_specs=[pl.BlockSpec((1, S, 2 * D),
                                   lambda i: (i // (H // 2), 0,
                                              i % (H // 2)))],
            out_specs=pl.BlockSpec((1, 1, S, 2 * D),
                                   lambda i: (i // (H // 2),
                                              i % (H // 2), 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, H // 2, S, 2 * D),
                                           qkv.dtype),
        )(flat)

    # 3. XLA transpose of the same logical op (split+transpose)
    def xla_transpose(qkv):
        q = qkv[:, :, 0]                      # (B, S, H, D)
        return q.transpose(0, 2, 1, 3)        # (B, H, S, D)

    t1 = _scan_time(copy_contig, (qt,))
    t2 = _scan_time(copy_strided, (qkv,))
    t3 = _scan_time(jax.jit(xla_transpose), (qkv,))
    nbytes = B * H * S * D * 2
    for name, t in (("contig pallas copy", t1), ("strided pallas gather", t2),
                    ("xla slice+transpose", t3)):
        print(f"{name:22s} {t*1e3:7.3f} ms  "
              f"{2*nbytes/t/1e9:6.0f} GB/s (r+w)")


if __name__ == "__main__":
    main()
