"""Batch sweep + optimizer-dtype dial for the RN50 bench point (after
the welford→XLA BN-stats switch moved the bottleneck)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.resnet import ResNet
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import mesh as M


def run_point(B, iters=8, warmup=2, stem="conv7"):
    model = ResNet("resnet50", num_classes=1000, axis_name=None,
                   stem=stem)
    params, mstate = model.init(jax.random.PRNGKey(0))
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params16)
    x16 = jax.random.normal(jax.random.PRNGKey(1), (B, 224, 224, 3),
                            jnp.bfloat16)
    y = jax.random.randint(jax.random.PRNGKey(2), (B,), 0, 1000)

    def step(state, mstate):
        from apex_tpu.optimizers import flat as F
        p = F.unflatten(state.params, opt.spec)

        def lf(p):
            logits, nms = model.apply(p, mstate, x16, training=True)
            loss = jnp.mean(softmax_cross_entropy_loss(
                logits.astype(jnp.float32), y))
            return loss, nms

        grads, nms = jax.grad(lf, has_aux=True)(p)
        _, new_state = opt.step(state, grads)
        return new_state, nms

    jstep = jax.jit(step, donate_argnums=(0, 1))
    args = (state, mstate)
    for _ in range(warmup):
        args = jstep(*args)
    _ = np.asarray(args[0].params.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        args = jstep(*args)
    _ = np.asarray(args[0].params.ravel()[:1])
    dt = (time.perf_counter() - t0) / iters
    print(f"B={B:<4} stem={stem:<15} {dt*1e3:8.2f} ms/step  "
          f"{B/dt:8.0f} img/s", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "batch"
    if which == "batch":
        for B in (128, 256, 384, 512):
            run_point(B)
    elif which == "stem":
        run_point(256, stem="conv7")
        run_point(256, stem="space_to_depth")
