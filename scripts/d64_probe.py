"""Probe: does head_dim=64 cost HBM lane padding + bandwidth, and how
much of the attention sublayer is layout glue (transposes around the
flash kernel) vs the kernel itself?

Three measurements on the real chip:
  1. memory_analysis argument bytes for (b,h,s,64) vs (b,h,s,128)
     bf16 arrays feeding the flash kernel — is the minor-64 array
     lane-padded in HBM (2x bytes)?
  2. Copy bandwidth: time jit(lambda x: x + 1) over both shapes.
  3. The GPT attention sublayer glue: time (a) the full sublayer,
     (b) flash kernel alone on pre-transposed operands, (c) the
     qkv reshape/transpose + ctx transpose alone.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

B, H, S, D = 12, 16, 1024, 64


def timeit(f, *args, iters=20):
    o = f(*args)
    _ = np.asarray(jax.tree.leaves(o)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        o = f(*args)
    _ = np.asarray(jax.tree.leaves(o)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    k = jax.random.PRNGKey(0)

    # --- 1. memory analysis: lane padding of minor-64 ---
    # one jitted probe, hoisted out of the loop (apex_tpu.lint HS405):
    # the per-shape retraces land in one cache instead of rebuilding
    # the jit wrapper each iteration
    probe = jax.jit(lambda x: x * 2)
    for d in (64, 128):
        x = jnp.zeros((B, H, S, d), jnp.bfloat16)
        c = probe.lower(x).compile()
        ma = c.memory_analysis()
        logical = B * H * S * d * 2
        print(f"d={d}: arg_bytes={ma.argument_size_in_bytes} "
              f"logical={logical} ratio={ma.argument_size_in_bytes/logical:.2f}")

    # --- 2. elementwise bandwidth over both shapes ---
    x64 = jax.random.normal(k, (B, H, S, 64), jnp.bfloat16)
    x128 = jax.random.normal(k, (B, H, S, 128), jnp.bfloat16)
    f = jax.jit(lambda x: x + 1)
    t64 = timeit(f, x64)
    t128 = timeit(f, x128)
    print(f"x+1: d=64 {t64:.3f} ms, d=128 {t128:.3f} ms "
          f"(same time => 64 is padded)")

    # --- 3. attention sublayer glue ---
    from apex_tpu.ops.flash_attention import flash_attention

    hdim = H * D
    xs = jax.random.normal(k, (S, B, hdim), jnp.bfloat16)
    wqkv = jax.random.normal(k, (hdim, 3 * hdim), jnp.bfloat16) * 0.02
    wproj = jax.random.normal(k, (hdim, hdim), jnp.bfloat16) * 0.02

    def sublayer(x, wqkv, wproj):
        qkv = x @ wqkv
        s, b, _ = qkv.shape
        qkv = qkv.reshape(s, b, 3, H, D)
        q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q, kk, v = (t.transpose(1, 2, 0, 3) for t in (q, kk, v))
        ctx = flash_attention(q, kk, v, causal=True,
                              softmax_scale=D ** -0.5)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        return ctx @ wproj

    def glue_only(x, wqkv):
        qkv = x @ wqkv
        s, b, _ = qkv.shape
        qkv = qkv.reshape(s, b, 3, H, D)
        q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        q, kk, v = (t.transpose(1, 2, 0, 3) for t in (q, kk, v))
        # ctx stand-in: transpose q back (same relayout cost as ctx)
        ctx = q.transpose(2, 0, 1, 3).reshape(s, b, -1)
        return ctx

    q = jax.random.normal(k, (B, H, S, D), jnp.bfloat16)
    kv = jax.random.split(k, 2)
    kq = jax.random.normal(kv[0], (B, H, S, D), jnp.bfloat16)
    vv = jax.random.normal(kv[1], (B, H, S, D), jnp.bfloat16)

    def grad_ms(fn, *args):
        g = jax.jit(jax.grad(
            lambda *a: fn(*a).astype(jnp.float32).mean(),
            argnums=tuple(range(len(args)))))
        return timeit(g, *args)

    t_sub = grad_ms(sublayer, xs, wqkv, wproj)
    t_kernel = grad_ms(
        lambda q, kk, v: flash_attention(q, kk, v, causal=True,
                                         softmax_scale=D ** -0.5),
        q, kq, vv)
    t_glue = grad_ms(glue_only, xs, wqkv)
    # matmuls alone (qkv proj + out proj)
    t_mm = grad_ms(lambda x, a, b: (x @ a)[..., :hdim] @ b,
                   xs, wqkv, wproj)
    print(f"sublayer fwd+bwd {t_sub:.2f} ms | kernel {t_kernel:.2f} | "
          f"glue(qkv proj+transposes) {t_glue:.2f} | proj-matmuls {t_mm:.2f}")
    print(f"x24 layers: sublayer {24*t_sub:.1f} ms, "
          f"non-kernel non-matmul residue "
          f"{24*(t_sub - t_kernel - t_mm):.1f} ms")


if __name__ == "__main__":
    main()
