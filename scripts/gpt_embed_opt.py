"""The unmeasured 1.3B step components: embedding gather fwd+bwd (TPU
scatter-add suspect) vs a one-hot-matmul backward, and the fused Adam
pass at 1.3B scale."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V, H = 50304, 2048
B, S = 8, 512


def _scan_time(fn, args, iters=30, reps=3):
    def make(length):
        def many(*a):
            def body(carry, _):
                out = fn(*((a[0] + carry.astype(a[0].dtype),) + a[1:]))
                return sum(jnp.sum(l.astype(jnp.float32))
                           for l in jax.tree.leaves(out)) * 1e-30, None
            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = max(1, iters // 5), iters
    return (total(make(hi)) - total(make(lo))) / (hi - lo)


emb = jax.random.normal(jax.random.PRNGKey(0), (V, H), jnp.bfloat16) * 0.02
tok = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)
dy = jax.random.normal(jax.random.PRNGKey(2), (B, S, H), jnp.bfloat16)


def take_fb(emb, tok, dy):
    out, vjp = jax.vjp(lambda e: jnp.take(e, tok, axis=0), emb)
    return out, vjp(dy)[0]


t = _scan_time(take_fb, (emb, tok, dy), iters=10)
print(f"embed take fwd + scatter-add bwd: {t*1e3:8.3f} ms", flush=True)


def onehot_fb(emb, tok, dy):
    # bwd of take is a scatter; expressing dE = onehot^T @ dy turns it
    # into one MXU matmul
    def f(e):
        return jnp.take(e, tok, axis=0)

    out = f(emb)
    oh = jax.nn.one_hot(tok.reshape(-1), V, dtype=jnp.bfloat16)
    dE = jax.lax.dot_general(oh, dy.reshape(-1, H).astype(jnp.bfloat16),
                             (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return out, dE


t = _scan_time(onehot_fb, (emb, tok, dy), iters=10)
print(f"embed take fwd + one-hot-matmul bwd: {t*1e3:5.3f} ms", flush=True)

# fused Adam at 1.3B bf16 state (the bench's optimizer tail)
from apex_tpu.ops import optimizer_kernels as K

n = (1_300_000_000 // K.FLAT_TILE + 1) * K.FLAT_TILE
p = jnp.zeros((n,), jnp.bfloat16)
m = jnp.zeros((n,), jnp.bfloat16)
v = jnp.zeros((n,), jnp.bfloat16)
g = jnp.full((n,), 1e-3, jnp.bfloat16)


def adam(p, m, v, g):
    return K.adam_flat(p, m, v, g, lr=1e-3, step=10.0,
                       use_pallas_override=True)


jstep = jax.jit(adam, donate_argnums=(0, 1, 2))
args = (p, m, v)
for _ in range(2):
    args = jstep(*args, g)
_ = np.asarray(args[0][:1])
t0 = time.perf_counter()
for _ in range(10):
    args = jstep(*args, g)
_ = np.asarray(args[0][:1])
t = (time.perf_counter() - t0) / 10
print(f"adam 1.3B bf16 p/m/v step: {t*1e3:14.3f} ms", flush=True)
