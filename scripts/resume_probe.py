"""Preemption/resume CI gate (ISSUE 9): save → kill → restore →
trajectory-match.

usage:
  python scripts/resume_probe.py             # full probe
  python scripts/resume_probe.py --selftest  # fixture drift gate
  python scripts/resume_probe.py --json      # machine-readable result

The full probe drives the whole preemption story on a real train step
(ZeRO-2 `DistributedFusedAdam` through `ddp.make_train_step`, amp
dynamic loss scaling, `CheckpointManager` async saves):

  1. BASELINE   — dp=2 trains `--steps` steps over fixed data, with a
                  committed checkpoint at `--save-at`.
  2. KILL       — a `chaos` fail point kills a later save mid-write;
                  the probe asserts the partial directory is NOT
                  loadable and the `--save-at` commit still restores
                  (the latest COMMITTED manifest always restores).
  3. RESUME =   — a fresh dp=2 run restores at `--save-at` and replays
                  the remaining steps: losses and the canonical master
                  flat must match the unpreempted baseline BITWISE.
  4. RESUME ≠   — dp=1 and dp=4 runs restore the SAME dp=2 checkpoint
                  (elastic re-shard + full gather): canonical master
                  flats must match allclose (fp reduction order is the
                  only difference — docs/checkpointing.md's matrix).
  5. SENTRY     — every resumed run is RecompileSentry-wrapped and
                  must show ZERO steady-state recompiles after the
                  resume warmup (restored state places through the
                  step's own partition specs, so nothing retraces).

Exit is nonzero on any mismatch.  On a CPU backend an 8-way virtual
device mesh is forced (conftest-style) and the tiny smoke config
substitutes through the same build path; on TPU run it as-is on a
multi-chip slice.

`--selftest` is the tier-1 fixture-drift gate (mirrors
`lint_step.py` / `comms_probe.py` / `flight_report.py`): the committed
manifest fixture (scripts/resume_fixture.json) must still validate,
the reshard round-trip must reproduce a synthetic canonical buffer
bitwise, and a seeded truncated shard must be REFUSED with the missing
rank named (the gate's own negative control).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--backend" in sys.argv[1:]:
    try:
        os.environ["JAX_PLATFORMS"] = \
            sys.argv[sys.argv.index("--backend") + 1]
    except IndexError:
        sys.exit("--backend needs a value (e.g. --backend tpu)")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# elastic resume needs dp up to 4: on the CPU backend force an 8-way
# virtual mesh (must precede the first jax import, conftest-style)
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "resume_fixture.json")


# ---------------------------------------------------------------------------
# selftest (tier-1)
# ---------------------------------------------------------------------------

def selftest() -> int:
    import numpy as np

    from apex_tpu.checkpoint import (IncompleteCheckpointError, chaos,
                                     save_sharded, validate_manifest,
                                     verify_shards)
    from apex_tpu.checkpoint import sharded as S

    with open(FIXTURE) as f:
        fixture = json.load(f)
    try:
        validate_manifest(fixture)
    except S.CheckpointError as e:
        print(f"resume_probe --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? regenerate scripts/"
              "resume_fixture.json with the new manifest schema)",
              file=sys.stderr)
        return 1

    # reshard round-trip: a synthetic 2-bucket dp=2 layout re-laid to
    # dp=4 single-bucket and back must reproduce the canonical buffer
    # bitwise (the elastic-resume math, no devices involved)
    src = {"align": 1, "total": 16, "n_tensors": 3, "num_shards": 2,
           "n_buckets": 2, "bucket_totals": [10, 6],
           "bucket_padded": [12, 8], "master_dtype": "float32"}
    dst = {"align": 1, "total": 16, "n_tensors": 3, "num_shards": 4,
           "n_buckets": 1, "bucket_totals": [16],
           "bucket_padded": [32], "master_dtype": "float32"}
    canon = np.arange(16, dtype=np.float32)
    shards = list(np.split(S.relayout_flat(canon, src), 2))
    re4 = S.reshard(shards, src, dst)
    back = S.canonical_flat(list(np.split(re4, 4)), dst)
    if not np.array_equal(back, canon):
        print("resume_probe --selftest: reshard round-trip is no longer "
              f"bitwise ({back} != {canon})", file=sys.stderr)
        return 1

    # negative control: a committed-then-truncated shard must be
    # REFUSED with the damaged rank named — a gate that stops flagging
    # its seeded corruption is not a gate
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="resume_probe_selftest_")
    try:
        p = save_sharded(
            tmp, 3,
            {"params_shard": ("sharded",
                              list(np.split(np.arange(8, dtype=np.float32),
                                            2))),
             "step": ("replicated", np.asarray(3, np.int32))},
            flat_layout={"align": 1, "total": 8, "n_tensors": 1,
                         "num_shards": 2, "n_buckets": 1,
                         "bucket_totals": [8], "bucket_padded": [8],
                         "master_dtype": "float32"})
        verify_shards(p)
        chaos.truncate_shard(p, "params_shard", rank=1)
        try:
            verify_shards(p)
        except IncompleteCheckpointError as e:
            if "rank 1" not in str(e) or "truncated" not in str(e):
                print("resume_probe --selftest: truncation error lost "
                      f"its rank/cause naming: {e}", file=sys.stderr)
                return 1
        else:
            print("resume_probe --selftest: seeded TRUNCATED shard was "
                  "NOT refused — verify_shards lost its teeth",
                  file=sys.stderr)
            return 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print("resume_probe --selftest: OK")
    return 0


# ---------------------------------------------------------------------------
# full probe
# ---------------------------------------------------------------------------

def _make_batches(n_steps, batch, seq, vocab):
    import numpy as np
    rng = np.random.RandomState(1234)
    out = []
    for _ in range(n_steps):
        t = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
        out.append((t, np.roll(t, -1, axis=1)))
    return out


def _run_segment(dp, ckpt_dir, batches, start, stop, *, cfg, batch_spec,
                 save_at=None, resume=False, n_buckets=2):
    """Build a fresh dp-way ZeRO-2 train step (optionally restoring
    `ckpt_dir`'s latest commit first), run steps [start, stop), saving
    on `save_at`.  Returns (losses, canonical_master, steady_recompiles,
    scale)."""
    import jax
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.checkpoint import CheckpointManager
    from apex_tpu.checkpoint import sharded as S
    from apex_tpu.monitor.compile import RecompileSentry
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M
    from apex_tpu.models.gpt import GPT
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:dp])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    amp_state = amp.initialize(opt_level="O0", loss_scale="dynamic")
    scaler = amp_state.loss_scalers[0]
    opt = DistributedFusedAdam(num_shards=dp, lr=1e-2,
                               n_buckets=n_buckets, use_pallas=False)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    manager = CheckpointManager(ckpt_dir, opt, every_n_steps=1,
                                keep=4)
    if resume:
        state, restored_scaler, _ = manager.restore(mesh)
        if restored_scaler is not None:
            scaler = restored_scaler
    step = ddp.make_train_step(
        lambda p, b: model.loss(p, b[0], b[1]), opt, mesh,
        amp_state=amp_state, batch_spec=batch_spec)
    sentry = RecompileSentry(step, name=f"resume_probe_dp{dp}",
                             warn=False)
    losses = []
    calls = 0
    for i in range(start, stop):
        t, l = batches[i]
        state, scaler, loss = sentry(state, scaler, (t, l))
        calls += 1
        if calls == 2:
            # the resume contract: first call compiles, a donated-state
            # second compile is legitimate — anything after is a
            # steady-state retrace and fails the probe
            _ = np.asarray(loss)
            sentry.mark_steady()
        losses.append(np.asarray(loss, np.float32))
        if save_at is not None and (i + 1) == save_at:
            manager.save(save_at, state, scaler)
            manager.wait()
    if calls == 1:
        sentry.mark_steady()
    glob = np.asarray(state.params_shard)
    canonical = S.canonical_flat(list(np.split(glob, dp)),
                                 opt.shard_layout())
    scale = float(np.asarray(scaler.scale))
    manager.wait()
    M.destroy_model_parallel()
    return (np.asarray(losses, np.float32), canonical,
            int(sentry.steady_recompiles), scale)


def probe(steps: int, save_at: int, as_json: bool) -> int:
    import shutil
    import tempfile

    import jax
    import numpy as np

    from apex_tpu.checkpoint import (chaos, latest_committed_step)
    from apex_tpu.checkpoint.chaos import SimulatedPreemption
    from apex_tpu.models.gpt import GPTConfig
    from jax.sharding import PartitionSpec as P

    on_tpu = jax.default_backend() not in ("cpu",)
    n_dev = len(jax.devices())
    if n_dev < 2:
        print("resume_probe: needs >= 2 devices for the dp=2 baseline",
              file=sys.stderr)
        return 2
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, seq_len=512, hidden=512,
                        num_layers=4, num_heads=8, dropout=0.0)
        batch = 8
    else:
        cfg = GPTConfig(vocab_size=64, seq_len=16, hidden=32,
                        num_layers=2, num_heads=2, dropout=0.0)
        batch = 8
    batches = _make_batches(steps, batch, cfg.seq_len, cfg.vocab_size)
    batch_spec = (P("dp"), P("dp"))
    tmp = tempfile.mkdtemp(prefix="resume_probe_")
    result = {"steps": steps, "save_at": save_at, "dp_baseline": 2}
    failures = []
    try:
        # 1. baseline (unpreempted) with a commit at save_at
        losses, canon, retraces, _ = _run_segment(
            2, tmp, batches, 0, steps, cfg=cfg, batch_spec=batch_spec,
            save_at=save_at)
        result["baseline_loss_first"] = float(losses[0])
        result["baseline_loss_last"] = float(losses[-1])
        if retraces:
            failures.append(f"baseline: {retraces} steady recompiles")

        # 2. kill-mid-save: a later save dies after its first shard
        # file; the partial must not be loadable and save_at must
        # still restore
        with chaos.preempt_at("ckpt.mid_shards", count=2):
            try:
                losses2, _, _, _ = _run_segment(
                    2, tmp, batches, 0, steps, cfg=cfg,
                    batch_spec=batch_spec, save_at=steps)
                failures.append("kill-mid-save: fail point never fired")
            except SimulatedPreemption:
                pass
        last = latest_committed_step(tmp)
        result["last_committed_after_kill"] = last
        if last != save_at:
            failures.append(
                f"kill-mid-save: latest committed step is {last}, "
                f"expected {save_at} (partial directory counted as a "
                "checkpoint?)")

        # 3. equal-topology resume: bitwise
        r_losses, r_canon, r_retraces, _ = _run_segment(
            2, tmp, batches, save_at, steps, cfg=cfg,
            batch_spec=batch_spec, resume=True)
        eq_losses = bool(np.array_equal(losses[save_at:], r_losses))
        eq_canon = bool(np.array_equal(canon, r_canon))
        result["equal_topology_bitwise"] = eq_losses and eq_canon
        if not eq_losses:
            failures.append(
                "equal-topology resume: loss trajectory NOT bitwise "
                f"({losses[save_at:]} vs {r_losses})")
        if not eq_canon:
            failures.append(
                "equal-topology resume: canonical master flat NOT "
                "bitwise")
        if r_retraces:
            failures.append(
                f"equal-topology resume: {r_retraces} steady-state "
                "recompile(s) after resume")

        # 4. elastic resume: dp=2 checkpoint → dp=1 (full gather) and
        # dp=4 (re-shard); fp reduction order differs, so allclose
        for dp in (1, 4):
            if dp > n_dev:
                result[f"dp{dp}_skipped"] = f"only {n_dev} devices"
                continue
            e_losses, e_canon, e_retraces, _ = _run_segment(
                dp, tmp, batches, save_at, steps, cfg=cfg,
                batch_spec=batch_spec, resume=True)
            # tolerance calibration: two FROM-SCRATCH runs at dp=1 vs
            # dp=2 on this config already differ by ~5e-5 max-abs after
            # 8 steps (grad psum_scatter reduction order through Adam's
            # normalized early updates) — the resume moves values
            # bitwise, so the only legitimate divergence is that same
            # class.  10x margin over it still catches real corruption,
            # which is O(param magnitude), 3+ orders larger.
            close = bool(np.allclose(canon, e_canon, rtol=1e-3,
                                     atol=5e-4))
            result[f"dp{dp}_allclose"] = close
            result[f"dp{dp}_max_abs_diff"] = float(
                np.abs(canon - e_canon).max())
            if not close:
                failures.append(
                    f"dp=2→dp={dp} resume: canonical master flat "
                    f"diverged (max abs diff "
                    f"{result[f'dp{dp}_max_abs_diff']:.3e})")
            if e_retraces:
                failures.append(
                    f"dp=2→dp={dp} resume: {e_retraces} steady-state "
                    "recompile(s) after resume")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    result["ok"] = not failures
    if as_json:
        # ONE line so callers can reverse-scan stdout past plugin noise
        # (the bench _run_isolated convention)
        print(json.dumps(result, sort_keys=True))
    else:
        for k in sorted(result):
            print(f"  {k}: {result[k]}")
    if failures:
        for f in failures:
            print(f"resume_probe: FAIL — {f}", file=sys.stderr)
        return 1
    print("resume_probe: OK (kill-mid-save survived, equal-topology "
          "resume bitwise, elastic resume allclose, zero steady-state "
          "recompiles after resume)")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="save→kill→restore→trajectory-match CI gate")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate; exit 1 on drift")
    ap.add_argument("--steps", type=int, default=8,
                    help="total training steps (default 8)")
    ap.add_argument("--save-at", type=int, default=4,
                    help="commit a checkpoint after this step")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result")
    ap.add_argument("--backend", default=None,
                    help="JAX_PLATFORMS override (resolved pre-import)")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if not 0 < args.save_at < args.steps:
        ap.error(f"--save-at must be in (0, {args.steps})")
    return probe(args.steps, args.save_at, args.json)


if __name__ == "__main__":
    sys.exit(main())
