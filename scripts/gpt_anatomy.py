"""Per-component GPT step anatomy (VERDICT r4 next-#3/#8): attribute
the missing MFU to specific ops by timing sub-programs in-jit
(slope-timed scans, dispatch-amortized).

Components at the bench configs (350M: b12 s1024; 1.3B: b8 s512):
  * embed + LM head + softmax-xent loss (fwd+bwd)
  * one transformer layer's attention sublayer (fwd+bwd) x L
  * one transformer layer's MLP sublayer (fwd+bwd) x L
  * full model step (the reference point)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PEAK = 197e12


def _scan_time(fn, args, iters=50, reps=3):
    def make(length):
        def many(*a):
            def body(carry, _):
                out = fn(*((a[0] + carry.astype(a[0].dtype),) + a[1:]))
                return sum(jnp.sum(l.astype(jnp.float32))
                           for l in jax.tree.leaves(out)) * 1e-30, None
            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = max(1, iters // 5), iters
    return (total(make(hi)) - total(make(lo))) / (hi - lo)


def anatomy(name, hidden, layers, heads, batch, seq, vocab=50304):
    print(f"--- {name}: h{hidden} L{layers} H{heads} b{batch} s{seq}",
          flush=True)
    key = jax.random.PRNGKey(0)
    d = hidden // heads
    x = jax.random.normal(key, (batch, seq, hidden), jnp.bfloat16)

    # attention sublayer: qkv proj + flash + out proj
    from apex_tpu.ops.flash_attention import flash_attention
    wqkv = jax.random.normal(key, (hidden, 3 * hidden), jnp.bfloat16) * 0.02
    wo = jax.random.normal(key, (hidden, hidden), jnp.bfloat16) * 0.02

    def attn(x, wqkv, wo):
        qkv = x @ wqkv
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_of(t):
            return t.reshape(batch, seq, heads, d).transpose(0, 2, 1, 3)

        o = flash_attention(heads_of(q), heads_of(k), heads_of(v),
                            causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(batch, seq, hidden)
        return o @ wo

    def attn_fb(x, wqkv, wo):
        out, vjp = jax.vjp(attn, x, wqkv, wo)
        return (out,) + vjp(out)

    t_attn = _scan_time(attn_fb, (x, wqkv, wo), iters=20)
    fl_attn = (2 * batch * seq * hidden * 4 * hidden       # proj
               + 2 * batch * heads * seq * seq * d * 2) * 3  # sdpa
    print(f"attn sublayer fwd+bwd: {t_attn*1e3:7.3f} ms x{layers} = "
          f"{t_attn*layers*1e3:7.1f} ms  ({fl_attn/t_attn/1e12:.0f} TF/s"
          f" {100*fl_attn/t_attn/PEAK:.0f}%pk)", flush=True)

    # MLP sublayer
    w1 = jax.random.normal(key, (hidden, 4 * hidden), jnp.bfloat16) * 0.02
    w2 = jax.random.normal(key, (4 * hidden, hidden), jnp.bfloat16) * 0.02

    def mlp(x, w1, w2):
        return (jax.nn.gelu(x @ w1)) @ w2

    def mlp_fb(x, w1, w2):
        out, vjp = jax.vjp(mlp, x, w1, w2)
        return (out,) + vjp(out)

    t_mlp = _scan_time(mlp_fb, (x, w1, w2), iters=20)
    fl_mlp = 2 * batch * seq * hidden * 8 * hidden * 3
    print(f"mlp  sublayer fwd+bwd: {t_mlp*1e3:7.3f} ms x{layers} = "
          f"{t_mlp*layers*1e3:7.1f} ms  ({fl_mlp/t_mlp/1e12:.0f} TF/s "
          f"{100*fl_mlp/t_mlp/PEAK:.0f}%pk)", flush=True)

    # LM head + loss (tied embedding matmul + xent)
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    emb = jax.random.normal(key, (vocab, hidden), jnp.bfloat16) * 0.02
    labels = jax.random.randint(key, (batch, seq), 0, vocab)

    def head(x, emb):
        logits = (x @ emb.T).astype(jnp.bfloat16)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.reshape(-1, vocab), labels.reshape(-1)))

    def head_fb(x, emb):
        out, vjp = jax.vjp(head, x, emb)
        return (out,) + vjp(jnp.ones_like(out))

    t_head = _scan_time(head_fb, (x, emb), iters=10)
    fl_head = 2 * batch * seq * hidden * vocab * 3
    print(f"LM head + xent fwd+bwd: {t_head*1e3:6.3f} ms          "
          f"({fl_head/t_head/1e12:.0f} TF/s "
          f"{100*fl_head/t_head/PEAK:.0f}%pk)", flush=True)

    # LayerNorm stack (2 per layer + final)
    from apex_tpu.ops.layer_norm import fused_layer_norm
    g = jnp.ones((hidden,))
    bb = jnp.zeros((hidden,))

    def ln_fb(x, g, bb):
        out, vjp = jax.vjp(lambda x, g, bb: fused_layer_norm(x, g, bb),
                           x, g, bb)
        return (out,) + vjp(out)

    t_ln = _scan_time(ln_fb, (x, g, bb), iters=50)
    n_ln = 2 * layers + 1
    print(f"layernorm fwd+bwd:     {t_ln*1e3:7.3f} ms x{n_ln} = "
          f"{t_ln*n_ln*1e3:7.1f} ms", flush=True)

    model_sum = (t_attn + t_mlp) * layers + t_head + t_ln * n_ln
    tot_fl = (fl_attn + fl_mlp) * layers + fl_head
    print(f"component sum: {model_sum*1e3:.1f} ms "
          f"({batch*seq/model_sum:,.0f} tok/s if additive; "
          f"model flops {tot_fl/1e12:.1f} TF)", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which in ("350m", "both"):
        anatomy("GPT-350M", 1024, 24, 16, 12, 1024)
    if which in ("1p3b", "both"):
        anatomy("GPT-1.3B", 2048, 24, 32, 8, 512)
