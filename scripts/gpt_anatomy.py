"""Per-component GPT/BERT step anatomy + per-GEMM roofline.

Round 5 (VERDICT r4 next-#3/#8) attributed the missing MFU to
sublayers by timing sub-programs in-jit (slope-timed scans,
dispatch-amortized).  Round 6 (VERDICT r5: "break the plateau or prove
it") descends one level: every individual GEMM of the training step —
QKV, attention-out, MLP-up, MLP-down, LM-head — timed as its three
constituent matmuls (fwd / dgrad / wgrad), each scored against its
SHAPE-ACHIEVABLE peak, not the paper peak:

    achievable(K) = PEAK · min(1, K / 128)

(the v5e MXU is a 128×128 systolic array; a contraction dim K < 128
fills K/128 of it — the d=64 attention matmuls top out at ~98 TF/s no
matter what the kernel does; see /opt guides + docs/PERF.md round-5
attention decomposition).  The flash kernel is scored as its 7-matmul
mix (3 contract over d, 4 over the sequence), and the xent epilogue is
reported as the LM-head row's non-GEMM residue.

Components at the bench configs (350M: b12 s1024; 1.3B: b7 s512;
BERT-Large: b32 s512 bidirectional):
  * embed + LM head + softmax-xent loss (fwd+bwd)
  * one transformer layer's attention sublayer (fwd+bwd) x L
  * one transformer layer's MLP sublayer (fwd+bwd) x L
  * full model step (the reference point)

Usage:
  python scripts/gpt_anatomy.py [350m|1p3b|bert|both]      # sublayer anatomy
  python scripts/gpt_anatomy.py roofline [350m|1p3b|bert|1p3b2k]  # per-GEMM table
  python scripts/gpt_anatomy.py blocks                     # flash block sweep, seq 512
  python scripts/gpt_anatomy.py tune [targets...]          # autotune + re-emit roofline
  python scripts/gpt_anatomy.py tune --check [targets...]  # verify committed defaults
                                                           # (nonzero exit on drift)
  python scripts/gpt_anatomy.py mem [targets...]           # AOT HBM budget tables
                                                           # (compile only, no execute)
  python scripts/gpt_anatomy.py lint [targets...]          # static lint of the bench
                                                           # steps (trace only; nonzero
                                                           # exit on new findings)
  python scripts/gpt_anatomy.py comms [targets...]         # collective inventory +
                                                           # overlap + ICI roofline
                                                           # (compile only, no execute)
  python scripts/gpt_anatomy.py timeline [targets...]      # MEASURED step anatomy from
                                                           # a profiler capture (executes
                                                           # 3 steady steps)
  python scripts/gpt_anatomy.py overlap [targets...]       # predicted-vs-measured
                                                           # per-collective overlap,
                                                           # chunked (overlap_chunks=2)
                                                           # vs monolithic spelling of
                                                           # the same tp=2 SP layer
                                                           # stack (executes both)

`tune` drives apex_tpu.tune.search over each target's flash shape (and
the flat-Adam block at the 1B point), writes the winners to the
persistent cache (apex_tpu.tune.cache_path()), then re-emits the
roofline tables so docs/PERF.md can be refreshed from the same run.
`tune --check` re-sweeps WITHOUT writing and exits 1 if any committed
default (apex_tpu/tune/defaults.py) for this device kind no longer wins
— the CI guard for stale committed configs.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

PEAK = 197e12
MXU = 128


def _scan_time(fn, args, iters=50, reps=3):
    def make(length):
        def many(*a):
            def body(carry, _):
                out = fn(*((a[0] + carry.astype(a[0].dtype),) + a[1:]))
                return sum(jnp.sum(l.astype(jnp.float32))
                           for l in jax.tree.leaves(out)) * 1e-30, None
            c, _ = lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=length)
            return c
        return jax.jit(many)

    def total(f):
        _ = np.asarray(f(*args))
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _ = np.asarray(f(*args))
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = max(1, iters // 5), iters
    return (total(make(hi)) - total(make(lo))) / (hi - lo)


def anatomy(name, hidden, layers, heads, batch, seq, vocab=50304,
            causal=True):
    print(f"--- {name}: h{hidden} L{layers} H{heads} b{batch} s{seq}",
          flush=True)
    key = jax.random.PRNGKey(0)
    d = hidden // heads
    x = jax.random.normal(key, (batch, seq, hidden), jnp.bfloat16)

    # attention sublayer: qkv proj + flash + out proj
    from apex_tpu.ops.flash_attention import flash_attention
    wqkv = jax.random.normal(key, (hidden, 3 * hidden), jnp.bfloat16) * 0.02
    wo = jax.random.normal(key, (hidden, hidden), jnp.bfloat16) * 0.02

    def attn(x, wqkv, wo):
        qkv = x @ wqkv
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_of(t):
            return t.reshape(batch, seq, heads, d).transpose(0, 2, 1, 3)

        o = flash_attention(heads_of(q), heads_of(k), heads_of(v),
                            causal=causal)
        o = o.transpose(0, 2, 1, 3).reshape(batch, seq, hidden)
        return o @ wo

    def attn_fb(x, wqkv, wo):
        out, vjp = jax.vjp(attn, x, wqkv, wo)
        return (out,) + vjp(out)

    t_attn = _scan_time(attn_fb, (x, wqkv, wo), iters=20)
    fl_attn = (2 * batch * seq * hidden * 4 * hidden       # proj
               + 2 * batch * heads * seq * seq * d * 2) * 3  # sdpa
    print(f"attn sublayer fwd+bwd: {t_attn*1e3:7.3f} ms x{layers} = "
          f"{t_attn*layers*1e3:7.1f} ms  ({fl_attn/t_attn/1e12:.0f} TF/s"
          f" {100*fl_attn/t_attn/PEAK:.0f}%pk)", flush=True)

    # MLP sublayer
    w1 = jax.random.normal(key, (hidden, 4 * hidden), jnp.bfloat16) * 0.02
    w2 = jax.random.normal(key, (4 * hidden, hidden), jnp.bfloat16) * 0.02

    def mlp(x, w1, w2):
        return (jax.nn.gelu(x @ w1)) @ w2

    def mlp_fb(x, w1, w2):
        out, vjp = jax.vjp(mlp, x, w1, w2)
        return (out,) + vjp(out)

    t_mlp = _scan_time(mlp_fb, (x, w1, w2), iters=20)
    fl_mlp = 2 * batch * seq * hidden * 8 * hidden * 3
    print(f"mlp  sublayer fwd+bwd: {t_mlp*1e3:7.3f} ms x{layers} = "
          f"{t_mlp*layers*1e3:7.1f} ms  ({fl_mlp/t_mlp/1e12:.0f} TF/s "
          f"{100*fl_mlp/t_mlp/PEAK:.0f}%pk)", flush=True)

    # LM head + loss (tied embedding matmul + xent)
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    emb = jax.random.normal(key, (vocab, hidden), jnp.bfloat16) * 0.02
    labels = jax.random.randint(key, (batch, seq), 0, vocab)

    def head(x, emb):
        logits = (x @ emb.T).astype(jnp.bfloat16)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.reshape(-1, vocab), labels.reshape(-1)))

    def head_fb(x, emb):
        out, vjp = jax.vjp(head, x, emb)
        return (out,) + vjp(jnp.ones_like(out))

    t_head = _scan_time(head_fb, (x, emb), iters=10)
    fl_head = 2 * batch * seq * hidden * vocab * 3
    print(f"LM head + xent fwd+bwd: {t_head*1e3:6.3f} ms          "
          f"({fl_head/t_head/1e12:.0f} TF/s "
          f"{100*fl_head/t_head/PEAK:.0f}%pk)", flush=True)

    # LayerNorm stack (2 per layer + final)
    from apex_tpu.ops.layer_norm import fused_layer_norm
    g = jnp.ones((hidden,))
    bb = jnp.zeros((hidden,))

    def ln_fb(x, g, bb):
        out, vjp = jax.vjp(lambda x, g, bb: fused_layer_norm(x, g, bb),
                           x, g, bb)
        return (out,) + vjp(out)

    t_ln = _scan_time(ln_fb, (x, g, bb), iters=50)
    n_ln = 2 * layers + 1
    print(f"layernorm fwd+bwd:     {t_ln*1e3:7.3f} ms x{n_ln} = "
          f"{t_ln*n_ln*1e3:7.1f} ms", flush=True)

    model_sum = (t_attn + t_mlp) * layers + t_head + t_ln * n_ln
    tot_fl = (fl_attn + fl_mlp) * layers + fl_head
    print(f"component sum: {model_sum*1e3:.1f} ms "
          f"({batch*seq/model_sum:,.0f} tok/s if additive; "
          f"model flops {tot_fl/1e12:.1f} TF)", flush=True)


# ------------------------------ per-GEMM roofline ----------------------------

def _achievable(k_contract):
    """Shape-achievable FLOP/s for one GEMM: the 128-deep contraction
    port of the MXU is the only shape term that matters at these sizes
    (M is always ≥ 3.5k rows and N ≥ 64 lanes pack)."""
    return PEAK * min(1.0, k_contract / MXU)


def _time_gemm(m, k, n, iters=30):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(key, (k, n), jnp.bfloat16) * 0.02

    def mm(x, w):
        return jnp.dot(x, w,
                       preferred_element_type=jnp.float32
                       ).astype(jnp.bfloat16)

    return _scan_time(mm, (x, w), iters=iters)


def _gemm_row(label, m, k, n, per_layer=1):
    """One logical GEMM of the step = three matmuls: fwd (M,K)x(K,N),
    dgrad (M,N)x(N,K), wgrad (K,M)x(M,N).  Returns the table row."""
    parts = [("fwd", m, k, n), ("dgrad", m, n, k), ("wgrad", k, m, n)]
    t_tot, floor = 0.0, 0.0
    sub = []
    for pname, pm, pk, pn in parts:
        fl = 2 * pm * pk * pn
        t = _time_gemm(pm, pk, pn)
        t_tot += t
        floor += fl / _achievable(pk)
        sub.append((pname, pk, fl / t / 1e12, _achievable(pk) / 1e12))
    fl_tot = sum(2 * pm * pk * pn for _, pm, pk, pn in parts)
    achieved = fl_tot / t_tot
    achievable = fl_tot / floor
    pct = 100 * achieved / achievable
    print(f"| {label:<22} | {t_tot*1e3*per_layer:7.2f} | "
          f"{achieved/1e12:6.0f} | {achievable/1e12:6.0f} | {pct:5.0f}% |",
          flush=True)
    for pname, pk, a, c in sub:
        print(f"|   · {pname:<18} |         | {a:6.0f} | {c:6.0f} | "
              f"{100*a/c:5.0f}% |  K={pk}", flush=True)
    return t_tot, fl_tot, pct


def _flash_row(batch, heads, seq, d, causal, block_q=None, block_k=None,
               heads_per_step=None, label="flash sdpa (7 mm)"):
    """The attention kernel as a 7-matmul mix: fwd S=QKᵀ + O=PV, bwd
    recompute-S + dP=dO·Vᵀ + dQ + dK + dV.  Three of the seven contract
    over d; the single-block causal config at seq ≤ 1024 executes the
    full square (no skipped blocks), which the executed-flop accounting
    reflects.  With all config args None the kernel consults the
    apex_tpu.tune cache — so a tuned machine's roofline row IS the
    tuned kernel."""
    from apex_tpu.ops.flash_attention import flash_attention
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (batch, heads, seq, d), jnp.bfloat16)
               for kk in keys)
    attn = functools.partial(flash_attention, causal=causal,
                             block_q=block_q, block_k=block_k,
                             heads_per_step=heads_per_step)

    def fb(q, k, v):
        out, vjp = jax.vjp(attn, q, k, v)
        return (out,) + vjp(out)

    t = _scan_time(fb, (q, k, v), iters=15)
    fl_one = 2 * batch * heads * seq * seq * d   # one executed matmul
    fl = 7 * fl_one
    floor = fl_one * (3 / _achievable(d) + 4 / _achievable(seq))
    achieved, achievable = fl / t, fl / floor
    pct = 100 * achieved / achievable
    print(f"| {label:<22} | {t*1e3:7.2f} | {achieved/1e12:6.0f} | "
          f"{achievable/1e12:6.0f} | {pct:5.0f}% |", flush=True)
    return t, fl, pct


def gemm_roofline(name, hidden, layers, heads, batch, seq, vocab=50304,
                  causal=True):
    """Markdown-ready roofline table: per logical GEMM of the training
    step, per-layer fwd+bwd time, achieved vs shape-achievable FLOP/s."""
    d = hidden // heads
    m_rows = batch * seq
    print(f"\n### {name} per-GEMM roofline  (h{hidden} L{layers} "
          f"H{heads} b{batch} s{seq}, M={m_rows})", flush=True)
    print("| GEMM (fwd+dgrad+wgrad) | ms/layer | TF/s | achv | %achv |",
          flush=True)
    print("|---|---|---|---|---|", flush=True)
    _gemm_row("qkv (M,H)x(H,3H)", m_rows, hidden, 3 * hidden)
    from apex_tpu import tune
    cfg = tune.tuned("flash_sdpa",
                     tune.flash_attrs(batch, heads, seq, seq, d,
                                      "bfloat16", causal))
    flabel = ("flash sdpa (7 mm)" if not cfg else
              f"flash tuned q{cfg.get('block_q')}k{cfg.get('block_k')}"
              f"hp{cfg.get('heads_per_step', 1)}")
    _flash_row(batch, heads, seq, d, causal, label=flabel)
    _gemm_row("attn_out (M,H)x(H,H)", m_rows, hidden, hidden)
    _gemm_row("mlp_up (M,H)x(H,4H)", m_rows, hidden, 4 * hidden)
    _gemm_row("mlp_down (M,4H)x(4H,H)", m_rows, 4 * hidden, hidden)
    t_lm, _, _ = _gemm_row("lm_head (M,H)x(H,V)", m_rows, hidden, vocab)

    # xent epilogue = LM-head+loss time minus its bare GEMMs — the
    # HBM-bound residue the fused bf16 xent (cross_entropy.py) halves
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, seq, hidden), jnp.bfloat16)
    emb = jax.random.normal(key, (vocab, hidden), jnp.bfloat16) * 0.02
    labels = jax.random.randint(key, (batch, seq), 0, vocab)

    def head(x, emb):
        logits = (x @ emb.T).astype(jnp.bfloat16)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.reshape(-1, vocab), labels.reshape(-1)))

    def head_fb(x, emb):
        out, vjp = jax.vjp(head, x, emb)
        return (out,) + vjp(jnp.ones_like(out))

    t_head = _scan_time(head_fb, (x, emb), iters=10)
    traffic = 2 * m_rows * vocab * 2 + m_rows * vocab * 2  # r/w logits + grad
    eps = max(t_head - t_lm, 1e-9)
    print(f"|   · xent epilogue      | {eps*1e3:7.2f} | "
          f"{traffic/eps/1e9:5.0f} GB/s effective (HBM-bound) |  |  |",
          flush=True)


def flash_block_sweep(batch=32, heads=16, seq=512, d=64, causal=False):
    """Flash block+packing re-sweep at seq 512 (the BERT/1.3B shape; the
    round-4 sweep only covered seq 1024 and predates head packing)."""
    print(f"--- flash blocks @ b{batch} H{heads} s{seq} d{d} "
          f"causal={causal}", flush=True)
    for bq, bk, hp in ((None, None, None), (512, 512, 1), (256, 512, 1),
                       (512, 256, 1), (256, 256, 1), (512, 512, 2),
                       (256, 512, 2), (512, 256, 4), (256, 256, 4)):
        try:
            t, _, _ = _flash_row(batch, heads, seq, d, causal,
                                 block_q=bq, block_k=bk,
                                 heads_per_step=hp,
                                 label=f"blocks ({bq},{bk})x{hp}")
        except Exception as e:
            print(f"blocks ({bq},{bk})x{hp}: FAIL {repr(e)[:80]}",
                  flush=True)


def _parse_key_attrs(key):
    """Invert tune.make_key: 'op|k=v,...' → (op, {k: int|bool|str})."""
    op, rest = key.split("|", 1)
    attrs = {}
    for kv in rest.split(","):
        k, v = kv.split("=", 1)
        if k in ("causal", "seg"):
            attrs[k] = v == "1"
        elif v.lstrip("-").isdigit():
            attrs[k] = int(v)
        else:
            attrs[k] = v
    return op, attrs


def _check_committed(committed):
    """Re-sweep EVERY committed default for this device kind (the keys
    themselves name the shapes) and return the list of drifted
    entries — so the CI guard can never silently skip a stale entry."""
    from apex_tpu.tune import search

    drift = []
    for key, entry in sorted(committed.items()):
        op, a = _parse_key_attrs(key)
        want = entry.get("config")
        try:
            if op == "flash_sdpa":
                if a.get("bias", "none") != "none" or a["sq"] != a["sk"]:
                    print(f"  --check: cannot sweep {key} (unsupported "
                          "key shape); skipping", flush=True)
                    continue
                print(f"--- check {key}", flush=True)
                best, _ = search.tune_flash(
                    a["b"], a["h"], a["sq"], a["d"], dtype=a["dtype"],
                    causal=a["causal"], seg=a["seg"], write=False,
                    verbose=True)
            elif op == "opt_flat":
                print(f"--- check {key}", flush=True)
                best, _ = search.tune_opt_flat(
                    a["rows"] * 128, kernel=a["kernel"], write=False)
            else:
                print(f"  --check: unknown op in {key}; skipping",
                      flush=True)
                continue
        except Exception as e:
            drift.append((key, want, f"SWEEP FAILED: {repr(e)[:80]}"))
            continue
        if best != want:
            drift.append((key, want, best))
            print(f"  DRIFT: committed {want} != fresh {best}",
                  flush=True)
        else:
            print(f"  ok: {want}", flush=True)
    return drift


def tune_mode(targets, check=False):
    """Autotune (or --check) the flash + flat-Adam configs at the bench
    shapes, then re-emit the roofline tables from the tuned cache.
    --check re-sweeps every committed default for this device kind and
    exits nonzero on any drift."""
    from apex_tpu import tune
    from apex_tpu.tune import defaults as tune_defaults
    from apex_tpu.tune import search

    kind = tune.device_kind()
    if check:
        committed = tune_defaults.DEFAULTS.get(kind, {})
        if not committed:
            print(f"tune --check: no committed defaults for device "
                  f"kind {kind!r} — nothing to verify", flush=True)
            return 0
        drift = _check_committed(committed)
        if drift:
            print(f"tune --check: {len(drift)} committed default(s) "
                  "drifted — update apex_tpu/tune/defaults.py:",
                  flush=True)
            for key, want, got in drift:
                print(f"  {key}: committed {want} -> fresh {got}",
                      flush=True)
            return 1
        print("tune --check: all committed defaults match fresh sweeps",
              flush=True)
        return 0
    for t in targets:
        nm, h, L, H, b, s, v, c = CONFIGS[t]
        d = h // H
        print(f"--- tune flash @ {nm}: b{b} H{H} s{s} d{d} causal={c}",
              flush=True)
        best, results = search.tune_flash(b, H, s, d, causal=c,
                                          write=True, verbose=True)
        print(f"  winner: {best} ({results[0][1]*1e3:.3f} ms)",
              flush=True)
    # flat-Adam block at the 1B bench point rides along
    try:
        best, _ = search.tune_opt_flat(10 ** 9, write=True)
        print(f"--- tune opt_flat @ 1B: winner {best}", flush=True)
    except Exception as e:
        print(f"--- tune opt_flat: FAIL {repr(e)[:80]}", flush=True)
    print(f"\ncache written to {tune.cache_path()} "
          f"(fingerprint {tune.fingerprint()}); tuned rooflines:",
          flush=True)
    for t in targets:
        nm, h, L, H, b, s, v, c = CONFIGS[t]
        gemm_roofline(nm, h, L, H, b, s, vocab=v, causal=c)
    return 0


# --------------------------- AOT memory anatomy ---------------------------

def _build_bench_step(t, on_tpu, mode="mem"):
    """Build one CONFIGS target's EXACT bench train step without
    compiling or executing it.  Returns (label, step, abstract args,
    analytic flops) — shared by `mem` (AOT budget) and `lint` (static
    analysis).  On a CPU backend the big configs would take minutes of
    XLA compile (mem) for no extra truth, so the smoke size
    substitutes while KEEPING the model family / optimizer / loss
    shape, so every target's build path stays exercised."""
    import jax.numpy as jnp

    from apex_tpu import monitor
    from apex_tpu.models.bert import Bert, BertConfig
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.optimizers.fused_lamb import FusedLAMB
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    nm, h, L, H, b, s, v, c = CONFIGS[t]
    is_bert = not c  # the one bidirectional bench config
    if on_tpu:
        batch = b
    else:
        print(f"--- {mode} {nm}: CPU backend, shrinking to the smoke "
              "config (structure only; run on TPU for real shapes)",
              flush=True)
        h, L, H, v = 64, 2, 4, 512
        batch, s = 2, 64
    M.destroy_model_parallel()
    if mode == "comms":
        # the comms gate is about COLLECTIVES: a single-device mesh
        # makes every group degenerate (n=1, excluded from the
        # aggregates), so the overlap gate would be vacuously green.
        # Mesh over ALL devices (dp = world, like comms_probe's
        # gpt_zero2 target); the batch must then shard over dp.
        mesh = M.initialize_model_parallel()
        dp = mesh.devices.size
        batch = -(-batch // dp) * dp
    else:
        # mem/lint read the single-program truth; one device keeps
        # the big-config XLA compile affordable
        mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    loss_fn = None
    if is_bert:
        # mirror bench._bert_seq_per_sec: BERT-Large MLM+NSP step
        # with FusedLAMB — the program must be the EXACT one the
        # bench times, not a causal GPT stand-in
        cfg = BertConfig(vocab_size=v, seq_len=s, hidden=h,
                         num_layers=L, num_heads=H,
                         dtype=jnp.bfloat16 if on_tpu
                         else jnp.float32,
                         use_flash_attention=on_tpu)
        model = Bert(cfg)
        loss_mask = jnp.zeros((batch, s), bool)
        nsp = jnp.zeros((batch,), jnp.int32)

        def loss_fn(p, tk, lb):
            return model.loss(p, tk, lb, loss_mask, nsp_labels=nsp)

        opt = FusedLAMB(lr=1e-4, weight_decay=0.01,
                        use_pallas=on_tpu,
                        master_dtype=jnp.bfloat16 if on_tpu
                        else jnp.float32)
        analytic = monitor.bert_step_flops(cfg, batch, seq=s)
    else:
        cfg = (GPTConfig(vocab_size=v, seq_len=s, hidden=h,
                         num_layers=L, num_heads=H, dropout=0.0,
                         dtype=jnp.bfloat16,
                         logits_dtype=jnp.bfloat16, remat=False,
                         use_flash_attention=True) if on_tpu else
               GPTConfig(vocab_size=v, seq_len=s, hidden=h,
                         num_layers=L, num_heads=H, dropout=0.0))
        model = GPT(cfg)
        opt = FusedAdam(lr=1e-4, use_pallas=on_tpu,
                        master_dtype=jnp.bfloat16 if on_tpu
                        else jnp.float32)
        analytic = monitor.gpt_step_flops(cfg, batch, seq=s)
    params = model.init(jax.random.PRNGKey(0))
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, loss_fn=loss_fn,
                                 donate=True)
    del params
    tokens = jax.ShapeDtypeStruct((batch, s), jnp.int32)
    labels = jax.ShapeDtypeStruct((batch, s), jnp.int32)
    label = f"{nm}: h{h} L{L} H{H} b{batch} s{s}"
    return label, step, (opt_state, tokens, labels), analytic


def mem_mode(targets):
    """Per-target HBM budget via the compile observatory (ISSUE 5):
    build the EXACT bench train step for each config, AOT lower+compile
    it WITHOUT executing, and print the budget table (params /
    optimizer state / activations+temps), the donation check, and the
    flops cross-check against monitor.flops' analytic accounting — the
    table an operator reads before picking a batch size."""
    from apex_tpu import monitor
    from apex_tpu.parallel import mesh as M

    on_tpu = jax.default_backend() not in ("cpu",)
    rc = 0
    for t in targets:
        label, step, args, analytic = _build_bench_step(t, on_tpu)
        print(f"\n--- mem {label} (AOT, no execution)", flush=True)
        rep = monitor.analyze_step(step, args, analytic_flops=analytic)
        print(monitor.render_budget_table(rep), flush=True)
        if on_tpu and (rep.donation_ok is False or rep.flops_ok is False):
            # a flagged budget is a failed gate, CI-style — but only
            # for the REAL configs; the CPU smoke substitution's flop
            # mix legitimately diverges (NSP/pooler residue at tiny h)
            rc = 1
        M.destroy_model_parallel()
    live = monitor.device_memory_stats()
    if live is not None:
        print(f"\nlive allocator: "
              f"{live.get('bytes_in_use', 0) / 2**30:.2f} GiB in use, "
              f"{live.get('peak_bytes_in_use', 0) / 2**30:.2f} GiB peak",
              flush=True)
    return rc


def lint_mode(targets):
    """Static lint of each target's EXACT bench train step (ISSUE 6):
    trace — never compile, never execute — and run apex_tpu.lint's
    dtype-policy / collective / donation passes.  Nonzero exit on any
    finding outside the committed allowlist
    (scripts/lint_allowlist.txt); `scripts/lint_step.py` is the richer
    CLI (adds the repo AST pass + --selftest)."""
    import os as _os

    from apex_tpu import lint
    from apex_tpu.parallel import mesh as M

    allowlist_path = _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)),
        "lint_allowlist.txt")
    allowlist = (lint.load_allowlist(allowlist_path)
                 if _os.path.exists(allowlist_path) else [])
    on_tpu = jax.default_backend() not in ("cpu",)
    rc = 0
    for t in targets:
        label, step, args, _ = _build_bench_step(t, on_tpu, mode="lint")
        print(f"\n--- lint {label} (trace only, no compile)",
              flush=True)
        findings = lint.lint_step(step, args, program=t)
        new, allowed = lint.apply_allowlist(findings, allowlist)
        rep = lint.LintReport(target=t, new=new, allowlisted=allowed)
        print(lint.render_findings(rep), flush=True)
        if not rep.ok:
            rc = 1
        M.destroy_model_parallel()
    return rc


def comms_mode(targets):
    """Per-target collective inventory + overlap + ICI roofline
    (ISSUE 7): build the EXACT bench train step, AOT lower+compile it
    WITHOUT executing, and print the comms table (`monitor.comms`) —
    what the step says over the interconnect and whether that talk
    hides behind compute.  Nonzero exit when an expected-overlap
    collective serialized on a backend where overlap is measurable
    (TPU); `scripts/comms_probe.py` is the richer CI gate (adds the
    ZeRO-2 dp target, the allowlist, and --selftest)."""
    from apex_tpu import monitor
    from apex_tpu.parallel import mesh as M

    on_tpu = jax.default_backend() not in ("cpu",)
    rc = 0
    for t in targets:
        label, step, args, _ = _build_bench_step(t, on_tpu, mode="comms")
        print(f"\n--- comms {label} (AOT, no execution)", flush=True)
        rep = monitor.comms_report(step, args)
        print(monitor.render_comms_table(rep, label=label), flush=True)
        if rep.async_supported and not rep.overlap_ok:
            rc = 1
        M.destroy_model_parallel()
    return rc


def timeline_mode(targets, n_steps=3):
    """Measured per-step anatomy of each target's EXACT bench train
    step (ISSUE 15): build via the shared builder (comms-style mesh —
    all devices, so the collective lanes are populated), EXECUTE two
    warmup + `n_steps` captured steps under a `ProfileCapture`, and
    print the timeline table `monitor.timeline` parses out of the
    trace — device-busy fraction, host gap, category attribution, and
    (on TPU) the measured per-collective overlap.  Nonzero exit when
    the trace parsed to zero device events or the step count drifted;
    `scripts/timeline_probe.py` is the richer CI gate (adds the ZeRO-2
    dp target, the comms crosscheck, and --selftest)."""
    import tempfile

    import jax.numpy as jnp

    from apex_tpu import monitor
    from apex_tpu.parallel import mesh as M

    on_tpu = jax.default_backend() not in ("cpu",)
    rc = 0
    for t in targets:
        label, step, (opt_state, tokens, labels), _ = \
            _build_bench_step(t, on_tpu, mode="comms")
        tok = jnp.zeros(tokens.shape, tokens.dtype)
        lab = jnp.zeros(labels.shape, labels.dtype)
        state = opt_state
        # two warmups absorb the compile + the donated-layout second
        # compile (the bench.py rule) so the capture holds STEADY steps
        for _ in range(2):
            state, loss = step(state, tok, lab)
        jax.block_until_ready(state)
        cap = monitor.profile_capture(
            range(n_steps),
            logdir=tempfile.mkdtemp(prefix="anatomy_timeline_"))
        try:
            for i in range(n_steps):
                with cap.step(i):
                    state, loss = step(state, tok, lab)
                    jax.block_until_ready(loss)
        finally:
            cap.close()  # a raise mid-capture must stop the profiler
        rep = monitor.analyze_trace(cap.trace_path())
        print(f"\n--- timeline {label} ({n_steps} measured steps)",
              flush=True)
        print(monitor.render_timeline_table(rep, label=label),
              flush=True)
        if rep.n_device_events == 0 or len(rep.steps) != n_steps:
            rc = 1
        M.destroy_model_parallel()
    return rc


def _build_overlap_step(t, on_tpu, chunks):
    """The CONFIGS target rebuilt as a tp=2 SEQUENCE-PARALLEL GPT with
    `overlap_chunks` forced — the chunked (AFTER) vs monolithic
    (BEFORE) spelling of the SAME layer stack for overlap_mode.
    Forcing the chunk count bypasses the tuner so both spellings are
    deterministic on untuned machines; everything else (model dims,
    optimizer, loss, mesh) is held fixed, so any inventory or overlap
    difference between the two is the chunking and nothing else."""
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    nm, h, L, H, b, s, v, causal = CONFIGS[t]
    if not causal:
        sys.exit(f"overlap mode needs a causal GPT target, not {nm}")
    if on_tpu:
        batch = b
        cfg = GPTConfig(vocab_size=v, seq_len=s, hidden=h,
                        num_layers=L, num_heads=H, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False, use_flash_attention=True,
                        sequence_parallel=True, overlap_chunks=chunks)
    else:
        print(f"--- overlap {nm}: CPU backend, shrinking to the smoke "
              "config (structure only; run on TPU for measured "
              "overlap)", flush=True)
        h, L, H, v = 64, 2, 4, 512
        batch, s = 2, 64
        cfg = GPTConfig(vocab_size=v, seq_len=s, hidden=h,
                        num_layers=L, num_heads=H, dropout=0.0,
                        sequence_parallel=True, overlap_chunks=chunks)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
    dp = mesh.devices.size // 2
    batch = -(-batch // max(1, dp)) * max(1, dp)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu,
                    master_dtype=jnp.bfloat16 if on_tpu
                    else jnp.float32)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tokens = jax.ShapeDtypeStruct((batch, s), jnp.int32)
    labels = jax.ShapeDtypeStruct((batch, s), jnp.int32)
    label = f"{nm}: h{h} L{L} H{H} b{batch} s{s} tp2-sp"
    return label, step, (opt_state, tokens, labels)


def _overlap_kind_summary(crep_dict, xc):
    """Per-kind rollup of one spelling: count, MiB, mean predicted and
    mean measured overlap over the counted collectives."""
    rows = {}
    meas_by_name = {r["name"]: r["measured_overlap_fraction"]
                    for r in xc["rows"]}
    for c in crep_dict["collectives"]:
        if c.get("group_size", 1) <= 1:
            continue
        r = rows.setdefault(c["kind"], dict(n=0, bytes=0, pred=[],
                                            meas=[]))
        r["n"] += 1
        r["bytes"] += c["operand_bytes"]
        if c.get("overlap_fraction") is not None:
            r["pred"].append(c["overlap_fraction"])
        m = meas_by_name.get(c["name"])
        if m is not None:
            r["meas"].append(m)
    return rows


def overlap_mode(targets, n_steps=3):
    """BEFORE/AFTER overlap anatomy (ISSUE 18): for each target, build
    the tp=2 sequence-parallel step in its MONOLITHIC (chunks=1) and
    CHUNKED (overlap_chunks=2) spelling, AOT-audit both with the comms
    observatory (predicted overlap), EXECUTE both under a profiler
    capture (measured overlap — TPU only; a CPU capture reports the
    measured plane UNMEASURABLE, honestly), and print the
    predicted-vs-measured crosscheck table per spelling plus a
    per-kind BEFORE/AFTER rollup.  This is the artifact docs/PERF.md's
    "Measured overlap — next TPU session" note asks for: the same
    layer, two spellings, one table.  Nonzero exit when a trace
    parsed broken or (on a measurable backend) a crosscheck row
    DIVERGES."""
    import tempfile

    import jax.numpy as jnp

    from apex_tpu import monitor
    from apex_tpu.monitor import comms as comms_lib
    from apex_tpu.monitor import timeline
    from apex_tpu.parallel import mesh as M

    on_tpu = jax.default_backend() not in ("cpu",)
    rc = 0
    for t in targets:
        summaries = {}
        for spelling, chunks in (("monolithic", 1), ("chunked", 2)):
            label, step, (opt_state, tokens, labels) = \
                _build_overlap_step(t, on_tpu, chunks)
            crep = comms_lib.comms_report(
                step, (opt_state, tokens, labels))
            tok = jnp.zeros(tokens.shape, tokens.dtype)
            lab = jnp.zeros(labels.shape, labels.dtype)
            state = opt_state
            for _ in range(2):  # compile + donated-layout recompile
                state, loss = step(state, tok, lab)
            jax.block_until_ready(state)
            cap = monitor.profile_capture(
                range(n_steps),
                logdir=tempfile.mkdtemp(prefix="anatomy_overlap_"))
            try:
                for i in range(n_steps):
                    with cap.step(i):
                        state, loss = step(state, tok, lab)
                        jax.block_until_ready(loss)
            finally:
                cap.close()
            rep = monitor.analyze_trace(cap.trace_path())
            xc = timeline.crosscheck_comms(rep, crep)
            print(f"\n--- overlap {label} [{spelling}, "
                  f"chunks={chunks}] ({n_steps} measured steps)",
                  flush=True)
            print(timeline.render_crosscheck(
                xc, label=f"{label} {spelling}"), flush=True)
            if not rep.overlap_measurable:
                print("measured plane: UNMEASURABLE on this backend "
                      "(honest) — predicted inventory still pins the "
                      "chunked pattern", flush=True)
            summaries[spelling] = _overlap_kind_summary(
                crep.to_dict(), xc)
            if rep.n_device_events == 0 or len(rep.steps) != n_steps:
                rc = 1
            if rep.overlap_measurable and not xc["ok"]:
                rc = 1
            M.destroy_model_parallel()

        def _fmt(vals):
            return (f"{100 * sum(vals) / len(vals):5.1f}%" if vals
                    else "  n/a ")

        print(f"\n=== overlap BEFORE/AFTER: {t} ===")
        print("| kind               | spelling   |  n |      MiB | "
              "pred ovl | meas ovl |")
        print("|---|---|---|---|---|---|")
        kinds = sorted(set(summaries["monolithic"])
                       | set(summaries["chunked"]))
        for k in kinds:
            for spelling in ("monolithic", "chunked"):
                r = summaries[spelling].get(k)
                if r is None:
                    print(f"| {k:<18} | {spelling:<10} |  0 |"
                          f"        - |      -   |      -   |")
                    continue
                print(f"| {k:<18} | {spelling:<10} | {r['n']:2d} | "
                      f"{r['bytes'] / 2**20:8.2f} | {_fmt(r['pred'])} "
                      f"| {_fmt(r['meas'])} |")
    return rc


CONFIGS = {
    # name: (hidden, layers, heads, batch, seq, vocab, causal)
    "350m": ("GPT-350M", 1024, 24, 16, 12, 1024, 50304, True),
    "1p3b": ("GPT-1.3B", 2048, 24, 32, 7, 512, 50304, True),
    # the seq-2048 1.3B attention shape — the d=64 plateau point ISSUE 3
    # targets (batch 4 keeps activations on one chip)
    "1p3b2k": ("GPT-1.3B-s2048", 2048, 24, 32, 4, 2048, 50304, True),
    "bert": ("BERT-Large", 1024, 24, 16, 32, 512, 30528, False),
}


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    if which == "roofline":
        targets = sys.argv[2:] or [t for t in CONFIGS if t != "1p3b2k"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown roofline target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        for t in targets:
            nm, h, L, H, b, s, v, c = CONFIGS[t]
            gemm_roofline(nm, h, L, H, b, s, vocab=v, causal=c)
    elif which == "tune":
        rest = sys.argv[2:]
        check = "--check" in rest
        targets = [t for t in rest if t != "--check"] or list(CONFIGS)
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown tune target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(tune_mode(targets, check=check))
    elif which == "mem":
        targets = sys.argv[2:] or ["350m"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown mem target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(mem_mode(targets))
    elif which == "lint":
        targets = sys.argv[2:] or ["350m", "bert"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown lint target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(lint_mode(targets))
    elif which == "comms":
        targets = sys.argv[2:] or ["350m"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown comms target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(comms_mode(targets))
    elif which == "timeline":
        targets = sys.argv[2:] or ["350m"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown timeline target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(timeline_mode(targets))
    elif which == "overlap":
        targets = sys.argv[2:] or ["350m"]
        bad = [t for t in targets if t not in CONFIGS]
        if bad:
            sys.exit(f"unknown overlap target(s) {bad}; "
                     f"choices: {sorted(CONFIGS)}")
        sys.exit(overlap_mode(targets))
    elif which == "blocks":
        flash_block_sweep(causal=False)   # BERT shape
        flash_block_sweep(batch=7, heads=32, seq=512, causal=True)  # 1.3B
        flash_block_sweep(batch=4, heads=32, seq=2048, causal=True)  # 2k
    elif which == "both":
        for t in ("350m", "1p3b"):
            nm, h, L, H, b, s, v, c = CONFIGS[t]
            anatomy(nm, h, L, H, b, s, vocab=v, causal=c)
    elif which in CONFIGS:
        nm, h, L, H, b, s, v, c = CONFIGS[which]
        anatomy(nm, h, L, H, b, s, vocab=v, causal=c)
    else:
        sys.exit(f"unknown mode {which!r}; expected one of "
                 f"{sorted(CONFIGS)} | both | roofline [target...] | "
                 "blocks | tune [--check] [target...] | mem [target...]"
                 " | lint [target...] | comms [target...] | "
                 "timeline [target...]")
