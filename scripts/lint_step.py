"""Static lint gate for the flagship train steps (ISSUE 6).

usage:
  python scripts/lint_step.py [targets...]      # default: gpt bert resnet ast
  python scripts/lint_step.py --selftest        # fixture schema-drift gate
  python scripts/lint_step.py --ast PATH...     # source pass over trees
  python scripts/lint_step.py --json            # machine-readable reports

Builds the EXACT flagship GPT-350M / BERT-Large / ResNet-50 train
steps (the bench.py programs; on a CPU backend the smoke-size configs
substitute, same build path), traces them WITHOUT compiling or
executing, and runs `apex_tpu.lint`'s program passes (dtype-policy,
collectives, donation) plus the repo-wide AST retrace/host-sync pass
over apex_tpu/, examples/, scripts/ and bench.py.  Exit is nonzero on
any finding not accepted by the committed allowlist
(scripts/lint_allowlist.txt) — the CI gate ZeRO-3 and the TP-overlap
work are developed against.

`--selftest` renders the committed fixture (scripts/lint_fixture.json)
through `lint.validate_findings` + `lint.render_findings` and exits
nonzero when the finding schema drifted or the rendering lost its
load-bearing markers (mirrors `flight_report.py --selftest`); run from
the tier-1 suite (tests/test_lint.py).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# scripts/ itself, for the shared gpt_anatomy._build_bench_step builder
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

# tracing is host-side; never let a pinned TPU tunnel stall the gate
# unless the operator explicitly asked for device truth
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the moe target needs a dp x ep mesh: on the CPU backend force a
# 4-way virtual mesh (must precede the first jax import, conftest-
# style; the other targets build single-device meshes and are
# unaffected by extra visible devices)
if os.environ.get("JAX_PLATFORMS") == "cpu" and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
ALLOWLIST = os.path.join(_HERE, "lint_allowlist.txt")
FIXTURE = os.path.join(_HERE, "lint_fixture.json")

# markers the fixture rendering must contain; losing one means the
# renderer no longer tells the story the fixture encodes
_FIXTURE_MARKERS = (
    "=== lint: fixture-step ===",
    "ERROR   CL201",
    "ERROR   CL206",
    "WARNING DP101",
    "WARNING DP105",
    "HS401 examples/broken.py:12",
    "fix: cast the operands",
    "5 new finding(s), 3 error(s)",
    "(1 allowlisted finding(s) accepted)",
)

# AST-pass trees (repo-relative) the default gate walks
AST_TREES = ("apex_tpu", "examples", "scripts", "bench.py", "tests")


def selftest() -> int:
    from apex_tpu import lint

    with open(FIXTURE) as f:
        rep = json.load(f)
    try:
        lint.validate_findings(rep)
        text = lint.render_findings(rep)
    except ValueError as e:
        print(f"lint_step --selftest: SCHEMA DRIFT — {e}",
              file=sys.stderr)
        print("(bump-side change? update scripts/lint_fixture.json to "
              "the new schema)", file=sys.stderr)
        return 1
    missing = [m for m in _FIXTURE_MARKERS if m not in text]
    if missing:
        print(text)
        print(f"lint_step --selftest: rendering lost expected "
              f"markers: {missing}", file=sys.stderr)
        return 1
    print(text)
    print("lint_step --selftest: OK")
    return 0


def _build_gpt(on_tpu):
    """The flagship GPT-350M step — gpt_anatomy's shared builder (the
    EXACT bench program; one copy, not a drift-prone re-spelling)."""
    import gpt_anatomy

    _, step, args, _ = gpt_anatomy._build_bench_step(
        "350m", on_tpu, mode="lint")
    return step, args


def _build_bert(on_tpu):
    """The flagship BERT-Large MLM+NSP step with FusedLAMB — same
    shared builder."""
    import gpt_anatomy

    _, step, args, _ = gpt_anatomy._build_bench_step(
        "bert", on_tpu, mode="lint")
    return step, args


def _build_resnet(on_tpu):
    """The flagship ResNet AMP-O1 step (ddp.make_train_step path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    from apex_tpu.optimizers.fused_sgd import FusedSGD
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M

    batch, size, arch = (256, 224, "resnet50") if on_tpu else \
        (4, 32, "resnet18")
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = ResNet(arch, num_classes=1000, axis_name="dp",
                   stem="space_to_depth" if on_tpu else "conv7")
    params, mstate = model.init(jax.random.PRNGKey(0))
    amp_state = amp.initialize(opt_level="O1")

    def loss_fn(p, ms, b):
        x, y = b
        logits, new_ms = model.apply(p, ms, x, training=True)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y)), new_ms

    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True)
    x = jax.ShapeDtypeStruct((batch, size, size, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((batch,), jnp.int32)
    return step, (state, scaler, mstate, (x, y))


def _build_serve(on_tpu):
    """The flagship serving DECODE step (apex_tpu.serve, ISSUE 8): the
    continuous-batching program that must stay HS4xx-clean — a host
    sync inside it would serialize every concurrent stream.  Built via
    the shared serve builder (the exact bench/example program); the
    smoke slot count keeps the CPU trace fast while exercising the
    full paged-attention + state-update jaxpr."""
    from apex_tpu.serve import build_flagship_engine

    eng = build_flagship_engine(on_tpu)
    return eng.decode_step, (eng.params, eng.kv, eng.state)


def _build_moe(on_tpu):
    """The flagship expert-parallel MoE-GPT step (apex_tpu.moe, ISSUE
    13): dp x ep mesh over all visible devices, ZeRO-2 master state
    sharded over the combined data axes, dispatch/combine all_to_alls
    over ep — the program the CL206/DP105 rules exist to hold.  Built
    via the shared builder (the exact bench program)."""
    from apex_tpu.models.moe_gpt import build_moe_train_step

    _, step, args, _ = build_moe_train_step(on_tpu)
    return step, args


BUILDERS = {"gpt": _build_gpt, "bert": _build_bert,
            "resnet": _build_resnet, "serve": _build_serve,
            "moe": _build_moe}


def main() -> int:
    ap = argparse.ArgumentParser(
        description="static lint gate for the flagship train steps")
    ap.add_argument("targets", nargs="*",
                    help=f"subset of {sorted(BUILDERS)} + 'ast' "
                         "(default: all)")
    ap.add_argument("--selftest", action="store_true",
                    help="render the committed fixture; exit 1 on "
                         "schema drift")
    ap.add_argument("--ast", nargs="+", metavar="PATH", default=None,
                    help="ONLY run the AST pass over these paths")
    ap.add_argument("--allowlist", default=ALLOWLIST,
                    help="allowlist file (default: the committed one)")
    ap.add_argument("--json", action="store_true",
                    help="print LintReport JSON lines instead of text")
    args = ap.parse_args()
    if args.selftest:
        return selftest()

    from apex_tpu import lint

    allowlist = (lint.load_allowlist(args.allowlist)
                 if os.path.exists(args.allowlist) else [])

    reports = []
    if args.ast is not None:
        targets = []
        ast_paths = args.ast
    else:
        targets = args.targets or sorted(BUILDERS) + ["ast"]
        bad = [t for t in targets if t != "ast" and t not in BUILDERS]
        if bad:
            ap.error(f"unknown target(s) {bad}; choices: "
                     f"{sorted(BUILDERS) + ['ast']}")
        ast_paths = ([os.path.join(_ROOT, t) for t in AST_TREES]
                     if "ast" in targets else [])

    import jax
    on_tpu = jax.default_backend() not in ("cpu",)
    for t in targets:
        if t == "ast":
            continue
        step, step_args = BUILDERS[t](on_tpu)
        findings = lint.lint_step(step, step_args, program=t)
        new, allowed = lint.apply_allowlist(findings, allowlist)
        reports.append(lint.LintReport(target=t, new=new,
                                       allowlisted=allowed))
        from apex_tpu.parallel import mesh as M
        M.destroy_model_parallel()
    if ast_paths:
        findings = lint.lint_paths(ast_paths, root=_ROOT)
        new, allowed = lint.apply_allowlist(findings, allowlist)
        reports.append(lint.LintReport(target="ast", new=new,
                                       allowlisted=allowed))

    rc = 0
    for rep in reports:
        if args.json:
            print(json.dumps(rep.to_dict()))
        else:
            print(lint.render_findings(rep))
            print()
        if not rep.ok:
            rc = 1
    if not args.json:
        verdict = "CLEAN" if rc == 0 else "FINDINGS — gate fails"
        print(f"lint_step: {len(reports)} target(s), {verdict}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
