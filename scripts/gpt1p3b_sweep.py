"""GPT-1.3B single-chip config sweep (VERDICT r4 next-#3: 13.2k flat
for two rounds; target >= 15.2k tok/s ~= 60% MFU).

Dials: seq 512 vs 1024, batch, remat policy (dots / attn-only / off).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

import bench
from apex_tpu.models.gpt import GPT2_1p3B, GPTConfig


def point(name, batch, seq, remat, policy, **cfg_kw):
    cfg = GPTConfig(vocab_size=50304, seq_len=seq, dropout=0.0,
                    dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                    remat=remat, remat_policy=policy,
                    use_flash_attention=True, **GPT2_1p3B, **cfg_kw)
    try:
        tps = bench._fused_tokens_per_sec(True, batch, seq, cfg,
                                          master_dtype=jnp.bfloat16)
        print(f"{name:<28} b{batch} s{seq}: {tps:,.0f} tok/s", flush=True)
    except Exception as e:
        print(f"{name:<28} b{batch} s{seq}: FAIL {repr(e)[:90]}",
              flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "a"
    if which == "a":
        point("current (dots remat)", 8, 512, True, "dots")
        point("s1024 dots", 4, 1024, True, "dots")
        point("s1024 dots b6", 6, 1024, True, "dots")
        point("s512 no-remat", 4, 512, False, None)
    elif which == "b":
        point("s512 b12 dots", 12, 512, True, "dots")
        point("s1024 b8 dots", 8, 1024, True, "dots")
        point("s512 b8 names:ffn1", 8, 512, True, "names:ffn1")
        point("s512 b6 no-remat", 6, 512, False, None)
    elif which == "c":
        point("names:all5", 8, 512, True,
              "names:qkv,attn_ctx,attn_out,ffn1,ffn_out")
        point("names:attn_ctx+ffn1", 8, 512, True,
              "names:attn_ctx,ffn1")
        point("names:all5 b10", 10, 512, True,
              "names:qkv,attn_ctx,attn_out,ffn1,ffn_out")
    elif which == "d":
        point("s512 b8 no-remat", 8, 512, False, None)
        point("s512 b7 no-remat", 7, 512, False, None)
    elif which == "e":
        # round 6: batch knee around the r5 best (b7 no-remat) now that
        # the fused bf16 xent freed the fp32 (S,B,V) xent residual, and
        # a fused-xent A/B at the same point
        point("b7 no-remat (r5 best)", 7, 512, False, None)
        point("b8 no-remat", 8, 512, False, None)
        point("b9 no-remat", 9, 512, False, None)
        point("b7 UNfused xent", 7, 512, False, None, fused_xent=False)
        point("b8 dots", 8, 512, True, "dots")
