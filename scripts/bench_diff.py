"""Schema-aware diff of two bench JSONs (ISSUE 15 satellite).

usage:
  python scripts/bench_diff.py OLD.json NEW.json [--threshold PCT]
  python scripts/bench_diff.py OLD.json NEW.json --json
  python scripts/bench_diff.py --selftest

The BENCH_r*.json trajectory is the repo's perf memory, but comparing
rounds has been a by-hand `diff <(jq .) <(jq .)` affair — and a raw
diff has no idea that tokens/s going DOWN is a regression while p99
going DOWN is an improvement.  This tool knows the schema's
directions: every top-level numeric metric of the two files is
compared, the delta judged direction-aware (throughput/MFU/busy
fraction up = good; latencies, p99s, comm/drop/shed fractions, host
gap down = good; verdict booleans True→False = regression outright),
and the exit code is nonzero when any metric regressed beyond
`--threshold` percent (default 5%) — CI-composable, like every other
gate in scripts/.

Metrics only one side carries are listed (new/gone) but never judged;
metrics with no known direction print their delta with verdict `n/a`
(a number moving is information, guessing its polarity is not).
Harness wall-clocks (`metric_durations_s`) and nested detail dicts
are excluded — they time the BENCH, not the system.

`--selftest` diffs the two committed mini-fixtures
(scripts/bench_diff_fixture_{a,b}.json) whose B side seeds a
throughput drop, a p99 rise, and a verdict-flag flip; each must be
flagged BY NAME and the reverse diff must report them as
improvements — the fixture drift gate, run from tier-1
(tests/test_bench_cli.py).
"""

import argparse
import json
import math
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE_A = os.path.join(_HERE, "bench_diff_fixture_a.json")
FIXTURE_B = os.path.join(_HERE, "bench_diff_fixture_b.json")

# keys that are numbers but not system metrics — never diffed
_SKIP = {"monitor_schema_version", "baseline_batch", "serve_streams"}

# explicit directions that the suffix rules below would mis-read
_EXPLICIT = {
    "value": +1,                      # the flagship tokens/s
    "vs_baseline": +1,
    "timeline_device_busy_fraction": +1,
    "serve_pool_util": 0,             # utilization is load, not merit
    "serve_pool_util_peak": 0,
    "loss_scale": 0,
}


def metric_direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 no verdict."""
    k = key.lower()
    if k in _EXPLICIT:
        return _EXPLICIT[k]
    if k.endswith(("_per_sec", "_per_chip")) or "per_sec" in k \
            or "goodput" in k or k.endswith("mfu"):
        return +1
    if "recompile" in k or "overflow" in k or "skipped" in k:
        return -1 if not k.endswith("_ok") else 0
    if k.endswith(("_ms", "_s")):
        return -1  # latencies, barrier/blocking seconds, p50/p99
    if k.endswith("_fraction"):
        # busy fraction up = the device worked more; every other
        # fraction in the schema (drop/shed/comm/collective/host-gap)
        # is overhead
        return +1 if "busy" in k else -1
    return 0


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def diff_metrics(old: dict, new: dict, threshold_pct: float) -> dict:
    """The engine: per-metric rows + the regression list."""
    rows, regressions, only = [], [], {"new": [], "gone": []}
    for key in sorted(set(old) | set(new)):
        if key in _SKIP:
            continue
        a, b = old.get(key), new.get(key)
        if isinstance(a, bool) or isinstance(b, bool):
            if isinstance(a, bool) and isinstance(b, bool):
                if a == b:
                    continue
                verdict = "REGRESS" if (a and not b) else "IMPROVE"
                rows.append({"metric": key, "old": a, "new": b,
                             "delta_pct": None, "verdict": verdict})
                if verdict == "REGRESS":
                    regressions.append(key)
            elif isinstance(a, bool):
                # a verdict flag VANISHING (the gate stopped stamping)
                # must be listed, not silently dropped — the exact
                # truncation failure this tool exists to surface
                only["gone"].append(key)
            else:
                only["new"].append(key)
            continue
        if not (_numeric(a) or _numeric(b)):
            continue
        if a is None or not _numeric(a):
            only["new"].append(key)
            continue
        if b is None or not _numeric(b):
            only["gone"].append(key)
            continue
        delta = b - a
        pct = (100.0 * delta / abs(a)) if a != 0 else \
            (0.0 if delta == 0 else math.inf)
        direction = metric_direction(key)
        if direction == 0:
            verdict = "n/a"
        elif abs(pct) <= threshold_pct:
            verdict = "ok"
        elif (delta > 0) == (direction > 0):
            verdict = "IMPROVE"
        else:
            verdict = "REGRESS"
        if verdict == "REGRESS":
            regressions.append(key)
        rows.append({"metric": key, "old": a, "new": b,
                     "delta_pct": None if math.isinf(pct)
                     else round(pct, 2),
                     "verdict": verdict})
    return {"rows": rows, "regressions": regressions,
            "only_in_new": only["new"], "only_in_old": only["gone"],
            "threshold_pct": threshold_pct,
            "ok": not regressions}


def render_diff(result: dict, label_a: str, label_b: str) -> str:
    lines = [
        f"=== bench diff: {label_a} -> {label_b} "
        f"(threshold {result['threshold_pct']}%) ===",
        "| metric                                 |        old |"
        "        new |   delta% | verdict |",
        "|---|---|---|---|---|",
    ]

    def fv(v):
        if isinstance(v, bool):
            return str(v).lower()
        if isinstance(v, float):
            return f"{v:.4g}"
        return str(v)

    for r in result["rows"]:
        if r["verdict"] == "ok":
            continue  # the interesting rows only; --json has them all
        pct = ("" if r["delta_pct"] is None
               else f"{r['delta_pct']:+.1f}%")
        mark = " **" if r["verdict"] == "REGRESS" else ""
        lines.append(
            f"| {r['metric']:<38} | {fv(r['old']):>10} | "
            f"{fv(r['new']):>10} | {pct:>8} | {r['verdict']}{mark} |")
    n_ok = sum(1 for r in result["rows"] if r["verdict"] == "ok")
    if n_ok:
        lines.append(f"({n_ok} metric(s) within threshold not shown)")
    if result["only_in_new"]:
        lines.append("new metrics: " + ", ".join(result["only_in_new"]))
    if result["only_in_old"]:
        lines.append("gone metrics: " + ", ".join(result["only_in_old"]))
    if result["regressions"]:
        lines.append(f"verdict: {len(result['regressions'])} "
                     f"REGRESSION(s): "
                     + ", ".join(result["regressions"]))
    else:
        lines.append("verdict: no regression")
    return "\n".join(lines)


def selftest() -> int:
    with open(FIXTURE_A) as f:
        a = json.load(f)
    with open(FIXTURE_B) as f:
        b = json.load(f)
    res = diff_metrics(a, b, threshold_pct=5.0)
    print(render_diff(res, "fixture_a", "fixture_b"))
    # the B side seeds exactly these, by name: a 20% throughput drop,
    # a 50% p99 rise, and a verdict-flag flip
    expected = {"value", "serve_p99_ms", "comms_overlap_ok"}
    got = set(res["regressions"])
    if not expected <= got:
        print(f"bench_diff --selftest: seeded regression(s) not "
              f"flagged: {sorted(expected - got)}", file=sys.stderr)
        return 1
    if "bert_seq_per_sec" in got:
        print("bench_diff --selftest: the within-threshold metric was "
              "flagged — the threshold is dead", file=sys.stderr)
        return 1
    # reversed, the seeded regressions must read as improvements (and
    # the forward improvements as regressions): the judgement is
    # direction-aware, not magnitude-only
    rev = diff_metrics(b, a, threshold_pct=5.0)
    improved = {r["metric"] for r in rev["rows"]
                if r["verdict"] == "IMPROVE"}
    if not expected <= improved:
        print(f"bench_diff --selftest: reverse diff lost the "
              f"improvements: {sorted(expected - improved)}",
              file=sys.stderr)
        return 1
    if "serve_decode_tokens_per_sec" not in rev["regressions"]:
        print("bench_diff --selftest: reverse diff failed to flag the "
              "forward improvement as a regression — direction table "
              "is asymmetric", file=sys.stderr)
        return 1
    print("bench_diff --selftest: OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="direction-aware diff of two bench JSONs")
    ap.add_argument("old", nargs="?", help="baseline BENCH_r*.json")
    ap.add_argument("new", nargs="?", help="candidate BENCH_r*.json")
    ap.add_argument("--threshold", type=float, default=5.0,
                    metavar="PCT",
                    help="regression threshold in percent (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable result")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture drift gate; exit 1 when the seeded "
                         "regressions stop being flagged")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if not args.old or not args.new:
        ap.error("need OLD.json and NEW.json (or --selftest)")

    def load(path):
        with open(path) as f:
            d = json.load(f)
        # the committed BENCH_r*.json files are driver wrappers: the
        # bench result lives under "parsed" — unwrap so both the raw
        # `python bench.py > out.json` form and the wrapper diff
        if isinstance(d.get("parsed"), dict) and "value" not in d:
            d = d["parsed"]
        return d

    old, new = load(args.old), load(args.new)
    res = diff_metrics(old, new, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(res))
    else:
        print(render_diff(res, os.path.basename(args.old),
                          os.path.basename(args.new)))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
