"""TP layers vs unsharded reference ≡ tests/L0/run_transformer/test_layers.py,
test_cross_entropy.py, test_random.py — on the 8-device CPU mesh.

Gradients are taken INSIDE the shard_map region (the same structure as
real training steps — ddp.make_train_step), which is where the Megatron
custom_vjp collective semantics apply.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.xentropy import softmax_cross_entropy_reference
from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.tensor_parallel import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    model_parallel_fold_in,
    vocab_parallel_cross_entropy,
)

TP = 8
COL_SPEC = {"weight": P(None, "tp"), "bias": P("tp")}
ROW_SPEC = {"weight": P("tp", None), "bias": P()}


def _mesh():
    return M.initialize_model_parallel(tensor_model_parallel_size=TP)


def _tree_close(a, b, rtol=1e-4, atol=1e-4):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


def test_column_parallel_linear():
    mesh = _mesh()
    col = ColumnParallelLinear(12, 24, gather_output=True)
    params = col.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 12))

    f = shard_map(col.apply, mesh=mesh, in_specs=(COL_SPEC, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, x)
    want = x @ params["weight"] + params["bias"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def local_grads(p, x):
        return jax.grad(
            lambda p, x: jnp.sum(col.apply(p, x) ** 2), argnums=(0, 1)
        )(p, x)

    g = shard_map(local_grads, mesh=mesh, in_specs=(COL_SPEC, P()),
                  out_specs=(COL_SPEC, P()), check_vma=False)(params, x)
    ref = jax.grad(
        lambda p, x: jnp.sum((x @ p["weight"] + p["bias"]) ** 2),
        argnums=(0, 1))(params, x)
    _tree_close(g, ref)


def test_column_row_mlp_pattern():
    """col(no gather) → gelu → row(input_is_parallel): the Megatron MLP."""
    mesh = _mesh()
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    pc = col.init(jax.random.PRNGKey(2))
    pr = row.init(jax.random.PRNGKey(3))
    x = jax.random.normal(jax.random.PRNGKey(4), (7, 16))

    def mlp_local(pc, pr, x):
        return row.apply(pr, jax.nn.gelu(col.apply(pc, x)))

    def ref(pc, pr, x):
        return jax.nn.gelu(x @ pc["weight"] + pc["bias"]) @ pr["weight"] \
            + pr["bias"]

    f = shard_map(mlp_local, mesh=mesh, in_specs=(COL_SPEC, ROW_SPEC, P()),
                  out_specs=P(), check_vma=False)
    np.testing.assert_allclose(np.asarray(f(pc, pr, x)),
                               np.asarray(ref(pc, pr, x)),
                               rtol=1e-4, atol=1e-4)

    def local_grads(pc, pr, x):
        return jax.grad(lambda a, b, c: jnp.sum(mlp_local(a, b, c) ** 2),
                        argnums=(0, 1, 2))(pc, pr, x)

    g = shard_map(local_grads, mesh=mesh,
                  in_specs=(COL_SPEC, ROW_SPEC, P()),
                  out_specs=(COL_SPEC, ROW_SPEC, P()),
                  check_vma=False)(pc, pr, x)
    r = jax.grad(lambda a, b, c: jnp.sum(ref(a, b, c) ** 2),
                 argnums=(0, 1, 2))(pc, pr, x)
    _tree_close(g, r)


def test_sequence_parallel_mlp():
    """SP: seq-sharded in/out around the TP block (mappings.py:213-268)."""
    mesh = _mesh()
    col = ColumnParallelLinear(16, 32, gather_output=False,
                               sequence_parallel=True)
    row = RowParallelLinear(32, 16, input_is_parallel=True,
                            sequence_parallel=True)
    pc = col.init(jax.random.PRNGKey(5))
    pr = row.init(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (16, 16))  # (seq, d)

    def mlp_local(pc, pr, x):
        return row.apply(pr, jax.nn.gelu(col.apply(pc, x)))

    def ref(pc, pr, x):
        return jax.nn.gelu(x @ pc["weight"] + pc["bias"]) @ pr["weight"] \
            + pr["bias"]

    f = shard_map(mlp_local, mesh=mesh,
                  in_specs=(COL_SPEC, ROW_SPEC, P("tp")),
                  out_specs=P("tp"), check_vma=False)
    np.testing.assert_allclose(np.asarray(f(pc, pr, x)),
                               np.asarray(ref(pc, pr, x)),
                               rtol=1e-4, atol=1e-4)

    def local_grads(pc, pr, x):
        # NOTE: the local loss stays UNREDUCED (no psum): each rank seeds
        # its own sequence-slice term; the collective custom_vjps mix the
        # cross-rank contributions in backward (Megatron semantics).
        def loss(a, b, c):
            y = mlp_local(a, b, c)
            return jnp.sum(y ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(pc, pr, x)

    g = shard_map(local_grads, mesh=mesh,
                  in_specs=(COL_SPEC, ROW_SPEC, P("tp")),
                  out_specs=(COL_SPEC, ROW_SPEC, P("tp")),
                  check_vma=False)(pc, pr, x)
    r = jax.grad(lambda a, b, c: jnp.sum(ref(a, b, c) ** 2),
                 argnums=(0, 1, 2))(pc, pr, x)
    # row bias is replicated but its grad accumulates per-shard
    # contributions only on this rank's sequence slice — psum over tp
    # happens via the collective custom_vjp; compare directly:
    _tree_close(g, r)


def test_vocab_parallel_embedding():
    mesh = _mesh()
    emb = VocabParallelEmbedding(64, 8)
    params = emb.init(jax.random.PRNGKey(8))
    ids = jax.random.randint(jax.random.PRNGKey(9), (4, 6), 0, 64)
    espec = {"weight": P("tp", None)}

    f = shard_map(emb.apply, mesh=mesh, in_specs=(espec, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, ids)
    want = jnp.take(params["weight"], ids, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)

    def local_grads(p, ids):
        return jax.grad(lambda p: jnp.sum(emb.apply(p, ids) ** 2))(p)

    g = shard_map(local_grads, mesh=mesh, in_specs=(espec, P()),
                  out_specs=espec, check_vma=False)(params, ids)
    r = jax.grad(lambda p: jnp.sum(jnp.take(p["weight"], ids, 0) ** 2))(params)
    _tree_close(g, r, rtol=1e-5, atol=1e-5)


def test_vocab_parallel_cross_entropy():
    mesh = _mesh()
    logits = jax.random.normal(jax.random.PRNGKey(10), (6, 64)) * 3
    labels = jax.random.randint(jax.random.PRNGKey(11), (6,), 0, 64)

    for smoothing in (0.0, 0.1):
        f = shard_map(
            lambda lg, lb: vocab_parallel_cross_entropy(lg, lb, smoothing),
            mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(),
            check_vma=False)
        got = f(logits, labels)
        want = softmax_cross_entropy_reference(logits, labels, smoothing)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

        def local_grads(lg, lb):
            return jax.grad(lambda lg: jnp.mean(
                vocab_parallel_cross_entropy(lg, lb, smoothing)))(lg)

        g = shard_map(local_grads, mesh=mesh,
                      in_specs=(P(None, "tp"), P()),
                      out_specs=P(None, "tp"), check_vma=False)(logits,
                                                                labels)
        r = jax.grad(lambda lg: jnp.mean(
            softmax_cross_entropy_reference(lg, labels, smoothing)))(logits)
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_model_parallel_fold_in_diverges():
    """≡ test_random.py: tp ranks share default key, diverge on the
    model-parallel key."""
    mesh = _mesh()
    key = jax.random.PRNGKey(0)

    def local(k):
        sub = model_parallel_fold_in(k)
        return jax.random.normal(sub, (1, 4))

    f = shard_map(local, mesh=mesh, in_specs=P(), out_specs=P("tp"),
                  check_vma=False)
    out = np.asarray(f(key))
    assert len({tuple(r) for r in out.round(6).tolist()}) == TP


def test_vocab_parallel_cross_entropy_fused_matches_unfused_fp32():
    """The fused custom_vjp backward must reproduce the AD-derived
    backward bit-for-near-bit on fp32 logits (same fp32 math, different
    derivation)."""
    mesh = _mesh()
    logits = jax.random.normal(jax.random.PRNGKey(20), (6, 64)) * 3
    labels = jax.random.randint(jax.random.PRNGKey(21), (6,), 0, 64)

    for smoothing in (0.0, 0.1):
        outs = {}
        for fused in (False, True):
            f = shard_map(
                lambda lg, lb: vocab_parallel_cross_entropy(
                    lg, lb, smoothing, fused=fused),
                mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(),
                check_vma=False)

            def local_grads(lg, lb):
                return jax.grad(lambda lg: jnp.mean(
                    vocab_parallel_cross_entropy(
                        lg, lb, smoothing, fused=fused)))(lg)

            g = shard_map(local_grads, mesh=mesh,
                          in_specs=(P(None, "tp"), P()),
                          out_specs=P(None, "tp"),
                          check_vma=False)(logits, labels)
            outs[fused] = (f(logits, labels), g)
        np.testing.assert_allclose(np.asarray(outs[True][0]),
                                   np.asarray(outs[False][0]),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(outs[True][1]),
                                   np.asarray(outs[False][1]),
                                   rtol=1e-5, atol=1e-7)


def test_vocab_parallel_cross_entropy_bf16_auto_fused():
    """bf16 logits auto-select the fused path (fused=None); loss and
    grads must track the fp32 reference on the SAME (bf16-quantized)
    logits within bf16 resolution, and the cotangent must come back in
    the logits dtype (the point of the fusion: no fp32 (S, B, V)
    residual)."""
    mesh = _mesh()
    logits = (jax.random.normal(jax.random.PRNGKey(22), (6, 64)) * 3
              ).astype(jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(23), (6,), 0, 64)
    want = softmax_cross_entropy_reference(
        logits.astype(jnp.float32), labels)

    f = shard_map(
        lambda lg, lb: vocab_parallel_cross_entropy(lg, lb),
        mesh=mesh, in_specs=(P(None, "tp"), P()), out_specs=P(),
        check_vma=False)
    got = f(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)

    def local_grads(lg, lb):
        return jax.grad(lambda lg: jnp.mean(
            vocab_parallel_cross_entropy(lg, lb)))(lg)

    g = shard_map(local_grads, mesh=mesh,
                  in_specs=(P(None, "tp"), P()),
                  out_specs=P(None, "tp"), check_vma=False)(logits, labels)
    assert g.dtype == jnp.bfloat16
    r = jax.grad(lambda lg: jnp.mean(softmax_cross_entropy_reference(
        lg, labels)))(logits.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(g, np.float32), np.asarray(r),
                               rtol=0.02, atol=2e-3)
