"""Serving observatory (ISSUE 10): the streaming percentile estimator
vs the NumPy oracle (exact below reservoir capacity, tolerance above,
tiny-sample edges), request-lifecycle ledger exactness under a
hand-tracked churn schedule (head-of-line queue waits included), the
re-expressed `measure_decode` pinned to the old percentile math, the
SCHEMA v7 `serve_*` stamps through `MetricsLogger(serve=engine)`,
crash-dump ledger attachment validity, SLO verdicts naming the
violated axis, and the `scripts/slo_probe.py` CI gates."""

import json
import os
import pathlib
import subprocess
import sys

import jax
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.serve import (
    DecodeEngine,
    ServeConfig,
    ServeSLO,
    StreamingPercentiles,
    measure_decode,
    step_latency_percentiles,
    validate_serve_report,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent

_CFG = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                 num_heads=4, dropout=0.0)
_SC = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                  page_size=4)


def _params(seed=7, spread=20.0):
    params = GPT(_CFG).init(jax.random.PRNGKey(seed))
    params["pos_embed"] = params["pos_embed"] * spread
    return params


def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


# ------------------------------------------------------------------
# streaming percentile estimator vs the NumPy oracle
# ------------------------------------------------------------------


@pytest.mark.parametrize("dist", ["uniform", "lognormal", "normal",
                                  "constant", "bimodal"])
def test_estimator_exact_below_capacity(dist):
    """Below reservoir capacity the estimator retains EVERY sample, so
    its percentiles must equal np.percentile exactly (same linear
    interpolation) — across distribution shapes."""
    rng = np.random.RandomState(0)
    xs = {
        "uniform": rng.rand(300),
        "lognormal": rng.lognormal(0.0, 2.0, 300),
        "normal": rng.randn(300),
        "constant": np.full(300, 3.25),
        "bimodal": np.concatenate([rng.randn(150), 100 + rng.randn(150)]),
    }[dist]
    est = StreamingPercentiles(capacity=4096, seed=0)
    est.extend(xs)
    for q in (0.0, 25.0, 50.0, 95.0, 99.0, 100.0):
        want = float(np.percentile(xs, q))
        got = est.percentile(q)
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12,
                                   err_msg=f"{dist} p{q}")
    assert est.n == len(xs)
    np.testing.assert_allclose(est.mean, xs.mean(), rtol=1e-12)
    assert est.min == xs.min() and est.max == xs.max()


def test_estimator_tiny_sample_edges():
    est = StreamingPercentiles(capacity=16, seed=0)
    assert est.percentile(50.0) is None          # empty: no samples,
    assert est.mean is None and est.max is None  # never a fake zero
    s = est.summary()
    assert s["n"] == 0 and s["p99"] is None

    est.add(4.0)                                 # one sample: every q
    for q in (0.0, 50.0, 100.0):                 # IS that sample
        assert est.percentile(q) == 4.0
    for n in (2, 3, 5):                          # tiny n: exact oracle
        e = StreamingPercentiles(capacity=16, seed=0)
        xs = np.arange(n, dtype=float) * 1.5
        e.extend(xs)
        for q in (10.0, 50.0, 99.0):
            np.testing.assert_allclose(
                e.percentile(q), float(np.percentile(xs, q)),
                rtol=1e-12)

    with pytest.raises(ValueError, match="non-finite"):
        est.add(float("nan"))
    with pytest.raises(ValueError, match="not in"):
        est.percentile(101.0)
    with pytest.raises(ValueError, match="capacity"):
        StreamingPercentiles(capacity=0)


def test_estimator_reservoir_beyond_capacity():
    """Above capacity: lifetime counters stay exact, percentile
    estimates stay tolerance-close to the oracle, memory stays
    bounded, and the eviction pattern is deterministic (seeded)."""
    rng = np.random.RandomState(42)
    xs = rng.lognormal(0.0, 1.0, 30_000)
    a = StreamingPercentiles(capacity=1024, seed=0)
    b = StreamingPercentiles(capacity=1024, seed=0)
    for x in xs:
        a.add(x)
        b.add(x)
    assert a.n == len(xs) and len(a._buf) == 1024
    np.testing.assert_allclose(a.mean, xs.mean(), rtol=1e-12)
    assert a.max == xs.max() and a.min == xs.min()   # exact extremes
    assert abs(a.percentile(50.0) - np.percentile(xs, 50)) \
        / np.percentile(xs, 50) < 0.15
    assert abs(a.percentile(99.0) - np.percentile(xs, 99)) \
        / np.percentile(xs, 99) < 0.35
    # determinism: same seed + same stream -> identical estimate
    assert a.percentile(99.0) == b.percentile(99.0)


# ------------------------------------------------------------------
# measure_decode re-expression: regression pin vs the old math
# ------------------------------------------------------------------


def test_step_latency_percentiles_pins_old_measure_decode_math():
    """The satellite regression gate: `step_latency_percentiles` must
    reproduce the percentile math previously inlined in
    `measure_decode` — on identical recorded step durations — for
    normal, all-churn-fallback, and short-window cases."""
    rng = np.random.RandomState(3)
    cases = [
        (list(rng.rand(40) * 1e-2), list(rng.rand(40) < 0.3), 2),
        (list(rng.rand(5) * 1e-3), [True, False, True, False, False], 2),
        ([0.5, 0.01], [True, True], 2),          # all-churn fallback
        ([0.7], [True], 2),                      # single step
        (list(rng.rand(10)), [False] * 10, 5),   # custom warm
    ]
    for per_step, churn, warm in cases:
        # the pre-ISSUE-10 implementation, verbatim
        w = min(warm, len(per_step) - 1)
        window = per_step[w:]
        pure = [t for t, c in zip(window, churn[w:]) if not c]
        decode_only = pure or window
        want_p50 = 1e3 * float(np.percentile(decode_only, 50))
        want_p99 = 1e3 * float(np.percentile(decode_only, 99))

        got = step_latency_percentiles(per_step, churn, warm=warm)
        assert got["p50_ms"] == want_p50 and got["p99_ms"] == want_p99
        assert got["pure_decode_steps"] == len(pure)
        assert got["window_steps"] == len(window)

    with pytest.raises(ValueError, match="no steps"):
        step_latency_percentiles([], [])
    with pytest.raises(ValueError, match="churn flags"):
        step_latency_percentiles([0.1, 0.2], [True])


def test_measure_decode_quotes_shared_convention_and_ledger():
    """measure_decode's returned p50/p99 must equal
    step_latency_percentiles over its own per_step_s/churn record, and
    its new admitted/retired/ledger keys must reconcile."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)
    budgets = [3, 5, 2, 4, 6]
    for i, b in enumerate(budgets):
        eng.submit([i + 1, i + 2], b)
    m = measure_decode(eng)
    pct = step_latency_percentiles(m["per_step_s"], m["churn"], warm=2)
    assert m["p50_ms"] == pct["p50_ms"]
    assert m["p99_ms"] == pct["p99_ms"]
    assert m["pure_decode_steps"] == pct["pure_decode_steps"]
    assert m["admitted"] == m["retired"] == len(budgets)
    assert m["ledger"]["n_retired"] == len(budgets)
    assert m["ledger"]["tokens_emitted"] == sum(budgets)
    # the live step-time estimator saw the same pure decode steps
    assert eng.telemetry.step_lat.n == m["pure_decode_steps"] or \
        eng.telemetry.step_lat.n == 0  # (all-churn tiny runs)


# ------------------------------------------------------------------
# ledger accounting under a hand-tracked churn schedule
# ------------------------------------------------------------------


def test_ledger_accounting_exact_vs_hand_tracked_churn():
    """Drive 8 ragged requests through 3 slots STEP BY STEP, tracking
    the engine's (admitted, retired) returns by hand: the ledger's
    counters must move in lockstep, every lifecycle is causally
    ordered, per-request token counts match what poll() returned, and
    head-of-line-blocked requests carry strictly positive queue wait
    while the first-admitted cohort's is (near) zero."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)          # 3 slots
    prompts = [[1, 2], [3, 4, 5], [7], [9, 10, 11, 12], [13, 14],
               [15, 16, 17, 18, 19], [21], [22, 23]]
    budgets = [4, 6, 3, 5, 8, 2, 7, 4]
    rids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    led = eng.telemetry.ledger
    assert led.n_submitted == len(prompts) and led.n_admitted == 0

    hand_admitted = hand_retired = 0
    finished = {}
    steps = 0
    while eng.pending:
        a, r = eng.step()
        hand_admitted += a
        hand_retired += r
        # lockstep: the ledger's lifetime counters ARE the hand tally
        assert led.n_admitted == hand_admitted
        assert led.n_retired == hand_retired
        for f in eng.poll():
            finished[f.request_id] = f.tokens
        steps += 1
        assert steps < 200
    assert hand_admitted == hand_retired == len(prompts)
    assert led.n_open == 0
    assert led.tokens_emitted == sum(budgets) == sum(
        len(t) for t in finished.values())

    tail = {rec.request_id: rec for rec in led.tail}
    assert set(tail) == set(rids)
    for rid in rids:
        rec = tail[rid]
        assert rec.n_tokens == len(finished[rid])
        assert (rec.submit_t <= rec.admit_t <= rec.first_token_t
                <= rec.retire_t), rec.to_dict()
        assert rec.queue_wait_s >= 0 and rec.ttft_s > 0
    # churn: 8 requests into 3 slots — the first three admit
    # immediately, the rest are head-of-line blocked behind live
    # decodes, so their queue wait must dominate the first cohort's
    waits = sorted(tail[r].queue_wait_s for r in rids)
    first_cohort, blocked = waits[:3], waits[3:]
    assert min(blocked) > 0.0
    assert float(np.median(blocked)) > float(np.median(first_cohort))
    # the very first admission never waited on anything
    assert min(first_cohort) < min(blocked)
    # estimators saw exactly the retired requests' samples
    assert led.ttft.n == led.queue_wait.n == len(prompts)
    want_p99 = float(np.percentile(
        [tail[r].queue_wait_s for r in rids], 99))
    np.testing.assert_allclose(led.queue_wait.percentile(99.0),
                               want_p99, rtol=1e-12)


def test_telemetry_off_is_bitwise_and_free():
    """telemetry=False: no ledger, identical tokens (the observatory
    observes, it never steers), and serve_record() is empty."""
    params = _params(seed=11)
    prompts = [[1, 2], [3, 4, 5], [7], [9, 10]]
    budgets = [4, 6, 3, 5]
    eng_on = DecodeEngine(_CFG, params, _SC)
    eng_off = DecodeEngine(_CFG, params, _SC, telemetry=False)
    assert eng_off.telemetry is None and eng_off.serve_record() == {}
    on = {}
    for p, b in zip(prompts, budgets):
        rid = eng_on.submit(p, b)
        on[rid] = None
    for f in eng_on.run():
        on[f.request_id] = f.tokens
    off = {}
    for p, b in zip(prompts, budgets):
        rid = eng_off.submit(p, b)
        off[rid] = None
    for f in eng_off.run():
        off[f.request_id] = f.tokens
    assert on == off


def test_restored_requests_reconcile_without_poisoning_estimators():
    """Preemption resume (ISSUE 9 x ISSUE 10): a snapshot restored
    into a fresh engine re-registers queued + in-flight requests so
    retire events still reconcile — but in-flight ones are marked
    `restored` and never feed the latency estimators (their stamps
    are resume-relative)."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)
    for i in range(5):                       # 3 live + 2 queued
        eng.submit([i + 1, i + 2], 6)
    eng.step()
    eng.step()
    snap = eng.state_dict()
    n_live = len(snap["scheduler"]["live"])
    n_queued = len(snap["scheduler"]["pending"])
    assert n_live == 3 and n_queued == 2

    eng2 = DecodeEngine(_CFG, params, _SC)
    eng2.load_state_dict(snap)
    led2 = eng2.telemetry.ledger
    assert led2.n_submitted == n_live + n_queued
    assert led2.n_admitted == n_live         # in-flight re-registered
    fins = eng2.run()
    assert led2.n_retired == n_live + n_queued
    assert len(fins) == n_live + n_queued
    restored = [r for r in led2.tail if r.restored]
    assert len(restored) == n_live
    # only the re-queued cohort (real queue waits from the restore
    # point) feeds the estimators
    assert led2.ttft.n == n_queued
    assert led2.queue_wait.n == n_queued

    # in-place ROLLBACK on a non-fresh engine: the ledger is rebuilt,
    # not appended to — pre-rollback rids are not double-counted and
    # no record is stranded open, so reconciliation still closes
    eng2.submit([9, 9], 3)                   # post-restore traffic
    eng2.run()
    eng2.load_state_dict(snap)               # roll eng2 itself back
    led3 = eng2.telemetry.ledger
    assert led3.n_submitted == n_live + n_queued
    assert led3.n_retired == 0
    eng2.run()
    assert led3.n_retired == n_live + n_queued
    assert led3.n_open == 0


# ------------------------------------------------------------------
# SCHEMA v7: serve_* stamps + MetricsLogger(serve=engine)
# ------------------------------------------------------------------


def _base_record():
    return {
        "monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
        "loss": 1.0, "grad_norm": 1.0, "param_norm": 1.0,
        "update_norm": 0.1, "loss_scale": 1.0, "overflow_count": 0,
        "skipped_steps": 0, "tokens_seen": 10.0, "step_time_ms": 1.0,
        "tokens_per_sec": 10.0, "mfu": 0.1,
    }


def test_engine_serve_record_validates_v7():
    """A drained engine's serve_record() carries the full v7 plane and
    validates; nulls and mistyped values under the reserved prefix are
    rejected (never-null, the v4 rule)."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC,
                       slo=ServeSLO(ttft_p99_ms=1e9))
    for i in range(4):
        eng.submit([i + 1, i + 2], 4)
    eng.run()
    rec = _base_record()
    sr = eng.serve_record()
    for k in ("serve_queue_depth", "serve_slots_live",
              "serve_pool_util", "serve_ttft_p50_ms",
              "serve_ttft_p99_ms", "serve_token_p50_ms",
              "serve_token_p99_ms", "serve_queue_wait_p99_ms",
              "serve_queue_wait_max_ms", "serve_requests_retired",
              "serve_tokens_emitted", "serve_slo_ok"):
        assert k in sr, k
    assert sr["serve_slo_ok"] is True
    assert sr["serve_requests_retired"] == 4
    rec.update(sr)
    monitor.validate_record(rec)

    with pytest.raises(ValueError, match="serve_ttft_p99_ms"):
        monitor.validate_record(dict(rec, serve_ttft_p99_ms=None))
    with pytest.raises(ValueError, match="serve_slo_ok"):
        monitor.validate_record(dict(rec, serve_slo_ok=1))
    with pytest.raises(ValueError, match="serve_queue_depth"):
        monitor.validate_record(dict(rec, serve_queue_depth=1.5))
    with pytest.raises(ValueError, match="scalar"):
        monitor.validate_record(dict(rec, serve_gauges={"a": 1}))


def test_metrics_logger_stamps_live_serve_plane(tmp_path):
    """MetricsLogger(serve=engine): every record gains the live
    gauges; percentile fields appear once requests have retired — and
    the whole JSONL stream round-trips through validate_records."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)
    path = tmp_path / "m.jsonl"
    logger = monitor.MetricsLogger([monitor.JSONLSink(str(path))],
                                   serve=eng, log_tuner=False)
    metrics = monitor.init_metrics()

    # before any serving: gauges stamp (zeros), percentiles absent
    metrics = metrics._replace(step=metrics.step + 1)
    r1 = logger.log_step(metrics)
    assert r1["serve_queue_depth"] == 0 and r1["serve_slots_live"] == 0
    assert "serve_ttft_p99_ms" not in r1

    for i in range(5):
        eng.submit([i + 1, i + 2], 4)
    eng.run()
    metrics = metrics._replace(step=metrics.step + 1)
    r2 = logger.log_step(metrics)
    assert r2["serve_requests_retired"] == 5
    assert r2["serve_ttft_p99_ms"] > 0
    assert r2["serve_queue_wait_p99_ms"] >= 0
    logger.close()

    records = [json.loads(line) for line in path.read_text().splitlines()]
    monitor.validate_records(records)
    assert records[1]["serve_tokens_emitted"] == 20


# ------------------------------------------------------------------
# crash-dump attachment
# ------------------------------------------------------------------


def test_crash_dump_carries_ledger_tail(tmp_path):
    """FlightRecorder.attach_serve (auto-hooked by the engine's
    recorder= arg): the dump is valid JSON whose `serve` key holds a
    schema-valid telemetry report with the ledger tail AS OF the
    crash, and validate_report still accepts the full artifact (the
    additive no-schema-change contract)."""
    from apex_tpu.monitor.trace.report import validate_report

    params = _params(seed=11)
    rec = monitor.FlightRecorder(str(tmp_path / "flight.json"),
                                 capacity=8)
    eng = DecodeEngine(_CFG, params, _SC, recorder=rec)
    for i in range(4):
        eng.submit([i + 1, i + 2], 3)
    with pytest.raises(RuntimeError, match="boom"):
        with rec.guard():
            while eng.pending:
                eng.step()
                if eng.telemetry.ledger.n_retired >= 2:
                    raise RuntimeError("boom")

    with open(tmp_path / "flight.json") as f:
        dump = json.load(f)                      # valid JSON, period
    validate_report(dump)
    serve = dump["serve"]
    validate_serve_report(serve)
    assert serve["ledger"]["n_retired"] >= 2
    assert len(serve["ledger_tail"]) == serve["ledger"]["n_retired"]
    for entry in serve["ledger_tail"]:
        assert entry["retire_t"] >= entry["submit_t"]
    assert serve["stats"]["n_slots"] == _SC.n_slots

    # a dict attachment (post-mortem path) works the same way
    rec2 = monitor.FlightRecorder(str(tmp_path / "f2.json"))
    rec2.attach_serve(eng.telemetry_report())
    dump2 = rec2.dump()
    validate_serve_report(dump2["serve"])


# ------------------------------------------------------------------
# SLO verdicts
# ------------------------------------------------------------------


def test_slo_verdict_names_axis_and_percentile():
    slo = ServeSLO(ttft_p99_ms=10.0, per_token_p99_ms=5.0,
                   max_queue_wait_ms=100.0)
    ok = slo.evaluate_summary({"ttft_p99_ms": 9.0,
                               "per_token_p99_ms": 4.0,
                               "queue_wait_max_ms": 99.0})
    assert ok.ok and not ok.breaches and not ok.skipped
    assert "OK" in ok.describe()

    bad = slo.evaluate_summary({"ttft_p99_ms": 25.0,
                                "per_token_p99_ms": 4.0,
                                "queue_wait_max_ms": 250.0})
    assert not bad.ok
    axes = {(b.axis, b.percentile) for b in bad.breaches}
    assert axes == {("ttft", "p99"), ("queue_wait", "max")}
    assert "ttft" in bad.describe() and "queue_wait" in bad.describe()
    d = bad.to_dict()
    assert d["ok"] is False and len(d["breaches"]) == 2

    # a configured axis with NO samples is skipped, never green —
    # and a partially-skipped green is NOT grounded (must not stamp)
    sk = slo.evaluate_summary({"ttft_p99_ms": 9.0,
                               "per_token_p99_ms": None,
                               "queue_wait_max_ms": None})
    assert sk.ok and set(sk.skipped) == {"per_token", "queue_wait"}
    assert sk.n_judged == 1 and not sk.grounded
    # a breach is always grounded, even with other axes skipped
    skbad = slo.evaluate_summary({"ttft_p99_ms": 99.0,
                                  "per_token_p99_ms": None,
                                  "queue_wait_max_ms": None})
    assert not skbad.ok and skbad.grounded
    # fully measured green IS grounded
    assert ok.n_judged == 3 and ok.grounded
    assert ok.to_dict()["grounded"] is True
    # disabled axes are neither judged nor skipped; an all-disabled
    # SLO judges nothing and grounds nothing
    none = ServeSLO().evaluate_summary({"ttft_p99_ms": 1e9})
    assert none.ok and not none.skipped
    assert none.n_judged == 0 and not none.grounded


def test_zero_span_requests_carry_no_per_token_signal():
    """A request that finishes within its admitting step has its
    first-token and retire stamps ride the SAME poll: per_token_s is
    None (not 0.0 — a zero sample would deflate the estimator, and a
    per_token SLO would pass vacuously on short-request workloads)."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC)
    for i in range(3):
        eng.submit([i + 1, i + 2], 2)     # prefill token + 1 decode:
    eng.run()                             # done within admitting step
    led = eng.telemetry.ledger
    assert led.n_retired == 3
    for rec in led.tail:
        assert rec.n_tokens == 2
        assert rec.decode_s == 0.0        # same-poll stamps...
        assert rec.per_token_s is None    # ...are not a latency sample
    assert led.token_lat.n == 0
    # and the SLO correctly reports the axis as unmeasured
    v = eng.slo_verdict(ServeSLO(per_token_p99_ms=1.0))
    assert v.ok and v.skipped == ["per_token"] and not v.grounded


def test_slo_ok_stamp_requires_grounded_verdict():
    """serve_record must NOT stamp serve_slo_ok while every configured
    axis is unmeasured (idle engine) — a vacuous green in the JSONL
    stream would be indistinguishable from a measured pass."""
    params = _params(seed=11)
    eng = DecodeEngine(_CFG, params, _SC,
                       slo=ServeSLO(ttft_p99_ms=1e9))
    assert "serve_slo_ok" not in eng.serve_record()   # nothing served
    eng.submit([1, 2], 3)
    eng.run()
    assert eng.serve_record()["serve_slo_ok"] is True  # now grounded


def test_measure_decode_warm_param_reaches_live_estimator():
    """measure_decode(warm=N) must apply the SAME warmup exclusion to
    the live step-time estimator it feeds — the two views of the one
    convention cannot disagree."""
    params = _params(seed=11)
    eng5 = DecodeEngine(_CFG, params, _SC)
    eng5.submit([1, 2], 8)
    m5 = measure_decode(eng5, warm=5)
    pct5 = step_latency_percentiles(m5["per_step_s"], m5["churn"],
                                    warm=5)
    assert eng5.telemetry.step_lat.n == pct5["pure_decode_steps"]


# ------------------------------------------------------------------
# the standing CI gates (scripts/slo_probe.py)
# ------------------------------------------------------------------


def test_slo_probe_selftest():
    """Tier-1 fixture-drift gate (mirrors resume_probe --selftest):
    the committed telemetry report still validates, the estimator
    reproduces the oracle, and the SEEDED BREACH negative control is
    flagged with the `ttft` axis named."""
    r = _run_script(ROOT / "scripts" / "slo_probe.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "slo_probe --selftest: OK" in r.stdout
    # the negative control is asserted BY NAME: the fixture seeds a
    # ttft-p99 breach and the verdict must name that axis
    with open(ROOT / "scripts" / "slo_fixture.json") as f:
        fixture = json.load(f)
    br = fixture["seeded_breach"]
    assert br["expect_axis"] == "ttft"
    verdict = ServeSLO(**br["slo"]).evaluate_summary(br["summary"])
    assert not verdict.ok
    assert "ttft" in [b.axis for b in verdict.breaches]


def test_slo_probe_full_gate():
    """The standing serving-observatory gate (ISSUE 10 acceptance):
    churn workload on the flagship build path — ledger reconciles
    exactly with step() accounting, estimators match the oracle, SLO
    green, zero steady-state recompiles, decode bitwise with
    telemetry off."""
    r = _run_script(ROOT / "scripts" / "slo_probe.py",
                    "--requests", "12", "--max-new", "4", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    payload = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    assert payload is not None, r.stdout
    assert payload["ok"] is True
    assert payload["ledger_reconciles"] is True
    assert payload["bitwise_telemetry_off"] is True
    assert payload["recompile_ok"] is True
    assert payload["slo_ok"] is True
