"""Pipeline schedule tests ≡ tests/L0/run_transformer/
test_pipeline_parallel_fwd_bwd.py and test_microbatches.py: the SPMD
pipeline produces the same outputs/grads as sequential layer
application, for both plain and interleaved schedules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
    spmd_pipeline,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    split_into_microbatches,
)

PP = 4
D = 8


def _mesh(pp=PP):
    M.destroy_model_parallel()
    return M.initialize_model_parallel(pipeline_model_parallel_size=pp)


def _stage_fn(params, x, chunk):
    # one "layer": x @ w + tanh residual — shape-preserving
    return x + jnp.tanh(x @ params["w"] + params["b"])


def _make_params(key, n_layers):
    ks = jax.random.split(key, n_layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (D, D)) * 0.3 for k in ks]),
        "b": jnp.zeros((n_layers, D)),
    }


def _sequential(params, x, n_layers):
    for i in range(n_layers):
        x = _stage_fn({"w": params["w"][i], "b": params["b"][i]}, x, 0)
    return x


@pytest.mark.parametrize("m", [4, 8])
def test_pipeline_matches_sequential(m):
    """pp=4, one layer per stage: pipeline out == sequential out."""
    mesh = _mesh()
    params = _make_params(jax.random.PRNGKey(0), PP)
    mbs = jax.random.normal(jax.random.PRNGKey(1), (m, 2, D))

    # stage s holds layer s: the sharded leading dim (local size 1) IS
    # the chunk dim for num_model_chunks=1
    def local(params, mbs):
        return spmd_pipeline(_stage_fn, params, mbs, num_model_chunks=1)

    f = shard_map(local, mesh=mesh,
                  in_specs=({"w": P("pp"), "b": P("pp")}, P()),
                  out_specs=P(), check_vma=False)
    got = f(params, mbs)
    want = jax.vmap(lambda x: _sequential(params, x, PP))(mbs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential():
    mesh = _mesh()
    params = _make_params(jax.random.PRNGKey(2), PP)
    mbs = jax.random.normal(jax.random.PRNGKey(3), (4, 2, D))

    def local_grad(params, mbs):
        def loss(p):
            out = spmd_pipeline(_stage_fn, p, mbs, num_model_chunks=1)
            return jnp.mean(out ** 2)
        return jax.grad(loss)(params)

    g = shard_map(local_grad, mesh=mesh,
                  in_specs=({"w": P("pp"), "b": P("pp")}, P()),
                  out_specs={"w": P("pp"), "b": P("pp")},
                  check_vma=False)(params, mbs)

    def ref_loss(p):
        out = jax.vmap(lambda x: _sequential(p, x, PP))(mbs)
        return jnp.mean(out ** 2)

    r = jax.grad(ref_loss)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g, r)


def test_interleaved_pipeline_matches_sequential():
    """pp=4 × 2 chunks = 8 global stages ≡ interleaved schedule."""
    mesh = _mesh()
    n_layers = PP * 2
    params = _make_params(jax.random.PRNGKey(4), n_layers)
    mbs = jax.random.normal(jax.random.PRNGKey(5), (4, 2, D))

    # device s holds layers s (chunk 0) and pp+s (chunk 1): stacked
    # leaves (pp, chunks, ...) — reshape global (2*pp, ...) accordingly
    def reorder(l):
        # global layer index g = c*pp + s → device s, chunk c
        return l.reshape(2, PP, *l.shape[1:]).swapaxes(0, 1)

    dev_params = jax.tree_util.tree_map(reorder, params)

    def local(params, mbs):
        # local leaf (1, chunks, ...): drop the sharded stage dim
        p = jax.tree_util.tree_map(lambda l: l[0], params)
        return spmd_pipeline(_stage_fn, p, mbs, num_model_chunks=2)

    f = shard_map(local, mesh=mesh,
                  in_specs=({"w": P("pp"), "b": P("pp")}, P()),
                  out_specs=P(), check_vma=False)
    got = f(dev_params, mbs)
    want = jax.vmap(lambda x: _sequential(params, x, n_layers))(mbs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_no_pipelining_schedule():
    params = {"w": jax.random.normal(jax.random.PRNGKey(6), (D, 1)) * 0.1}
    batch = jax.random.normal(jax.random.PRNGKey(7), (6, 2, D))

    def fwd(p, mb):
        return jnp.mean((mb @ p["w"]) ** 2)

    loss, grads = forward_backward_no_pipelining(
        fwd, batch, params, num_microbatches=6)
    want_loss = jnp.mean(jnp.stack([fwd(params, batch[i])
                                    for i in range(6)]))
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-6)
    r = jax.grad(lambda p: jnp.mean(jnp.stack(
        [fwd(p, batch[i]) for i in range(6)])))(params)
    np.testing.assert_allclose(np.asarray(grads["w"]), np.asarray(r["w"]),
                               rtol=1e-5)


def test_microbatch_calculators():
    """≡ test_microbatches.py + test_dynamic_batchsize.py."""
    c = ConstantNumMicroBatches(64, 4, 2)
    assert c.get() == 8
    assert c.get_current_global_batch_size() == 64

    r = RampupBatchsizeNumMicroBatches(
        start_batch_size=16, batch_size_increment=16, ramup_samples=48,
        global_batch_size=64, micro_batch_size=4, data_parallel_size=2)
    assert r.get_current_global_batch_size() == 16
    r.update(16, True)
    assert r.get_current_global_batch_size() == 32
    r.update(48, True)
    assert r.get_current_global_batch_size() == 64
    assert r.get() == 8
    with pytest.raises(AssertionError):
        ConstantNumMicroBatches(63, 4, 2)


def test_split_into_microbatches():
    batch = {"x": jnp.arange(24.0).reshape(12, 2)}
    mbs = split_into_microbatches(batch, 4)
    assert mbs["x"].shape == (4, 3, 2)
    np.testing.assert_allclose(np.asarray(mbs["x"][1][0]),
                               np.asarray(batch["x"][3]))


# ------------- 1F1B activation memory (round 4: VERDICT missing #3) ---------

def _loss_pipeline(params, mbs, labels, window):
    total = spmd_pipeline(
        _stage_fn, params, mbs, num_model_chunks=1,
        checkpoint_window=window,
        loss_fn=lambda y, lbl: jnp.sum((y - lbl) ** 2), loss_args=labels)
    return total / mbs.shape[0]


@pytest.mark.parametrize("window", [2, PP, 5])
def test_pipeline_checkpoint_window_grads_match(window):
    """Windowed-remat pipeline (incl. a window that does NOT divide the
    clock count) is bit-compatible with the plain scan: same loss, same
    grads."""
    mesh = _mesh()
    params = _make_params(jax.random.PRNGKey(0), PP)
    m = 8
    mbs = jax.random.normal(jax.random.PRNGKey(1), (m, 2, D))
    labels = jax.random.normal(jax.random.PRNGKey(2), (m, 2, D))

    def run(window):
        def local(params, mbs, labels):
            return jax.value_and_grad(
                lambda p: _loss_pipeline(p, mbs, labels, window))(params)
        f = jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=({"w": P("pp"), "b": P("pp")}, P(), P()),
            out_specs=(P(), {"w": P("pp"), "b": P("pp")}),
            check_vma=False))
        return f(params, mbs, labels)

    l_ref, g_ref = run(None)
    l_win, g_win = run(window)
    np.testing.assert_allclose(float(l_win), float(l_ref), rtol=1e-6)
    for kk in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_win[kk]),
                                   np.asarray(g_ref[kk]),
                                   rtol=1e-5, atol=1e-6, err_msg=kk)


def _pipeline_temp_bytes(m, window, hidden=2048, tokens=256):
    """Compiled temp size of a pipeline train step at a 1.3B-class stage
    width (h=2048, 4h FFN — one GPT2-1.3B block per stage)."""
    mesh = _mesh()
    ffn = 4 * hidden
    params = {
        "w1": jnp.zeros((PP, hidden, ffn)),
        "w2": jnp.zeros((PP, ffn, hidden)),
    }

    def stage(p, x, chunk):
        return x + jnp.tanh(x @ p["w1"]) @ p["w2"]

    mbs = jnp.zeros((m, tokens, hidden))
    labels = jnp.zeros((m, tokens, hidden))

    def local(params, mbs, labels):
        def loss(p):
            total = spmd_pipeline(
                stage, p, mbs, num_model_chunks=1,
                checkpoint_window=window,
                loss_fn=lambda y, lbl: jnp.sum((y - lbl) ** 2),
                loss_args=labels)
            return total / m
        return jax.grad(loss)(params)

    spec = {"w1": P("pp"), "w2": P("pp")}
    f = shard_map(local, mesh=mesh, in_specs=(spec, P(), P()),
                  out_specs=spec, check_vma=False)
    stats = jax.jit(f).lower(params, mbs, labels).compile() \
        .memory_analysis()
    M.destroy_model_parallel()
    return stats.temp_size_in_bytes


def test_pipeline_checkpoint_window_memory_bound():
    """checkpoint_window=pp gives 1F1B-shaped activation memory:
    doubling num_microbatches must NOT double peak temp (the plain scan
    — GPipe-shaped — roughly does), and the windowed peak at m=16 must
    sit well below the plain scan's."""
    plain16 = _pipeline_temp_bytes(16, None)
    win8 = _pipeline_temp_bytes(8, PP)
    win16 = _pipeline_temp_bytes(16, PP)
    # windowed growth with m: boundary carries only (m/pp extra acts)
    assert win16 / win8 < 1.6, (win8, win16)
    # windowed vs GPipe at the same m: O(pp + m/pp) vs O(m + pp - 1)
    assert win16 < 0.67 * plain16, (win16, plain16)
