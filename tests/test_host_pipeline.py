"""Host-driven (MPMD) pipeline driver ≡ the reference's
forward_backward_pipelining_without_interleaving running per-stage
programs from the host (SURVEY §7's second pipeline design — the
multi-slice/DCN one).  Parity vs single-program autodiff."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.pipeline_parallel.host_driver import (
    HostPipelineStage,
    host_pipeline_train_step,
)


def _mk_stage_fns(n_stage, h=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2 * n_stage)
    params = []
    fns = []
    for i in range(n_stage):
        p = {"w": jax.random.normal(ks[2 * i], (h, h)) * 0.3,
             "b": jax.random.normal(ks[2 * i + 1], (h,)) * 0.1}
        params.append(p)
        if i < n_stage - 1:
            def f(p, x):
                return jnp.tanh(x @ p["w"] + p["b"])
            fns.append(f)
        else:
            def f(p, x):
                y = jnp.tanh(x @ p["w"] + p["b"])
                return jnp.mean(y ** 2)
            fns.append(f)
    return fns, params


def _reference_grads(fns, params, microbatches):
    """Single-program oracle: mean loss over microbatches, grads by
    plain jax.grad through the composed stages."""
    def total_loss(params_list):
        losses = []
        for x in microbatches:
            h = x
            for i in range(len(fns) - 1):
                h = fns[i](params_list[i], h)
            losses.append(fns[-1](params_list[-1], h))
        return sum(losses) / len(losses)

    loss, grads = jax.value_and_grad(total_loss)(list(params))
    return float(loss), grads


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("n_stage,n_mb", [(2, 4), (4, 8), (4, 3)])
def test_host_pipeline_matches_single_program(n_stage, n_mb, schedule):
    """Loss + per-stage grads ≡ jax.grad through the composed model —
    including n_mb < n_stage (degenerate warmup) and both schedules."""
    fns, params = _mk_stage_fns(n_stage)
    devs = jax.devices()[:n_stage]
    stages = [HostPipelineStage(fns[i], device=devs[i])
              for i in range(n_stage)]
    mbs = [jax.random.normal(jax.random.PRNGKey(100 + m), (4, 16))
           for m in range(n_mb)]

    loss, grads = host_pipeline_train_step(stages, params, mbs,
                                           schedule=schedule)
    ref_loss, ref_grads = _reference_grads(fns, params, mbs)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-6, atol=1e-7)
    for i in range(n_stage):
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            grads[i], ref_grads[i])


def test_host_pipeline_stage_devices():
    """Per-stage grads live on their stage's device (stage-local
    optimizer contract) and activations really crossed devices."""
    fns, params = _mk_stage_fns(3)
    devs = jax.devices()[:3]
    stages = [HostPipelineStage(fns[i], device=devs[i]) for i in range(3)]
    mbs = [jax.random.normal(jax.random.PRNGKey(7), (4, 16))]
    _, grads = host_pipeline_train_step(stages, params, mbs)
    for i in range(3):
        leaf = jax.tree_util.tree_leaves(grads[i])[0]
        assert leaf.devices() == {devs[i]}, (i, leaf.devices())


def test_host_pipeline_in_flight_bound():
    """1F1B keeps <= warmup+1 saved inputs per stage, independent of
    the microbatch count (the activation-memory bound the schedule
    exists for); gpipe holds all n_mb."""
    fns, params = _mk_stage_fns(4)
    stages = [HostPipelineStage(fns[i]) for i in range(4)]
    mbs = [jax.random.normal(jax.random.PRNGKey(m), (2, 16))
           for m in range(12)]
    loss, _, stats = host_pipeline_train_step(stages, params, mbs,
                                              schedule="1f1b",
                                              return_stats=True)
    assert np.isfinite(loss)
    n = len(stages)
    # true 1F1B bound: stage i holds at most n_stage - i saved inputs
    # (the LAST stage never holds more than 1)
    for i, peak in enumerate(stats["peak_in_flight_per_stage"]):
        assert peak <= n - i, (i, stats)
    assert stats["peak_in_flight_per_stage"][-1] == 1, stats

    _, _, stats_g = host_pipeline_train_step(stages, params, mbs,
                                             schedule="gpipe",
                                             return_stats=True)
    assert stats_g["peak_in_flight"] == len(mbs), stats_g


def test_host_pipeline_rejects_empty():
    """Zero microbatches/stages raise a clear ValueError rather than a
    ZeroDivisionError in loss averaging (review r5 note)."""
    fns, params = _mk_stage_fns(2)
    stages = [HostPipelineStage(fns[i]) for i in range(2)]
    with pytest.raises(ValueError, match="microbatch"):
        host_pipeline_train_step(stages, params, [])
    with pytest.raises(ValueError, match="stage"):
        host_pipeline_train_step([], [], [jnp.ones((2, 16))])
    with pytest.raises(ValueError, match="params_list"):
        host_pipeline_train_step(stages, params[:1],
                                 [jnp.ones((2, 16))])
