"""The README/docs-index quickstart must actually run (round 5: the
index.md snippet had drifted to a stale init_sharded_optimizer/step
signature).  This mirrors the documented flow line for line at toy
size — if a public signature changes, this fails before the docs rot."""

import jax
import jax.numpy as jnp
import numpy as np


def test_docs_index_quickstart_flow():
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import initialize_model_parallel
    from apex_tpu.parallel.mesh import destroy_model_parallel
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    destroy_model_parallel()
    mesh = initialize_model_parallel(tensor_model_parallel_size=1)
    cfg = GPTConfig(vocab_size=512, seq_len=32, hidden=64,
                    num_layers=2, num_heads=4, dtype=jnp.bfloat16)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=3e-4)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    opt_state, loss = step(opt_state, tokens, labels)
    assert np.isfinite(float(loss))
    destroy_model_parallel()


def test_migration_per_leaf_groups_flow():
    """The MIGRATION.md per-group recipe: wd_mask from the standard
    no-decay helper feeds FusedAdam and trains."""
    from apex_tpu.models.bert import Bert, BertConfig
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import initialize_model_parallel
    from apex_tpu.parallel.mesh import destroy_model_parallel
    from apex_tpu.transformer.pipeline_parallel.common import (
        get_params_for_weight_decay_optimization,
    )
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    destroy_model_parallel()
    mesh = initialize_model_parallel(tensor_model_parallel_size=1)
    cfg = BertConfig(seq_len=32, hidden=64, num_layers=2, num_heads=4,
                     dtype=jnp.bfloat16)
    model = Bert(cfg)
    params = model.init(jax.random.PRNGKey(0))
    wd_mask = get_params_for_weight_decay_optimization(params)
    opt = FusedAdam(lr=3e-4, weight_decay=0.01, wd_mask=wd_mask)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size)
    mlm = jnp.roll(tokens, -1, axis=1)
    lm = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15, (8, 32))

    def loss_fn(p, t, l):
        return model.loss(p, t, l, lm)

    step = make_tp_dp_train_step(model, opt, mesh, loss_fn=loss_fn)
    opt_state, loss = step(opt_state, tokens, mlm)
    assert np.isfinite(float(loss))
    destroy_model_parallel()
