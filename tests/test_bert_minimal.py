"""BERT minimal ≡ tests/L0/run_transformer/test_bert_minimal.py: TP loss
consistency, pad-mask behavior, and MLM+NSP convergence with FusedLAMB
(the BERT+LAMB baseline config, BASELINE.md)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.bert import Bert, BertConfig
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.parallel import mesh as M

VOCAB, SEQ, HID, LAYERS, HEADS = 64, 16, 32, 2, 4


def _cfg():
    return BertConfig(vocab_size=VOCAB, seq_len=SEQ, hidden=HID,
                      num_layers=LAYERS, num_heads=HEADS)


def _data(batch=4):
    k = jax.random.PRNGKey(0)
    tokens = jax.random.randint(k, (batch, SEQ), 0, VOCAB)
    mlm_labels = jax.random.randint(jax.random.PRNGKey(1), (batch, SEQ), 0,
                                    VOCAB)
    loss_mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15,
                                     (batch, SEQ))
    nsp = jax.random.randint(jax.random.PRNGKey(3), (batch,), 0, 2)
    return tokens, mlm_labels, loss_mask, nsp


def _loss(tp):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=tp)
    model = Bert(_cfg())
    params = model.init(jax.random.PRNGKey(4))
    tokens, mlm, mask, nsp = _data()
    f = shard_map(
        lambda p, t, l, lm, n: model.loss(p, t, l, lm, n),
        mesh=mesh, in_specs=(model.partition_specs(), P(), P(), P(), P()),
        out_specs=P(), check_vma=False)
    out = float(f(params, tokens, mlm, mask, nsp))
    M.destroy_model_parallel()
    return out


def test_bert_loss_consistent_across_tp():
    l2 = _loss(2)
    l4 = _loss(4)
    np.testing.assert_allclose(l2, l4, rtol=2e-3)
    # MLM ≈ log(V), NSP ≈ log(2)
    assert abs(l2 - (np.log(VOCAB) + np.log(2))) < 1.0


def test_bert_pad_mask():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
    model = Bert(_cfg())
    params = model.init(jax.random.PRNGKey(5))
    tokens, _, _, _ = _data(2)
    pad = jnp.zeros((2, SEQ), bool).at[:, SEQ // 2:].set(True)

    def enc(p, t, pm):
        return model.encode(p, t, pad_mask=pm)

    f = shard_map(enc, mesh=mesh,
                  in_specs=(model.partition_specs(), P(), P()),
                  out_specs=P(), check_vma=False)
    h = f(params, tokens, pad)
    # changing padded tokens must not change unpadded positions' output
    tokens2 = tokens.at[:, SEQ // 2:].set(0)
    h2 = f(params, tokens2, pad)
    np.testing.assert_allclose(np.asarray(h[: SEQ // 2]),
                               np.asarray(h2[: SEQ // 2]),
                               rtol=1e-4, atol=1e-5)


def test_bert_trains_with_lamb():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
    model = Bert(_cfg())
    params = model.init(jax.random.PRNGKey(6))
    tokens, mlm, mask, nsp = _data(8)
    opt = FusedLAMB(lr=2e-2, weight_decay=0.01, use_pallas=False)

    from apex_tpu.transformer.training import (
        init_sharded_optimizer, make_tp_dp_train_step)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(
        model, opt, mesh, donate=False,
        loss_fn=lambda p, t, l: model.loss(p, t, l[0], l[1], l[2]))
    losses = []
    for _ in range(12):
        opt_state, loss = step(opt_state, tokens, (mlm, mask, nsp))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9


def test_bert_flash_vs_dense_attention_parity():
    """Padding-masked flash BERT ≡ dense FusedScaleMaskSoftmax BERT on
    non-pad positions (VERDICT r1 missing #2 / weak #3)."""
    import dataclasses
    from apex_tpu.models.bert import Bert, BertConfig
    cfg = BertConfig(vocab_size=128, seq_len=64, hidden=64, num_layers=2,
                     num_heads=4)
    dense = Bert(cfg)
    flash = Bert(dataclasses.replace(cfg, use_flash_attention=True))
    params = dense.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 128)
    pad = jnp.zeros((2, 64), bool).at[:, 48:].set(True)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=1)
    outs = []
    for model in (dense, flash):
        f = shard_map(
            lambda p, t, pm, m=model: m.encode(p, t, pad_mask=pm),
            mesh=mesh, in_specs=(model.partition_specs(), P(), P()),
            out_specs=P(), check_vma=False)
        outs.append(f(params, tokens, pad))
    M.destroy_model_parallel()
    np.testing.assert_allclose(np.asarray(outs[0][:48]),
                               np.asarray(outs[1][:48]),
                               rtol=2e-4, atol=2e-4)
