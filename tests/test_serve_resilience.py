"""Serving resilience (ISSUE 14): per-request deadlines/TTL,
cancellation in-queue and mid-generation, bounded-queue overload
shedding (policy ordering + SLO-driven proactive shed), the ledger's
terminal states and exact balance identity, the PagedKVCache
double-release guard, the EngineWatchdog stall-trip/restart contract,
graceful drain, the SCHEMA v10 stamps, and the
`scripts/serve_chaos_probe.py` CI gates."""

import os
import pathlib
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from apex_tpu import monitor
from apex_tpu.checkpoint import chaos
from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.serve import (
    DecodeEngine,
    EngineStalledError,
    EngineWatchdog,
    PageAccountingError,
    PagedKVCache,
    KVCacheConfig,
    PoisonedOutputError,
    RequestLedger,
    ServeConfig,
    ServeSLO,
    choose_shed_victim,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent

_CFG = GPTConfig(vocab_size=64, seq_len=64, hidden=32, num_layers=2,
                 num_heads=4, dropout=0.0)
_SC = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                  page_size=4)

_PROMPTS = [[5, 9, 2, 17], [33, 1], [40, 41, 42], [8, 9], [11, 12, 13],
            [21, 22], [7, 7, 7]]
_BUDGETS = [6, 8, 5, 4, 7, 3, 5]


@pytest.fixture(scope="module")
def params():
    p = GPT(_CFG).init(jax.random.PRNGKey(7))
    p["pos_embed"] = p["pos_embed"] * 20.0  # varied decode trajectories
    return p


@pytest.fixture(scope="module")
def ref_tokens(params):
    """The unloaded baseline every surviving request must match
    BITWISE (faults may kill requests, never change survivors)."""
    eng = DecodeEngine(_CFG, params, _SC)
    for p, b in zip(_PROMPTS, _BUDGETS):
        eng.submit(p, b)
    return {f.request_id: f.tokens for f in eng.run()}


@pytest.fixture(autouse=True)
def _disarm():
    chaos.disarm_all()
    yield
    chaos.disarm_all()


def _drive(eng, max_steps=400, sleep_when_stalled=0.0):
    fins = {}
    steps = 0
    while eng.pending:
        assert steps < max_steps, "drive loop exceeded bound"
        eng.step()
        for f in eng.poll():
            fins[f.request_id] = f
        if sleep_when_stalled and eng.stalled:
            time.sleep(sleep_when_stalled)
        steps += 1
    eng._retire_finished()
    for f in eng.poll():
        fins[f.request_id] = f
    return fins


def _assert_clean(eng, fins, ref):
    """Every leg's shared invariants: ok-survivors bitwise, pool fully
    reconciled, ledger balance identity closed."""
    for rid, f in fins.items():
        if f.status == "ok":
            assert f.tokens == ref[rid], f"request {rid} drifted"
    assert eng.cache.free_pages == eng.kv_config.usable_pages
    if eng.telemetry is not None:
        assert eng.telemetry.ledger.balance()["ok"], \
            eng.telemetry.ledger.balance()


# ------------------------------------------------------------------
# deadlines / TTL
# ------------------------------------------------------------------


def test_deadline_expires_in_queue(params, ref_tokens):
    """A queued request whose TTL passes is evicted at the admit sweep
    (terminal `expired`, where='queue', no pages ever reserved) and
    the survivors decode bitwise."""
    eng = DecodeEngine(_CFG, params, _SC)
    rids = [eng.submit(p, b) for p, b in zip(_PROMPTS[:3], _BUDGETS[:3])]
    doomed = eng.submit([1, 2, 3], 4, deadline_ms=0.001)
    time.sleep(0.005)
    fins = _drive(eng)
    assert fins[doomed].status == "expired"
    assert fins[doomed].tokens == []
    led = eng.telemetry.ledger
    assert led.n_expired_queue == 1 and led.n_expired_live == 0
    rec = {r.request_id: r for r in led.tail}[doomed]
    assert rec.status == "expired" and rec.where == "queue"
    assert rec.admit_t is None                 # never admitted
    assert rec.deadline_ms == 0.001
    # expiry never fed the latency estimators
    assert led.ttft.n == 3 and led.queue_wait.n == 3
    for rid in rids:
        assert fins[rid].status == "ok"
    _assert_clean(eng, fins, ref_tokens)


def test_deadline_evicts_live_slot(params, ref_tokens):
    """A LIVE request past its deadline is evicted at the retire poll:
    pages released mid-generation, partial tokens noted, terminal
    `expired` where='live' — and no other stream is disturbed."""
    eng = DecodeEngine(_CFG, params, _SC)
    doomed = eng.submit(_PROMPTS[0], _BUDGETS[0], deadline_ms=25.0)
    other = eng.submit(_PROMPTS[1], _BUDGETS[1])
    eng.step()                                  # both admitted, decoding
    assert any(r.rid == doomed for r in eng._live.values())
    pages_live = eng.cache.free_pages
    time.sleep(0.05)                            # deadline passes mid-gen
    fins = _drive(eng)
    assert fins[doomed].status == "expired"
    led = eng.telemetry.ledger
    assert led.n_expired_live == 1
    rec = {r.request_id: r for r in led.tail}[doomed]
    assert rec.where == "live" and rec.admit_t is not None
    assert fins[other].status == "ok"
    assert fins[other].tokens == ref_tokens[other]
    assert eng.cache.free_pages > pages_live    # pages came back
    _assert_clean(eng, fins, ref_tokens)


def test_submit_validates_deadline(params):
    eng = DecodeEngine(_CFG, params, _SC)
    with pytest.raises(ValueError, match="deadline_ms"):
        eng.submit([1, 2], 4, deadline_ms=0.0)
    with pytest.raises(ValueError, match="deadline_ms"):
        eng.submit([1, 2], 4, deadline_ms=-5.0)


# ------------------------------------------------------------------
# cancellation
# ------------------------------------------------------------------


def test_cancel_in_queue_and_mid_generation(params, ref_tokens):
    """cancel() removes a queued request outright and ends a live one
    through the done mask (next retire poll, partial tokens, pages
    released); unknown/terminal ids return False; survivors bitwise;
    zero steady recompiles (the done-mask edit is a VALUE edit)."""
    eng = DecodeEngine(_CFG, params, _SC)
    rids = [eng.submit(p, b) for p, b in zip(_PROMPTS, _BUDGETS)]
    assert eng.cancel(rids[4])                  # still queued
    eng.step()
    live_rid = next(iter(eng._live.values())).rid
    assert eng.cancel(live_rid)                 # mid-generation
    assert not eng.cancel(live_rid)             # double-cancel: no-op
    assert not eng.cancel(10_000)               # unknown id
    fins = _drive(eng)
    assert fins[rids[4]].status == "cancelled"
    assert fins[rids[4]].tokens == []
    assert fins[live_rid].status == "cancelled"
    led = eng.telemetry.ledger
    assert led.n_cancelled_queue == 1 and led.n_cancelled_live == 1
    # a cancelled live request keeps its partial generation (info only)
    rec = {r.request_id: r for r in led.tail}[live_rid]
    assert rec.status == "cancelled" and rec.where == "live"
    assert eng.recompile_ok
    _assert_clean(eng, fins, ref_tokens)


# ------------------------------------------------------------------
# overload control
# ------------------------------------------------------------------


def test_bounded_queue_sheds_newest(params, ref_tokens):
    """shed-newest at capacity: the incoming request is the victim,
    `last_shed_rid` surfaces the signal through submit(), the
    saturation gauge reads 1.0, and the ledger counts every shed."""
    sc = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                     page_size=4, max_queue_depth=2)
    eng = DecodeEngine(_CFG, params, sc)
    kept = [eng.submit(_PROMPTS[0], _BUDGETS[0])]
    assert eng.last_shed_rid is None and not eng.overloaded
    kept.append(eng.submit(_PROMPTS[1], _BUDGETS[1]))
    assert eng.last_shed_rid is None
    assert eng.gauges()["queue_saturation"] == 1.0
    assert eng.overloaded
    shed = eng.submit(_PROMPTS[2], _BUDGETS[2])
    assert eng.last_shed_rid == shed
    fins = {f.request_id: f for f in eng.poll()}
    assert fins[shed].status == "shed" and fins[shed].tokens == []
    assert eng.telemetry.ledger.n_shed == 1
    fins.update(_drive(eng))
    for rid in kept:
        assert fins[rid].status == "ok"
    _assert_clean(eng, fins, ref_tokens)


def test_shed_lowest_deadline_policy_ordering(params):
    """shed-lowest-deadline sheds the earliest-deadline candidate
    (least slack = least feasible work wasted); deadline-less requests
    go last.  Checked through the engine AND the pure spelling the
    chaos probe's selftest replays."""

    class _C:
        def __init__(self, rid, deadline_t):
            self.rid, self.deadline_t = rid, deadline_t

    cands = [_C(0, 9.0), _C(1, 2.5), _C(2, None), _C(3, 7.0)]
    assert choose_shed_victim(cands, "shed-lowest-deadline").rid == 1
    assert choose_shed_victim(cands, "shed-newest").rid == 3
    assert choose_shed_victim([_C(0, None), _C(1, None)],
                              "shed-lowest-deadline").rid == 1  # FIFO tilt
    with pytest.raises(ValueError, match="shed policy"):
        choose_shed_victim(cands, "shed-oldest")

    sc = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                     page_size=4, max_queue_depth=3,
                     shed_policy="shed-lowest-deadline")
    eng = DecodeEngine(_CFG, params, sc)
    r_far = eng.submit([1, 2], 4, deadline_ms=90_000.0)
    r_soon = eng.submit([3, 4], 4, deadline_ms=10_000.0)
    r_none = eng.submit([5, 6], 4)
    r_in = eng.submit([7, 8], 4, deadline_ms=50_000.0)  # queue full now
    # victim = r_soon (earliest deadline), NOT the incoming request
    assert eng.last_shed_rid == r_soon
    statuses = {f.request_id: f.status for f in eng.poll()}
    assert statuses == {r_soon: "shed"}
    assert {r.rid for r in eng._pending} == {r_far, r_none, r_in}


def test_slo_projection_sheds_before_breach(params):
    """With ServeSLO(max_queue_wait_ms=) attached, the engine sheds
    when the PROJECTED wait (depth x mean service / slots) would
    breach — before the queue-wait plane does.  Seeded service
    samples make the projection deterministic."""
    eng = DecodeEngine(_CFG, params, _SC)
    eng.slo = ServeSLO(max_queue_wait_ms=100.0)
    # no service data yet: the projection never guesses
    assert eng.projected_queue_wait_s() is None
    r0 = eng.submit([1, 2], 4)
    assert eng.last_shed_rid is None
    # seed the service estimator: 0.2 s per request, 3 slots → each
    # queued request projects 0.2/3 s ≈ 66.7 ms of added wait
    for _ in range(4):
        eng.telemetry.ledger.service.add(0.2)
    r1 = eng.submit([3, 4], 4)        # depth 1 → proj 66.7ms < 100ms
    assert eng.last_shed_rid is None
    r2 = eng.submit([5, 6], 4)        # depth 2 → proj 133ms > 100ms: shed
    assert eng.last_shed_rid == r2
    assert eng.telemetry.ledger.n_shed == 1
    assert eng.overloaded             # the standing backpressure signal


def test_overload_storm_4x_mixed_deadlines(params, ref_tokens):
    """The satellite churn test: 4x slot capacity, bounded queue,
    mixed deadlines — shed-policy ordering holds, zero page leaks
    after the storm, and every surviving output is bitwise equal to
    the uncontended run."""
    sc = ServeConfig(n_slots=3, max_prompt_len=8, max_new_cap=8,
                     page_size=4, max_queue_depth=4,
                     shed_policy="shed-lowest-deadline")
    eng = DecodeEngine(_CFG, params, sc)
    # the full 7-request workload (vs 3 slots, pool-capped at 2 live)
    # + 5 filler requests = 4x capacity, half with finite deadlines
    rids, deadline_rids = [], []
    for i, (p, b) in enumerate(zip(_PROMPTS, _BUDGETS)):
        dl = 120_000.0 if i % 2 else None
        rids.append(eng.submit(p, b, deadline_ms=dl))
        if dl is not None:
            deadline_rids.append(rids[-1])
    extra = [eng.submit([9, 9 + i], 3, deadline_ms=120_000.0)
             for i in range(5)]
    led = eng.telemetry.ledger
    assert led.n_shed > 0, "4x storm shed nothing"
    # policy ordering: with every queued deadline equal, victims are
    # the NEWEST deadline-carrying candidates; deadline-less queued
    # requests survive shedding entirely
    shed = {f.request_id for f in eng.poll() if f.status == "shed"}
    assert shed and shed <= set(deadline_rids) | set(extra)
    fins = _drive(eng)
    for f in fins.values():
        assert f.status in ("ok", "shed")
    _assert_clean(eng, fins, ref_tokens)
    assert eng.recompile_ok
    bal = led.balance()
    assert bal["ok"] and bal["n_shed"] == len(shed)


# ------------------------------------------------------------------
# ledger terminal states: exact reconciliation (satellite)
# ------------------------------------------------------------------


def test_terminal_states_reconcile_against_step_sums(params):
    """Lifetime counters balance EXACTLY against step()'s (admitted,
    retired) sums plus the terminal counts: every vacated slot is a
    normal retire, a live expiry, or a live cancel — and every
    submission is admitted, queue-terminal, or still open."""
    eng = DecodeEngine(_CFG, params, _SC)
    rids = [eng.submit(p, b, deadline_ms=(30.0 if i == 5 else None))
            for i, (p, b) in enumerate(zip(_PROMPTS, _BUDGETS))]
    eng.cancel(rids[6])                        # queue-side cancel
    hand_admitted = hand_retired = 0
    a, r = eng.step()
    hand_admitted += a
    hand_retired += r
    eng.cancel(next(iter(eng._live.values())).rid)   # live cancel
    time.sleep(0.05)                           # rid 5's deadline passes
    steps = 0
    while eng.pending:
        a, r = eng.step()
        hand_admitted += a
        hand_retired += r
        eng.poll()
        steps += 1
        assert steps < 400
    hand_retired += eng._retire_finished()
    led = eng.telemetry.ledger
    # slot exits == step() retire sums (normal + live-cancel + expiry)
    assert (led.n_retired + led.n_cancelled_live + led.n_expired_live
            == hand_retired)
    # admissions == step() admit sums
    assert led.n_admitted == hand_admitted
    # the submission identity
    assert (led.n_submitted == led.n_retired + led.n_expired
            + led.n_cancelled + led.n_shed + led.n_open)
    assert led.n_open == 0
    assert led.balance()["ok"]


def test_restored_requests_keep_original_submit_stamps(params):
    """ISSUE 14 satellite: the snapshot preserves submit AGE, so a
    restored request's ledger record keeps its original submit stamp
    (queue wait spans the preemption) and a live deadline keeps
    counting down instead of resetting."""
    eng = DecodeEngine(_CFG, params, _SC)
    for i in range(5):
        eng.submit([i + 1, i + 2], 6,
                   deadline_ms=(90_000.0 if i == 4 else None))
    eng.step()
    time.sleep(0.02)
    snap = eng.state_dict()
    ages = {e[0]: e[3] for e in snap["scheduler"]["pending"]}
    assert all(a >= 0.02 for a in ages.values())      # real ages
    eng2 = DecodeEngine(_CFG, params, _SC)
    t_restore = time.perf_counter()
    eng2.load_state_dict(snap)
    led2 = eng2.telemetry.ledger
    for req in eng2._pending:
        rec = led2._open[req.rid]
        # original stamp: age-adjusted to BEFORE the restore moment
        # (a fresh re-stamp would land after t_restore)
        assert rec.submit_t < t_restore
        if req.deadline_ms is not None:
            # remaining deadline re-absolutized, not reset: strictly
            # less than a fresh 90 s TTL from the restore point
            assert req.deadline_t < t_restore + 90.0
    fins = _drive(eng2)
    assert all(f.status == "ok" for f in fins.values())
    # the restored queued cohort's queue waits INCLUDE pre-snapshot
    # time (>= the sleep), proving the stamps survived
    waits = [r.queue_wait_s for r in led2.tail
             if not r.restored and r.queue_wait_s]
    assert waits and min(waits) >= 0.015
    assert led2.balance()["ok"]


def test_v1_snapshot_refused_by_version(params):
    eng = DecodeEngine(_CFG, params, _SC)
    snap = eng.state_dict()
    snap["serve_state_version"] = 1
    eng2 = DecodeEngine(_CFG, params, _SC)
    with pytest.raises(ValueError, match="serve_state_version"):
        eng2.load_state_dict(snap)


# ------------------------------------------------------------------
# PagedKVCache double-release (satellite)
# ------------------------------------------------------------------


def test_double_release_raises_by_name():
    """release_slot on an already-freed or never-allocated slot raises
    PageAccountingError instead of silently corrupting the free list;
    accounting stays exact through the failure."""
    cfg = KVCacheConfig(n_layers=1, n_kv_heads=2, head_dim=8,
                        n_slots=4, n_pages=9, pages_per_slot_max=4,
                        page_size=4)
    cache = PagedKVCache(cfg)
    assert cache.allocate_slot(0, 10) is not None     # 3 pages
    assert cache.allocate_slot(1, 4) is not None      # 1 page
    cache.release_slot(0)
    with pytest.raises(PageAccountingError, match="double release"):
        cache.release_slot(0)                          # double free
    with pytest.raises(PageAccountingError, match="never allocated"):
        cache.release_slot(3)                          # never allocated
    # the free list survived both refusals intact: no page lost, none
    # duplicated (the regression the silent path would have hidden)
    cache.release_slot(1)
    assert sorted(cache._free) == list(range(1, 9))
    assert cache.free_pages == cfg.usable_pages


# ------------------------------------------------------------------
# watchdog + poison + drain
# ------------------------------------------------------------------


def test_watchdog_trips_restarts_bitwise(params, ref_tokens):
    """The serve.stall_step wedge: the watchdog trips by name
    (naming the stuck step), dumps nothing silently, restart()
    resumes from the periodic snapshot and the finished tokens are
    BITWISE the unstalled run's; counters stamp into serve_record."""
    eng = DecodeEngine(_CFG, params, _SC)
    for p, b in zip(_PROMPTS[:5], _BUDGETS[:5]):
        eng.submit(p, b)
    dog = EngineWatchdog(eng, stall_timeout_s=0.05, snapshot_every=1)
    chaos.arm("serve.stall_step", 3)
    fins = {}
    tripped = None
    steps = 0
    while eng.pending:
        assert steps < 400
        eng.step()
        for f in eng.poll():
            fins[f.request_id] = f
        try:
            dog.check()
        except EngineStalledError as e:
            tripped = e
            eng = dog.restart()
        if eng.stalled:
            time.sleep(0.02)
        steps += 1
    eng._retire_finished()
    for f in eng.poll():
        fins[f.request_id] = f
    assert tripped is not None and tripped.step is not None
    assert "stalled" in str(tripped) and f"step {tripped.step}" in str(
        tripped)
    assert dog.stalls == 1 and dog.restarts == 1
    assert all(f.status == "ok" for f in fins.values())
    _assert_clean(eng, fins, ref_tokens)
    rec = eng.serve_record()
    assert rec["serve_watchdog_stalls"] == 1
    assert rec["serve_watchdog_restarts"] == 1


def test_watchdog_idle_engine_never_trips(params):
    """No pending work is not a stall: the clock re-arms while idle
    and after new submissions the timeout is judged fresh."""
    eng = DecodeEngine(_CFG, params, _SC)
    t = [0.0]
    dog = EngineWatchdog(eng, stall_timeout_s=1.0, clock=lambda: t[0])
    t[0] = 50.0
    dog.check()                                # idle: no trip
    eng.submit([1, 2], 2)
    t[0] = 50.5
    dog.check()                                # within timeout: fine
    t[0] = 52.0
    with pytest.raises(EngineStalledError):
        dog.check()


def test_poison_detected_and_snapshot_stays_good(params, ref_tokens):
    """serve.poison_logits: garbage token ids are refused BY NAME at
    the retire poll, and the watchdog's snapshot is last-KNOWN-GOOD
    (a poisoned candidate never replaces it), so one restart clears
    the corruption and the run finishes bitwise."""
    eng = DecodeEngine(_CFG, params, _SC)
    for p, b in zip(_PROMPTS[:4], _BUDGETS[:4]):
        eng.submit(p, b)
    dog = EngineWatchdog(eng, stall_timeout_s=30.0, snapshot_every=1)
    chaos.arm("serve.poison_logits", 2)
    fins = {}
    caught = None
    steps = restarts = 0
    while eng.pending:
        assert steps < 400
        try:
            eng.step()
        except PoisonedOutputError as e:
            caught = e
            restarts += 1
            assert restarts < 3, "snapshot was not known-good"
            eng = dog.restart()
            continue
        for f in eng.poll():
            fins[f.request_id] = f
        dog.check()
        steps += 1
    eng._retire_finished()
    for f in eng.poll():
        fins[f.request_id] = f
    assert caught is not None and caught.slot is not None
    assert "token ids outside" in str(caught)
    assert all(f.status == "ok" for f in fins.values())
    _assert_clean(eng, fins, ref_tokens)


def test_drain_finishes_live_snapshots_queue(params, ref_tokens):
    """drain(): admission stops (submit refuses), live slots finish,
    the snapshot carries the queued remainder, and a fresh engine of
    the same deployment completes them bitwise.  kill_mid_drain dies
    by SimulatedPreemption and the snapshot contract recovers."""
    eng = DecodeEngine(_CFG, params, _SC)
    for p, b in zip(_PROMPTS[:5], _BUDGETS[:5]):
        eng.submit(p, b)
    eng.step()
    n_queued = len(eng._pending)
    assert n_queued > 0
    snap = eng.drain()
    with pytest.raises(RuntimeError, match="drain"):
        # admission is stopped DURING drain; after it the engine is
        # reusable — check the guard via the draining flag path
        eng._draining = True
        eng.submit([1], 1)
    eng._draining = False
    assert len(eng._live) == 0
    assert len(snap["scheduler"]["pending"]) == n_queued
    fins = {f.request_id: f for f in eng.poll()}
    eng2 = DecodeEngine(_CFG, params, _SC)
    eng2.load_state_dict(snap)
    fins.update(_drive(eng2))
    assert set(fins) == set(range(5))
    assert all(f.status == "ok" for f in fins.values())
    _assert_clean(eng2, fins, ref_tokens)

    # the kill: drain dies partway, state_dict recovers
    eng3 = DecodeEngine(_CFG, params, _SC)
    for p, b in zip(_PROMPTS[:5], _BUDGETS[:5]):
        eng3.submit(p, b)
    eng3.step()
    chaos.arm("serve.kill_mid_drain", 2)
    with pytest.raises(chaos.SimulatedPreemption):
        eng3.drain()
    assert not eng3.draining                   # flag reset on the way out
    snap3 = eng3.state_dict()
    fins3 = {f.request_id: f for f in eng3.poll()}
    eng4 = DecodeEngine(_CFG, params, _SC)
    eng4.load_state_dict(snap3)
    fins3.update(_drive(eng4))
    assert all(f.status == "ok" for f in fins3.values())
    _assert_clean(eng4, fins3, ref_tokens)


# ------------------------------------------------------------------
# SCHEMA v10 stamps
# ------------------------------------------------------------------


def test_schema_v10_resilience_stamps_validate(params, tmp_path):
    """The terminal counters ride serve_record() always; watchdog
    counters once a watchdog attaches; a MetricsLogger(serve=) record
    carrying all of them validates under SCHEMA v10."""
    assert monitor.SCHEMA_VERSION >= 10
    eng = DecodeEngine(_CFG, params, _SC)
    doomed = eng.submit([1, 2], 4, deadline_ms=0.001)
    eng.submit([3, 4], 3)
    time.sleep(0.005)
    _drive(eng)
    EngineWatchdog(eng, stall_timeout_s=30.0)
    rec = eng.serve_record()
    assert rec["serve_expired_total"] == 1
    assert rec["serve_shed_total"] == 0
    assert rec["serve_cancelled_total"] == 0
    assert rec["serve_watchdog_stalls"] == 0
    assert rec["serve_watchdog_restarts"] == 0
    base = {
        "monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
        "loss": 1.0, "grad_norm": 1.0, "param_norm": 1.0,
        "update_norm": 0.1, "loss_scale": 1.0, "overflow_count": 0,
        "skipped_steps": 0, "tokens_seen": 10.0, "step_time_ms": 1.0,
        "tokens_per_sec": 10.0, "mfu": 0.1,
        "serve_shed_fraction": 0.25,
        "serve_goodput_tokens_per_sec": 123.4,
    }
    base.update(rec)
    monitor.validate_record(base)
    # the reserved-prefix rule still bites: a null terminal counter is
    # a schema violation, not a missing sample
    with pytest.raises(ValueError):
        monitor.validate_record(dict(base, serve_shed_total=None))
    _ = doomed


# ------------------------------------------------------------------
# the standing CI gates (scripts/serve_chaos_probe.py)
# ------------------------------------------------------------------


def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_serve_chaos_probe_selftest():
    """Tier-1 gate (the slo_probe convention): fixture drift + the
    seeded deadline-breach / shed-ordering / watchdog-trip negative
    controls, all asserted by name."""
    r = _run_script(ROOT / "scripts" / "serve_chaos_probe.py",
                    "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "serve_chaos_probe --selftest: OK" in r.stdout


def test_serve_chaos_probe_full_matrix():
    """The full overload + kill matrix on the flagship build path:
    survivors bitwise at every fail point, pool reconciled, ledger
    balanced, negative controls by name, zero steady recompiles."""
    r = _run_script(ROOT / "scripts" / "serve_chaos_probe.py",
                    "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    import json as _json

    # the JSON rides one line; the OK banner follows it (reverse-scan,
    # the bench _run_isolated convention)
    line = next(ln for ln in reversed(r.stdout.strip().splitlines())
                if ln.startswith("{"))
    out = _json.loads(line)
    assert out["ok"] is True
    assert out["stall"]["tripped"] and out["poison"]["detected"]
    assert out["kill_drain_ok"]
    assert out["overload"]["n_shed"] > 0
    assert out["overload"]["n_expired"] > 0
