"""Foundation tests: mesh construction + Megatron collective semantics.

≡ tests/L0/run_transformer/test_parallel_state.py and test_mapping.py in
the reference — group math and fwd/bwd collective pairs, here checked on
an 8-device CPU mesh via shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from apex_tpu.parallel import collectives as C
from apex_tpu.parallel import mesh as M


def test_mesh_shapes():
    m = M.initialize_model_parallel(tensor_model_parallel_size=2,
                                    pipeline_model_parallel_size=2)
    assert M.get_tensor_model_parallel_world_size() == 2
    assert M.get_pipeline_model_parallel_world_size() == 2
    assert M.get_data_parallel_world_size() == 2
    assert m.shape == {"pp": 2, "dp": 2, "tp": 2}
    M.destroy_model_parallel()
    assert not M.model_parallel_is_initialized()


def test_mesh_invalid_world():
    with pytest.raises(ValueError):
        M.initialize_model_parallel(tensor_model_parallel_size=3)


def _tp_shard_map(fn, mesh, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                     check_vma=False)


def test_copy_reduce_pair():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(16.0).reshape(2, 8)

    # reduce_from: fwd = sum over tp of identical copies = 8x
    f = _tp_shard_map(lambda a: C.reduce_from_tensor_model_parallel_region(a),
                      mesh, P(), P())
    np.testing.assert_allclose(f(x), 8 * x)

    # copy_to: fwd identity; bwd psum — grad of sum(copy(x)) per rank sums
    def loss(a):
        y = C.copy_to_tensor_model_parallel_region(a)
        return jnp.sum(y * y)

    g = _tp_shard_map(jax.grad(loss), mesh, P(), P())
    np.testing.assert_allclose(g(x), 8 * 2 * x)  # psum of identical grads


def test_scatter_gather_last_dim():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(32.0).reshape(4, 8)

    f = _tp_shard_map(
        lambda a: C.gather_from_tensor_model_parallel_region(
            C.scatter_to_tensor_model_parallel_region(a)),
        mesh, P(), P())
    np.testing.assert_allclose(f(x), x)


def test_sequence_parallel_roundtrip():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(64.0).reshape(8, 8)

    f = _tp_shard_map(
        lambda a: C.gather_from_sequence_parallel_region(
            C.scatter_to_sequence_parallel_region(a)),
        mesh, P(), P())
    np.testing.assert_allclose(f(x), x)

    # reduce_scatter fwd: 8 identical copies summed then split
    f2 = _tp_shard_map(
        lambda a: C.reduce_scatter_to_sequence_parallel_region(a),
        mesh, P(), P("tp"))
    out = f2(x)
    np.testing.assert_allclose(out, 8 * x)


def test_gather_seq_backward_is_reduce_scatter():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    # per-rank input shard: rows of x over tp
    x = jnp.arange(64.0).reshape(8, 8)

    def loss(a):
        full = C.gather_from_sequence_parallel_region(a)  # (8,8) per rank
        return jnp.sum(full * full)

    g = _tp_shard_map(jax.grad(loss), mesh, P("tp"), P("tp"))
    # each rank contributes grad 2*full; reduce-scatter sums 8 copies, splits
    np.testing.assert_allclose(g(x), 8 * 2 * x)


def test_ring_exchange_and_halo():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(8.0).reshape(8, 1)  # row r on rank r

    f = _tp_shard_map(lambda a: C.ring_exchange(a, "tp", 1),
                      mesh, P("tp"), P("tp"))
    out = f(x)
    np.testing.assert_allclose(out.ravel(), np.roll(np.arange(8.0), 1))

    # halo: each rank holds 4 rows; left halo = prev rank's last row
    y = jnp.arange(32.0).reshape(32, 1)

    def halo(a):
        left, right = C.halo_exchange_1d(a, "tp", halo=1, dim=0)
        return jnp.concatenate([left, right], axis=0)

    f2 = _tp_shard_map(halo, mesh, P("tp"), P("tp"))
    out = f2(y).ravel()
    # rank r gets left = y[4r-1], right = y[4r+4 mod 32]
    expect = []
    for r in range(8):
        expect += [(4 * r - 1) % 32, (4 * r + 4) % 32]
    np.testing.assert_allclose(out, np.array(expect, dtype=np.float32))


def test_group_stage_sets_and_rank_math():
    """Embedding / position-embedding / relative-pos / amax group parity.

    ≡ the group-construction logic of parallel_state.initialize_model_parallel
    (parallel_state.py:280-407) checked as stage sets and flat-rank math.
    """
    M.destroy_model_parallel()
    M.initialize_model_parallel(tensor_model_parallel_size=2,
                                pipeline_model_parallel_size=4,
                                pipeline_model_parallel_split_rank=2,
                                use_fp8=True)
    assert M.get_embedding_group_stages() == [0, 2, 3]
    assert M.get_position_embedding_group_stages() == [0, 2]
    assert M.get_encoder_relative_position_embedding_group_stages() == [0, 1]
    assert M.get_decoder_relative_position_embedding_group_stages() == [2, 3]
    assert M.is_rank_in_embedding_group(3) and not M.is_rank_in_embedding_group(1)
    assert M.is_pipeline_stage_before_split(1)
    assert not M.is_pipeline_stage_before_split(2)
    assert M.is_pipeline_stage_after_split(2)
    assert M.is_pipeline_stage_at_split(1)
    assert not M.is_pipeline_stage_at_split(2)

    # pipeline rank math: stride between stages is dp*tp = world//pp
    assert M.get_pipeline_model_parallel_next_rank(3) == 0
    assert M.get_pipeline_model_parallel_prev_rank(0) == 3
    assert M.get_pipeline_global_device_ranks(dp_index=0, tp_index=1) == \
        [1, 3, 5, 7]
    assert M.get_tensor_model_parallel_src_rank(5) == 4
    # dp=1 here: every device is its own DP group
    assert M.get_data_parallel_src_rank(7) == 7
    assert M.get_amax_reduction_axes() == ("dp", "tp")
    assert M.get_model_parallel_axes() == ("pp", "tp")
    M.destroy_model_parallel()

    # no split, pp=2, tp=2, dp=2: embedding group = first+last
    M.initialize_model_parallel(tensor_model_parallel_size=2,
                                pipeline_model_parallel_size=2)
    # device 7 = stage 1, dp 1, tp 1 -> DP group {5, 7}, first member 5
    assert M.get_data_parallel_src_rank(7) == 5
    assert M.get_data_parallel_src_rank(2) == 0
    M.destroy_model_parallel()
    M.initialize_model_parallel(pipeline_model_parallel_size=2)
    assert M.get_embedding_group_stages() == [0, 1]
    assert M.get_position_embedding_group_stages() == [0]
    assert M.get_encoder_relative_position_embedding_group_stages() == [0]
    with pytest.raises(M.MeshNotInitializedError):
        M.get_amax_reduction_axes()
    M.destroy_model_parallel()


def test_reduce_amax_under_shard_map():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=2,
                                       pipeline_model_parallel_size=2,
                                       use_fp8=True)
    x = jnp.arange(8.0)

    def f(a):
        return M.reduce_amax(jnp.max(jnp.abs(a)))[None]

    g = shard_map(f, mesh=mesh, in_specs=P(("pp", "dp", "tp")),
                  out_specs=P("pp"), check_vma=False)
    out = np.asarray(g(x))
    # per-stage (dp,tp) plane max: stage0 holds 0..3 -> 3, stage1 4..7 -> 7
    np.testing.assert_allclose(out, [3.0, 7.0])
    M.destroy_model_parallel()
