"""Foundation tests: mesh construction + Megatron collective semantics.

≡ tests/L0/run_transformer/test_parallel_state.py and test_mapping.py in
the reference — group math and fwd/bwd collective pairs, here checked on
an 8-device CPU mesh via shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from apex_tpu.parallel import collectives as C
from apex_tpu.parallel import mesh as M


def test_mesh_shapes():
    m = M.initialize_model_parallel(tensor_model_parallel_size=2,
                                    pipeline_model_parallel_size=2)
    assert M.get_tensor_model_parallel_world_size() == 2
    assert M.get_pipeline_model_parallel_world_size() == 2
    assert M.get_data_parallel_world_size() == 2
    assert m.shape == {"pp": 2, "dp": 2, "tp": 2}
    M.destroy_model_parallel()
    assert not M.model_parallel_is_initialized()


def test_mesh_invalid_world():
    with pytest.raises(ValueError):
        M.initialize_model_parallel(tensor_model_parallel_size=3)


def _tp_shard_map(fn, mesh, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                     check_vma=False)


def test_copy_reduce_pair():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(16.0).reshape(2, 8)

    # reduce_from: fwd = sum over tp of identical copies = 8x
    f = _tp_shard_map(lambda a: C.reduce_from_tensor_model_parallel_region(a),
                      mesh, P(), P())
    np.testing.assert_allclose(f(x), 8 * x)

    # copy_to: fwd identity; bwd psum — grad of sum(copy(x)) per rank sums
    def loss(a):
        y = C.copy_to_tensor_model_parallel_region(a)
        return jnp.sum(y * y)

    g = _tp_shard_map(jax.grad(loss), mesh, P(), P())
    np.testing.assert_allclose(g(x), 8 * 2 * x)  # psum of identical grads


def test_scatter_gather_last_dim():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(32.0).reshape(4, 8)

    f = _tp_shard_map(
        lambda a: C.gather_from_tensor_model_parallel_region(
            C.scatter_to_tensor_model_parallel_region(a)),
        mesh, P(), P())
    np.testing.assert_allclose(f(x), x)


def test_sequence_parallel_roundtrip():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(64.0).reshape(8, 8)

    f = _tp_shard_map(
        lambda a: C.gather_from_sequence_parallel_region(
            C.scatter_to_sequence_parallel_region(a)),
        mesh, P(), P())
    np.testing.assert_allclose(f(x), x)

    # reduce_scatter fwd: 8 identical copies summed then split
    f2 = _tp_shard_map(
        lambda a: C.reduce_scatter_to_sequence_parallel_region(a),
        mesh, P(), P("tp"))
    out = f2(x)
    np.testing.assert_allclose(out, 8 * x)


def test_gather_seq_backward_is_reduce_scatter():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    # per-rank input shard: rows of x over tp
    x = jnp.arange(64.0).reshape(8, 8)

    def loss(a):
        full = C.gather_from_sequence_parallel_region(a)  # (8,8) per rank
        return jnp.sum(full * full)

    g = _tp_shard_map(jax.grad(loss), mesh, P("tp"), P("tp"))
    # each rank contributes grad 2*full; reduce-scatter sums 8 copies, splits
    np.testing.assert_allclose(g(x), 8 * 2 * x)


def test_ring_exchange_and_halo():
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=8)
    x = jnp.arange(8.0).reshape(8, 1)  # row r on rank r

    f = _tp_shard_map(lambda a: C.ring_exchange(a, "tp", 1),
                      mesh, P("tp"), P("tp"))
    out = f(x)
    np.testing.assert_allclose(out.ravel(), np.roll(np.arange(8.0), 1))

    # halo: each rank holds 4 rows; left halo = prev rank's last row
    y = jnp.arange(32.0).reshape(32, 1)

    def halo(a):
        left, right = C.halo_exchange_1d(a, "tp", halo=1, dim=0)
        return jnp.concatenate([left, right], axis=0)

    f2 = _tp_shard_map(halo, mesh, P("tp"), P("tp"))
    out = f2(y).ravel()
    # rank r gets left = y[4r-1], right = y[4r+4 mod 32]
    expect = []
    for r in range(8):
        expect += [(4 * r - 1) % 32, (4 * r + 4) % 32]
    np.testing.assert_allclose(out, np.array(expect, dtype=np.float32))
