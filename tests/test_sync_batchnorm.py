"""SyncBatchNorm numerics vs single-device BN over the full batch.

≡ tests/distributed/synced_batchnorm/*.py — the defining property: BN
with stats merged across the dp axis equals BN over the unsharded batch,
forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops import welford
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, sync_batch_norm


def _reference_bn(x, scale, bias, eps=1e-5):
    mean = x.mean(axis=(0, 1, 2))
    var = x.var(axis=(0, 1, 2))
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return y * scale + bias


def test_channel_sums_pallas_parity():
    x = jax.random.normal(jax.random.PRNGKey(0), (37, 16))
    import apex_tpu.ops._common as common
    old = common._FORCE
    common._FORCE = "1"
    try:
        s, q = welford.channel_sums(x)
    finally:
        common._FORCE = old
    np.testing.assert_allclose(np.asarray(s), np.asarray(x.sum(0)),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(q), np.asarray((x * x).sum(0)),
                               rtol=1e-5, atol=1e-4)


def test_syncbn_matches_full_batch():
    mesh = M.initialize_model_parallel()  # dp=8
    N, H, W, C = 16, 4, 4, 6
    x = jax.random.normal(jax.random.PRNGKey(1), (N, H, W, C))
    scale = jnp.linspace(0.5, 1.5, C)
    bias = jnp.linspace(-1, 1, C)
    rm = jnp.zeros((C,))
    rv = jnp.ones((C,))

    def local(xl):
        y, nrm, nrv = sync_batch_norm(xl, scale, bias, rm, rv,
                                      training=True, axis_name="dp")
        return y, nrm, nrv

    f = shard_map(local, mesh=mesh, in_specs=P("dp"),
                  out_specs=(P("dp"), P(), P()), check_vma=False)
    y, nrm, nrv = f(x)
    want = _reference_bn(x, scale, bias)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # running stats: momentum 0.1, unbiased var
    n = N * H * W
    np.testing.assert_allclose(
        np.asarray(nrm), 0.1 * np.asarray(x.mean(axis=(0, 1, 2))),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(nrv),
        0.9 + 0.1 * np.asarray(x.var(axis=(0, 1, 2))) * n / (n - 1),
        rtol=1e-4, atol=1e-5)


def test_syncbn_backward_matches_full_batch():
    mesh = M.initialize_model_parallel()
    N, C = 16, 4
    x = jax.random.normal(jax.random.PRNGKey(2), (N, 3, 3, C))
    scale = jnp.ones((C,)) * 1.3
    bias = jnp.zeros((C,))
    rm, rv = jnp.zeros((C,)), jnp.ones((C,))
    t = jax.random.normal(jax.random.PRNGKey(3), x.shape)

    def sharded_loss(x, scale, bias, t):
        def local(xl, s, b, tl):
            y, _, _ = sync_batch_norm(xl, s, b, rm, rv, training=True,
                                      axis_name="dp")
            return jax.lax.psum(jnp.sum(y * tl), "dp")
        f = shard_map(local, mesh=mesh,
                      in_specs=(P("dp"), P(), P(), P("dp")),
                      out_specs=P(), check_vma=False)
        return f(x, scale, bias, t)

    def ref_loss(x, scale, bias, t):
        return jnp.sum(_reference_bn(x, scale, bias) * t)

    g1 = jax.grad(sharded_loss, argnums=(0, 1, 2))(x, scale, bias, t)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(x, scale, bias, t)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-4)


def test_syncbn_module_eval_mode():
    bn = SyncBatchNorm(5)
    params, state = bn.init()
    x = jax.random.normal(jax.random.PRNGKey(4), (3, 2, 2, 5)) * 2 + 1
    y, new_state = bn.apply(params, state, x, training=False, axis_name=None)
    # eval mode: normalize with running stats (0 mean, 1 var) → identity
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["running_mean"]), 0.0)
