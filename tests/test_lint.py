"""apex_tpu.lint — the static program/source linter (ISSUE 6).

Seeded-violation fixtures for every rule (a deliberate fp32 GEMM, an
fp16 psum, a missing donation, an `.item()` in a jitted fn, ...)
asserting rule id + location; clean-program zero-findings tests on the
REAL `ddp.make_train_step` / `make_tp_dp_train_step` programs; the
allowlist/suppression machinery; the `lint_step.py --selftest`
schema-drift gate; and the repo-wide AST pass over apex_tpu/ itself.

Everything here traces — nothing compiles or executes a step — so the
whole file stays cheap inside the tier-1 window.
"""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np  # noqa: F401
import pytest

from apex_tpu import lint
from apex_tpu.lint import LintConfig

ROOT = pathlib.Path(__file__).resolve().parent.parent
SDS = jax.ShapeDtypeStruct


def rules_of(findings):
    return sorted(f.rule for f in findings)


# ------------------------- dtype-policy pass -------------------------

def test_dp101_fp32_gemm_in_bf16_region():
    def f(x32, xbf, w):
        a = xbf @ w                       # the policy-conformant GEMM
        b = x32 @ x32.T                   # the fp32 offender
        return a.astype(jnp.float32).sum() + b.sum()

    fs = lint.lint_program(
        f, (SDS((64, 64), jnp.float32), SDS((64, 64), jnp.bfloat16),
            SDS((64, 64), jnp.bfloat16)), program="seed")
    hits = [f for f in fs if f.rule == "DP101"]
    assert len(hits) == 1
    assert "dot_general" in hits[0].location
    assert hits[0].location.startswith("seed:")

    # explicit declared dtype works too (no inference)
    fs2 = lint.lint_program(
        f, (SDS((64, 64), jnp.float32), SDS((64, 64), jnp.bfloat16),
            SDS((64, 64), jnp.bfloat16)),
        config=LintConfig(compute_dtype="bfloat16"))
    assert [f.rule for f in fs2 if f.rule == "DP101"] == ["DP101"]


def test_dp101_not_in_fp32_region():
    def f(x, w):
        return (x @ w).sum()

    fs = lint.lint_program(f, (SDS((64, 64), jnp.float32),
                               SDS((64, 64), jnp.float32)))
    assert rules_of(fs) == []


def test_dp102_lossy_roundtrip():
    def f(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    fs = lint.lint_program(f, (SDS((128, 128), jnp.float32),))
    assert rules_of(fs) == ["DP102"]
    assert "convert_element_type" in fs[0].location

    # small per-channel vectors (an amp policy's norm scale/bias
    # re-promotions) are exempt by the size floor
    fs_small = lint.lint_program(f, (SDS((64,), jnp.float32),))
    assert rules_of(fs_small) == []


def test_dp103_low_precision_large_reduction():
    # a raw lax-level reduce keeps the bf16 accumulator (jnp.sum — even
    # with dtype=bf16 — upcasts to f32 internally, which is why only
    # hand-written lax reductions can hit this)
    def f(x):
        return jax.lax.reduce_sum_p.bind(x, axes=(0,))

    fs = lint.lint_program(f, (SDS((1 << 18,), jnp.bfloat16),))
    assert "DP103" in rules_of(fs)

    # jnp's default f32 accumulation must NOT flag, dtype= included
    def g(x):
        return jnp.sum(x) + jnp.sum(x, dtype=jnp.bfloat16).astype(
            jnp.float32)

    assert rules_of(lint.lint_program(
        g, (SDS((1 << 18,), jnp.bfloat16),))) == []


def test_dp104_master_update_in_low_precision():
    def f(p, g):
        upd = p.astype(jnp.bfloat16) - 0.1 * g
        return upd.astype(jnp.float32)   # stored f32, computed bf16

    fs = lint.lint_program(
        f, (SDS((1 << 15,), jnp.float32), SDS((1 << 15,), jnp.bfloat16)))
    assert "DP104" in rules_of(fs)

    # the correct shape — upcast grads FIRST, math in f32 — is clean
    def ok(p, g):
        return p - 0.1 * g.astype(jnp.float32)

    assert rules_of(lint.lint_program(
        ok, (SDS((1 << 15,), jnp.float32),
             SDS((1 << 15,), jnp.bfloat16)))) == []


# ------------------------- collective pass -------------------------

def test_cl201_mismatched_axis():
    def f(x):
        return jax.lax.psum(x, "i")

    fs = lint.lint_program(
        f, (SDS((8,), jnp.float32),), axis_env=[("i", 2)],
        config=LintConfig(expected_axes=("dp", "tp")))
    assert rules_of(fs) == ["CL201"]
    assert "psum[0]" in fs[0].location
    assert fs[0].severity == "error"

    # matching declared mesh: clean
    assert rules_of(lint.lint_program(
        f, (SDS((8,), jnp.float32),), axis_env=[("i", 2)],
        config=LintConfig(expected_axes=("i",)))) == []


def test_cl202_psum_of_psum_and_of_pmean():
    def f(x):
        a = jax.lax.psum(jax.lax.psum(x, "i"), "i")
        b = jax.lax.psum(jax.lax.pmean(x, "i"), "i")
        return a + b

    fs = lint.lint_program(f, (SDS((8,), jnp.float32),),
                           axis_env=[("i", 2)])
    assert rules_of(fs) == ["CL202", "CL202"]


def test_cl203_scan_invariant_collective():
    def f(w, xs):
        def body(c, t):
            r = jax.lax.psum(w, "i")      # loop-invariant operand
            return c + r.sum() + t.sum(), ()

        c, _ = jax.lax.scan(body, jnp.zeros(()), xs)
        return c

    fs = lint.lint_program(f, (SDS((8,), jnp.float32),
                               SDS((4, 8), jnp.float32)),
                           axis_env=[("i", 2)])
    assert rules_of(fs) == ["CL203"]
    assert "scan" in fs[0].location

    # a carry-dependent collective must NOT flag
    def g(w, xs):
        def body(c, t):
            return c + jax.lax.psum(t, "i").sum(), ()

        c, _ = jax.lax.scan(body, jnp.zeros(()), xs)
        return c

    assert rules_of(lint.lint_program(
        g, (SDS((8,), jnp.float32), SDS((4, 8), jnp.float32)),
        axis_env=[("i", 2)])) == []


def test_cl204_fp16_psum():
    def f(x):
        return jax.lax.psum(x, "i")

    fs = lint.lint_program(f, (SDS((8,), jnp.float16),),
                           axis_env=[("i", 2)])
    assert rules_of(fs) == ["CL204"]
    # bf16 carries fp32's exponent — exempt
    assert rules_of(lint.lint_program(
        f, (SDS((8,), jnp.bfloat16),), axis_env=[("i", 2)])) == []


def test_cl205_dead_collective():
    def f(x):
        _dead = jax.lax.psum(x, "i")
        return x * 2.0

    fs = lint.lint_program(f, (SDS((8,), jnp.float32),),
                           axis_env=[("i", 2)])
    assert rules_of(fs) == ["CL205"]


def test_cl206_all_to_all_wrong_axis():
    """Expert dispatch/combine traffic off the ep axis (ISSUE 13): an
    all_to_all riding dp while the mesh carries ep is the silent
    token-scrambling transposition CL206 exists for."""
    def wrong(x):
        return jax.lax.all_to_all(x, "dp", split_axis=0, concat_axis=1,
                                  tiled=True)

    fs = lint.lint_program(wrong, (SDS((8, 8), jnp.float32),),
                           axis_env=[("dp", 2), ("ep", 2)])
    assert "CL206" in rules_of(fs)
    hit = next(f for f in fs if f.rule == "CL206")
    assert hit.severity == "error" and "all_to_all[0]" in hit.location

    # the conforming exchange — over ep — is clean
    def ok(x):
        return jax.lax.all_to_all(x, "ep", split_axis=0, concat_axis=1,
                                  tiled=True)

    assert "CL206" not in rules_of(lint.lint_program(
        ok, (SDS((8, 8), jnp.float32),),
        axis_env=[("dp", 2), ("ep", 2)]))
    # without any ep axis in sight, a dp all_to_all is legal
    assert "CL206" not in rules_of(lint.lint_program(
        wrong, (SDS((8, 8), jnp.float32),), axis_env=[("dp", 2)]))

    # a NON-dp all_to_all (the Ulysses cp head-scatter) is legitimate
    # non-expert traffic even when the mesh carries ep
    def ulysses(x):
        return jax.lax.all_to_all(x, "cp", split_axis=0, concat_axis=1,
                                  tiled=True)

    assert "CL206" not in rules_of(lint.lint_program(
        ulysses, (SDS((8, 8), jnp.float32),),
        axis_env=[("dp", 2), ("cp", 2), ("ep", 2)]))


def test_cl206_all_to_all_undeclared_axis():
    def f(x):
        return jax.lax.all_to_all(x, "zz", split_axis=0, concat_axis=1,
                                  tiled=True)

    fs = lint.lint_program(
        f, (SDS((8, 8), jnp.float32),), axis_env=[("zz", 2)],
        config=LintConfig(expected_axes=("dp", "ep")))
    assert "CL206" in rules_of(fs)


def test_cl207_incomplete_ppermute_ring():
    """A one-directional chain perm (the broken ring, ISSUE 18): rank 0
    sends but receives from nobody, so lax.ppermute silently hands it
    ZEROS — the hazard the chunked ring-overlap pipelines multiply by
    chunk count."""
    def chain(x):
        perm = [(i, i + 1) for i in range(3)]   # 4 ranks, no wrap
        return jax.lax.ppermute(x, "tp", perm)

    fs = lint.lint_program(chain, (SDS((8,), jnp.float32),),
                           axis_env=[("tp", 4)])
    hits = [f for f in fs if f.rule == "CL207"]
    assert len(hits) == 1
    assert "ZEROS" in hits[0].message and "[0]" in hits[0].message


def test_cl207_duplicate_destination():
    def dup(x):
        return jax.lax.ppermute(x, "tp", [(0, 1), (2, 1), (1, 0)])

    fs = lint.lint_program(dup, (SDS((8,), jnp.float32),),
                           axis_env=[("tp", 4)])
    assert "CL207" in rules_of(fs)
    hit = next(f for f in fs if f.rule == "CL207")
    assert "destinations" in hit.message


def test_cl207_complete_rings_clean():
    """ring_exchange / halo_exchange_1d spell complete cyclic perms —
    every sender receives — so the real overlap building blocks stay
    finding-free."""
    from apex_tpu.parallel import collectives as C

    def ring(x):
        return C.ring_exchange(x, "tp", shift=-1)

    def halo(x):
        left, right = C.halo_exchange_1d(x, "tp", halo=1, dim=0)
        return left + right

    for f in (ring, halo):
        fs = lint.lint_program(f, (SDS((8, 4), jnp.float32),),
                               axis_env=[("tp", 4)])
        assert "CL207" not in rules_of(fs), f.__name__


def test_dp105_low_precision_router_selection():
    """A bf16 router softmax feeding top_k is a finding; the
    apex_tpu.moe contract — bf16 gate GEMM operands with fp32
    accumulation, fp32 softmax + selection — is clean."""
    def bad(x, wg):
        probs = jax.nn.softmax(jnp.dot(x, wg), axis=-1)  # bf16 end-to-end
        g, _ = jax.lax.top_k(probs, 2)
        return g.sum()

    fs = lint.lint_program(
        bad, (SDS((64, 32), jnp.bfloat16), SDS((32, 8), jnp.bfloat16)))
    assert "DP105" in rules_of(fs)
    assert "top_k" in next(f for f in fs if f.rule == "DP105").location

    def good(x, wg):
        from apex_tpu.moe.router import topk_gates_dense
        out = topk_gates_dense(x, wg, 2)
        return out.gate.sum()

    assert "DP105" not in rules_of(lint.lint_program(
        good, (SDS((64, 32), jnp.bfloat16), SDS((32, 8), jnp.bfloat16))))


# ------------------------- donation pass -------------------------

def _smoke_ddp_step(donate):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    cfg = GPTConfig(vocab_size=512, seq_len=64, hidden=64, num_layers=2,
                    num_heads=4, dropout=0.0)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-3, use_pallas=False)
    state = opt.init(params)

    def loss_fn(p, b):
        return model.loss(p, b[0], b[1])

    step = ddp.make_train_step(loss_fn, opt, mesh, donate=donate,
                               batch_spec=(P("dp"), P("dp")))
    tok = SDS((8, 64), jnp.int32)
    return step, (state, None, (tok, tok))


def test_dn301_undonated_state():
    step, args = _smoke_ddp_step(donate=False)
    fs = lint.lint_step(step, args, program="undonated")
    assert rules_of(fs) == ["DN301"]
    assert "opt_state" in fs[0].location


def test_dn302_runtime_donation_cross_check():
    step, args = _smoke_ddp_step(donate=True)
    fake_report = {"donation_ok": False, "undonated_bytes": 123456,
                   "donated_bytes": 654321}
    fs = lint.lint_step(step, args, program="xchk",
                        compile_report=fake_report)
    assert rules_of(fs) == ["DN302"]
    assert fs[0].severity == "error"


def test_clean_ddp_train_step():
    """The real fused DDP step (donate=True) lints clean — the
    zero-findings contract the CI gate holds the flagships to."""
    step, args = _smoke_ddp_step(donate=True)
    fs = lint.lint_step(step, args, program="ddp")
    assert fs == []
    # the builder attached the mesh axes the collective pass used
    assert "dp" in step.mesh_axis_names


def test_clean_tp_dp_train_step():
    """The flagship builder (`make_tp_dp_train_step`, the bench
    program) lints clean at the smoke config."""
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    cfg = GPTConfig(vocab_size=512, seq_len=64, hidden=64, num_layers=2,
                    num_heads=4, dropout=0.0)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=False)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    tok = SDS((2, 64), jnp.int32)
    fs = lint.lint_step(step, (opt_state, tok, tok), program="tp_dp")
    assert fs == []


# ------------------------- hostsync (AST) pass -------------------------

_SEEDED_SRC = '''
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def step(x, y):
    if x > 0:                    # HS404
        z = float(y)             # HS402
    v = x.item()                 # HS401
    a = np.asarray(y)            # HS403
    if x.shape[0] > 2:           # static: exempt
        pass
    if y is None:                # identity test: exempt
        pass
    return x

def loss(p, b):
    return (p * b).sum()

g = jax.grad(loss)

lr = 0.0

@jax.jit
def update(p):
    return p - lr * p            # HS406: lr rebound in the loop below

def driver(p, n):
    global lr
    for i in range(n):
        lr = 0.1 * i
        f = jax.jit(lambda q: q) # HS405
        p = update(p)
    return p

def warmup(step_fn, state, batch):
    for _ in range(3):
        state, loss_v = step_fn(state, batch)
    _ = np.asarray(loss_v)       # host side: fine
    return state
'''


def test_hostsync_seeded_rules():
    fs = lint.lint_source_text(_SEEDED_SRC, "seeded.py")
    got = {(f.rule, int(f.location.split(":")[1])) for f in fs}
    assert ("HS401", 10) in got
    assert ("HS402", 9) in got
    assert ("HS403", 11) in got
    assert ("HS404", 8) in got
    assert ("HS405", 33) in got
    # host-side warmup loop syncs must NOT flag
    assert not any(loc > 38 for _, loc in got)


def test_hostsync_scalar_closure():
    src = '''
import jax

def make(n):
    lr = 0.0

    @jax.jit
    def update(p):
        return p - lr * p

    out = None
    for i in range(n):
        lr = 0.1 * i
        out = update(out)
    return out
'''
    fs = lint.lint_source_text(src, "closure.py")
    assert [f.rule for f in fs] == ["HS406"]
    assert "'lr'" in fs[0].message


def test_hostsync_fresh_def_per_iteration_exempt():
    """A def INSIDE the rebinding loop is a fresh function per
    iteration — per-iteration capture by construction, not a stale
    bake (the resnet_profile sweep shape)."""
    src = '''
import jax

def sweep(n):
    for i in range(n):
        s = i + 1

        def fb(x):
            def f(x):
                return x * s
            y, vjp = jax.vjp(f, x)
            return vjp(y)
        run(fb)
'''
    assert lint.lint_source_text(src, "sweep.py") == []


def test_hostsync_inline_disable():
    src = '''
import jax

def sweep(xs):
    for x in xs:
        f = jax.jit(lambda q: q * x)  # lint: disable=HS405
        f(x)
'''
    assert lint.lint_source_text(src, "s.py") == []
    # without the comment it fires
    assert [f.rule for f in lint.lint_source_text(
        src.replace("  # lint: disable=HS405", ""), "s.py")] == ["HS405"]


def test_repo_ast_pass_is_clean():
    """The repo-wide AST pass over apex_tpu/ itself (ISSUE 6
    satellite): the framework's own source carries no retrace/
    host-sync hazards outside inline-annotated deliberate sites."""
    fs = lint.lint_paths([str(ROOT / "apex_tpu")], root=str(ROOT))
    assert fs == [], [f"{f.rule} {f.location}" for f in fs]


# ------------------------- findings / allowlist -------------------------

def test_allowlist_parse_apply_and_glob():
    entries = lint.parse_allowlist(
        "# comment\n"
        "HS401 examples/*.py:*\n"
        "DP101\n")
    a = lint.make_finding("HS401", "examples/foo.py:12", "m")
    b = lint.make_finding("HS401", "scripts/foo.py:12", "m")
    c = lint.make_finding("DP101", "anywhere:dot_general[0]", "m")
    new, allowed = lint.apply_allowlist([a, b, c], entries)
    assert [f.location for f in new] == ["scripts/foo.py:12"]
    assert len(allowed) == 2

    with pytest.raises(ValueError, match="unknown rule"):
        lint.parse_allowlist("XX999 foo\n")


def test_committed_allowlist_is_empty():
    """ISSUE 6 satellite: every violation surfaced at introduction was
    fixed or inline-annotated — the committed gate starts empty."""
    entries = lint.load_allowlist(
        str(ROOT / "scripts" / "lint_allowlist.txt"))
    assert entries == []


def test_lint_report_schema_roundtrip():
    f = lint.make_finding("CL204", "p:psum[0]", "msg", hint="h")
    rep = lint.LintReport(target="t", new=[f], allowlisted=[])
    d = json.loads(json.dumps(rep.to_dict()))
    lint.validate_findings(d)          # round-trips
    assert d["ok"] is False
    text = lint.render_findings(d)
    assert "CL204" in text and "fix: h" in text

    bad = dict(d, lint_schema_version=999)
    with pytest.raises(ValueError, match="lint_schema_version"):
        lint.validate_findings(bad)
    with pytest.raises(ValueError, match="ok bit"):
        lint.validate_findings(dict(d, ok=True))


def test_unknown_rule_and_severity_rejected():
    with pytest.raises(ValueError, match="unknown lint rule"):
        lint.Finding(rule="ZZ000", severity="error", location="x",
                     message="m")
    with pytest.raises(ValueError, match="severity"):
        lint.Finding(rule="HS401", severity="fatal", location="x",
                     message="m")


# ------------------------- integration -------------------------

def test_analyze_step_attaches_lint():
    """monitor.analyze_step(lint=True): findings ride on the
    CompileReport — and from there into the flight-recorder crash
    dump."""
    from apex_tpu import monitor

    step, args = _smoke_ddp_step(donate=True)
    rep = monitor.analyze_step(step, args, lint=True)
    assert rep.lint is not None
    assert rep.lint["ok"] is True and rep.lint["findings"] == []
    assert rep.to_dict()["lint"]["ok"] is True
    assert "lint: clean" in monitor.render_budget_table(rep)

    # a lint=False report carries None (and renders without the line)
    rep2 = monitor.analyze_step(step, args)
    assert rep2.lint is None
    assert "lint" not in monitor.render_budget_table(rep2)


def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_lint_step_selftest():
    """Tier-1 CI gate (mirrors `flight_report.py --selftest`): the
    committed fixture validates + renders under the CURRENT schema."""
    r = _run_script(ROOT / "scripts" / "lint_step.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lint_step --selftest: OK" in r.stdout


def test_lint_step_cli_flagships_clean():
    """The acceptance gate: `scripts/lint_step.py` exits 0 on the
    flagship GPT/BERT/serve/MoE step functions with the EMPTY
    committed allowlist (the MoE step is the ISSUE 13 acceptance
    criterion: its ep all_to_alls and fp32 router must clear the
    CL206/DP105 rules built for them)."""
    r = _run_script(ROOT / "scripts" / "lint_step.py", "gpt", "bert",
                    "serve", "moe")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CLEAN" in r.stdout
