"""GPT minimal tests ≡ tests/L0/run_transformer/test_gpt_minimal.py:
loss consistency across parallel configs (tp2 vs tp4, SP on/off), init
loss sanity, and training convergence with FusedAdam on a tp×dp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import mesh as M

VOCAB, SEQ, HID, LAYERS, HEADS = 64, 16, 32, 2, 4


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, seq_len=SEQ, hidden=HID,
                num_layers=LAYERS, num_heads=HEADS, dropout=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _data(batch=4):
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, SEQ), 0,
                                VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


def _loss_fn(model, mesh):
    specs = model.partition_specs()
    return shard_map(model.loss, mesh=mesh,
                     in_specs=(specs, P(), P()), out_specs=P(),
                     check_vma=False)


def _run_loss(tp, sequence_parallel):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=tp)
    model = GPT(_cfg(sequence_parallel=sequence_parallel))
    params = model.init(jax.random.PRNGKey(7))
    tokens, labels = _data()
    loss = _loss_fn(model, mesh)(params, tokens, labels)
    M.destroy_model_parallel()
    return float(loss)


def test_init_loss_near_uniform():
    loss = _run_loss(tp=2, sequence_parallel=False)
    assert abs(loss - np.log(VOCAB)) < 0.5


def test_loss_consistent_across_tp():
    l2 = _run_loss(tp=2, sequence_parallel=False)
    l4 = _run_loss(tp=4, sequence_parallel=False)
    np.testing.assert_allclose(l2, l4, rtol=2e-3)


def test_sequence_parallel_matches():
    base = _run_loss(tp=4, sequence_parallel=False)
    sp = _run_loss(tp=4, sequence_parallel=True)
    np.testing.assert_allclose(base, sp, rtol=2e-3)


def test_gpt_trains_tp_dp():
    """tp=4 × dp=2 training: shard-local fwd/bwd, dp-pmean, tp-sharded
    FusedAdam; loss decreases (≡ test_gpt_minimal.py convergence)."""
    from apex_tpu.transformer.training import (
        init_sharded_optimizer, make_tp_dp_train_step)

    mesh = M.initialize_model_parallel(tensor_model_parallel_size=4)
    model = GPT(_cfg())
    params = model.init(jax.random.PRNGKey(8))
    opt = FusedAdam(lr=3e-3, use_pallas=False)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=False)
    tokens, labels = _data(batch=8)

    losses = []
    for _ in range(10):
        opt_state, loss = step(opt_state, tokens, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 0.9


def test_train_step_cache_keys_on_shapes():
    """VERDICT r2 #8: the step builder's jit cache is keyed on input
    shapes — a changed batch shape builds a fresh shard_map/jit instead
    of silently reusing the first one, and two models sharing no builder
    never cross-talk."""
    from apex_tpu.transformer.training import (
        init_sharded_optimizer, make_tp_dp_train_step)

    mesh = M.initialize_model_parallel(tensor_model_parallel_size=4)
    model = GPT(_cfg())
    params = model.init(jax.random.PRNGKey(9))
    opt = FusedAdam(lr=1e-3, use_pallas=False)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=False)

    t8, l8 = _data(batch=8)
    t4, l4 = _data(batch=4)
    opt_state, loss8 = step(opt_state, t8, l8)
    opt_state, loss4 = step(opt_state, t4, l4)  # new shape, same builder
    opt_state, loss8b = step(opt_state, t8, l8)
    assert np.isfinite(float(loss8)) and np.isfinite(float(loss4))
    assert np.isfinite(float(loss8b))

    # a SECOND model (different width) through its own builder: both
    # steps keep working interleaved — no shared-cache cross-talk
    model2 = GPT(_cfg(hidden=64, num_heads=4))
    params2 = model2.init(jax.random.PRNGKey(10))
    opt2 = FusedAdam(lr=1e-3, use_pallas=False)
    opt_state2 = init_sharded_optimizer(opt2, model2, params2, mesh)
    step2 = make_tp_dp_train_step(model2, opt2, mesh, donate=False)
    opt_state2, loss2 = step2(opt_state2, t8, l8)
    opt_state, loss8c = step(opt_state, t8, l8)
    assert np.isfinite(float(loss2)) and np.isfinite(float(loss8c))
