"""Pipelined GPT ≡ the reference's pipeline-parallel GPT tests
(test_pipeline_parallel_fwd_bwd.py + test_gpt_minimal.py with pp>1):
pp×tp×dp loss parity against the non-pipelined model, and gradient flow.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPT, GPTConfig, GPTPipelined
from apex_tpu.parallel import mesh as M

VOCAB, SEQ, HID, LAYERS, HEADS = 64, 16, 32, 4, 4


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, seq_len=SEQ, hidden=HID,
                num_layers=LAYERS, num_heads=HEADS, dropout=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _data(batch=4):
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, SEQ), 0,
                                VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


def _plain_loss(tp):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=tp)
    model = GPT(_cfg())
    params = model.init(jax.random.PRNGKey(3))
    tokens, labels = _data()
    f = shard_map(model.loss, mesh=mesh,
                  in_specs=(model.partition_specs(), P(), P()),
                  out_specs=P(), check_vma=False)
    out = float(f(params, tokens, labels))
    M.destroy_model_parallel()
    return out


def _pipelined_loss(pp, tp, m, chunks=1):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)
    model = GPTPipelined(_cfg(), num_microbatches=m,
                         pipeline_parallel_size=pp,
                         num_model_chunks=chunks)
    params = model.init(jax.random.PRNGKey(3))
    tokens, labels = _data()
    f = shard_map(model.loss, mesh=mesh,
                  in_specs=(model.partition_specs(), P(), P()),
                  out_specs=P(), check_vma=False)
    out = float(f(params, tokens, labels))
    M.destroy_model_parallel()
    return out


def test_pipelined_matches_plain():
    base = _plain_loss(tp=2)
    piped = _pipelined_loss(pp=2, tp=2, m=2)
    np.testing.assert_allclose(piped, base, rtol=2e-3)


def test_pipelined_interleaved_matches():
    base = _plain_loss(tp=2)
    piped = _pipelined_loss(pp=2, tp=2, m=2, chunks=2)
    np.testing.assert_allclose(piped, base, rtol=2e-3)


def test_pipelined_microbatch_count_invariance():
    l2 = _pipelined_loss(pp=2, tp=2, m=2)
    l4 = _pipelined_loss(pp=2, tp=2, m=4)
    np.testing.assert_allclose(l2, l4, rtol=2e-3)


def test_pipelined_grads_flow():
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    model = GPTPipelined(_cfg(), num_microbatches=2,
                         pipeline_parallel_size=2)
    params = model.init(jax.random.PRNGKey(4))
    tokens, labels = _data()
    specs = model.partition_specs()

    def local_grads(p, t, l):
        return jax.grad(lambda p: model.loss(p, t, l))(p)

    g = shard_map(local_grads, mesh=mesh, in_specs=(specs, P(), P()),
                  out_specs=specs, check_vma=False)(params, tokens, labels)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    # every stage's blocks received nonzero gradient
    bl = np.asarray(g["blocks"]["qkv"]["weight"])  # (pp, 1, lps, H, 3H/tp)
    for s in range(2):
        assert np.abs(bl[s]).max() > 0


def _pipeline_allreduce_sizes(with_loss_fn):
    """Lower spmd_pipeline over a pp-only mesh with a toy stage and
    return every float all-reduce's operand element count from the
    monitor.comms inventory of the optimized HLO (ISSUE 7 port of the
    hand-rolled shape-regex; the inventory also pins each all-reduce
    to the pp axis, which the regex could not see)."""
    from apex_tpu.monitor import comms
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        spmd_pipeline)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(pipeline_model_parallel_size=2,
                                       tensor_model_parallel_size=1)
    m, shape = 4, (8, 128)
    w = jnp.full((1, 1), 1.01)
    mbs = jnp.ones((m,) + shape)

    def stage_fn(p, x, chunk):
        return x * p[0, 0]

    kw = (dict(loss_fn=lambda y, _: jnp.mean(y), loss_args=None)
          if with_loss_fn else {})

    def run(w, mbs):
        out = spmd_pipeline(stage_fn, w[None], mbs, **kw)
        return jnp.sum(out) if not with_loss_fn else out

    f = jax.jit(shard_map(run, mesh=mesh, in_specs=(P(), P()),
                          out_specs=P(), check_vma=False))
    rep = comms.comms_report(f, (w, mbs), mesh=mesh)
    M.destroy_model_parallel()
    sizes = []
    for c in rep.collectives:
        if c.kind != "all-reduce" or c.dtype not in ("f32", "f16"):
            continue
        assert c.axes in (("pp",), ()), c  # a pp-only mesh
        sizes.append(c.operand_bytes // (4 if c.dtype == "f32" else 2))
    return sizes


def test_pipelined_scalar_loss_no_stacked_psum():
    """VERDICT r1 weak #4: with loss_fn the pipeline psums only SCALARS
    across pp — never the (m, ...) stacked output.  The stacked-output
    path (no loss_fn) is the positive control proving the probe sees
    the big all-reduce when it exists."""
    stacked = _pipeline_allreduce_sizes(with_loss_fn=False)
    assert any(s >= 4 * 8 * 128 for s in stacked), stacked
    scalar = _pipeline_allreduce_sizes(with_loss_fn=True)
    assert scalar and all(s <= 8 for s in scalar), scalar


def test_pipelined_training_keeps_tied_embed_in_sync():
    """pp-replicated leaves (tied embed, pos_embed, final LN) receive
    per-stage PARTIAL grads; the train step must psum them over pp (≡
    the reference's embedding-group allreduce, parallel_state.py:319-407)
    or the per-stage optimizer copies diverge."""
    from apex_tpu.optimizers import flat as F
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.transformer.training import (
        init_sharded_optimizer, make_tp_dp_train_step)
    pp, tp = 2, 2
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)
    model = GPTPipelined(_cfg(), num_microbatches=2,
                         pipeline_parallel_size=pp)
    params = model.init(jax.random.PRNGKey(5))
    opt = FusedAdam(lr=1e-2, use_pallas=False)
    st = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=False)
    tokens, labels = _data(batch=4)
    losses = []
    for _ in range(3):
        st, loss = step(st, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # buffer dim0 is sharded P(("pp","tp")): rows = per-(pp,tp) locals
    buf = np.asarray(st.params)
    n_dev = pp * tp * M.get_data_parallel_world_size()
    local = buf.reshape(pp, n_dev // pp, -1)  # (pp, dp*tp, local_len)
    trees = [F.unflatten(jnp.asarray(local[s, 0]), opt.spec)
             for s in range(pp)]
    for key in ("embed", "pos_embed", "final_ln"):
        a = jax.tree_util.tree_leaves(trees[0][key])
        b = jax.tree_util.tree_leaves(trees[1][key])
        for x, y in zip(a, b):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=0, atol=0,
                err_msg=f"{key} diverged across pp stages")
    M.destroy_model_parallel()
