"""Pipelined GPT ≡ the reference's pipeline-parallel GPT tests
(test_pipeline_parallel_fwd_bwd.py + test_gpt_minimal.py with pp>1):
pp×tp×dp loss parity against the non-pipelined model, and gradient flow.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPT, GPTConfig, GPTPipelined
from apex_tpu.parallel import mesh as M

VOCAB, SEQ, HID, LAYERS, HEADS = 64, 16, 32, 4, 4


def _cfg(**kw):
    base = dict(vocab_size=VOCAB, seq_len=SEQ, hidden=HID,
                num_layers=LAYERS, num_heads=HEADS, dropout=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _data(batch=4):
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, SEQ), 0,
                                VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


def _plain_loss(tp):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(tensor_model_parallel_size=tp)
    model = GPT(_cfg())
    params = model.init(jax.random.PRNGKey(3))
    tokens, labels = _data()
    f = shard_map(model.loss, mesh=mesh,
                  in_specs=(model.partition_specs(), P(), P()),
                  out_specs=P(), check_vma=False)
    out = float(f(params, tokens, labels))
    M.destroy_model_parallel()
    return out


def _pipelined_loss(pp, tp, m, chunks=1):
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(
        tensor_model_parallel_size=tp, pipeline_model_parallel_size=pp)
    model = GPTPipelined(_cfg(), num_microbatches=m,
                         pipeline_parallel_size=pp,
                         num_model_chunks=chunks)
    params = model.init(jax.random.PRNGKey(3))
    tokens, labels = _data()
    f = shard_map(model.loss, mesh=mesh,
                  in_specs=(model.partition_specs(), P(), P()),
                  out_specs=P(), check_vma=False)
    out = float(f(params, tokens, labels))
    M.destroy_model_parallel()
    return out


def test_pipelined_matches_plain():
    base = _plain_loss(tp=2)
    piped = _pipelined_loss(pp=2, tp=2, m=2)
    np.testing.assert_allclose(piped, base, rtol=2e-3)


def test_pipelined_interleaved_matches():
    base = _plain_loss(tp=2)
    piped = _pipelined_loss(pp=2, tp=2, m=2, chunks=2)
    np.testing.assert_allclose(piped, base, rtol=2e-3)


def test_pipelined_microbatch_count_invariance():
    l2 = _pipelined_loss(pp=2, tp=2, m=2)
    l4 = _pipelined_loss(pp=2, tp=2, m=4)
    np.testing.assert_allclose(l2, l4, rtol=2e-3)


def test_pipelined_grads_flow():
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2)
    model = GPTPipelined(_cfg(), num_microbatches=2,
                         pipeline_parallel_size=2)
    params = model.init(jax.random.PRNGKey(4))
    tokens, labels = _data()
    specs = model.partition_specs()

    def local_grads(p, t, l):
        return jax.grad(lambda p: model.loss(p, t, l))(p)

    g = shard_map(local_grads, mesh=mesh, in_specs=(specs, P(), P()),
                  out_specs=specs, check_vma=False)(params, tokens, labels)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    # every stage's blocks received nonzero gradient
    bl = np.asarray(g["blocks"]["qkv"]["weight"])  # (pp, 1, lps, H, 3H/tp)
    for s in range(2):
        assert np.abs(bl[s]).max() > 0
