"""The runtime timeline observatory (ISSUE 15): Chrome-trace parsing,
measured step anatomy, device-idle & overlap verdicts, the comms
crosscheck, the v11 schema stamps, and the `timeline_probe.py` /
example CLI gates.

The math tests run on HAND-AUTHORED trace-event fixtures (TPU-style
process names, exact microsecond spans) so the pinned numbers are
derivable by eye; the CLI gates execute the real capture → parse →
verdict loop on the flagship build paths.
"""

import gzip
import json
import os
import pathlib
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu import monitor  # noqa: E402
from apex_tpu.monitor import timeline  # noqa: E402

ROOT = pathlib.Path(__file__).resolve().parent.parent


# --------------------- hand-authored trace fixtures ---------------------

def _meta_tpu():
    """TPU-style process/thread metadata: one device pid with two op
    lanes, one host pid."""
    return [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 11, "name": "thread_name",
         "args": {"name": "XLA Ops #2"}},
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/host:CPU"}},
    ]


def _step(i, t0, wall=1000.0):
    return {"ph": "X", "pid": 9, "tid": 1, "name": "train-step",
            "ts": t0, "dur": wall, "args": {"step_num": str(i)}}


def _op(name, ts, dur, tid=10, pid=1, hlo=True):
    e = {"ph": "X", "pid": pid, "tid": tid, "name": name,
         "ts": ts, "dur": dur}
    if hlo:
        e["args"] = {"hlo_op": name, "hlo_module": "jit_step"}
    return e


def test_parse_trace_shapes_and_bad_rows():
    """String step_nums coerce to int, metadata fills the name maps,
    and a malformed row costs the EVENT, never the parse."""
    obj = {"traceEvents": _meta_tpu() + [
        _step(0, 0.0),
        _op("dot.1", 10.0, 50.0),
        {"ph": "X", "pid": "garbage", "tid": [], "name": "x"},
        {"ph": "B", "pid": 1, "name": "ignored-begin"},
        "not even a dict",
    ]}
    tr = timeline.parse_trace(obj)
    assert len(tr.events) == 2
    assert tr.process_names[1] == "/device:TPU:0"
    assert tr.thread_names[(1, 10)] == "XLA Ops"
    assert tr.events[0].step_num == 0
    assert tr.events[1].hlo_op == "dot.1"


def test_overlap_fraction_pinned_overlapped_vs_serialized():
    """The headline number: a 200 us collective with 100 us of
    concurrent device compute measures overlap_fraction == 0.5
    EXACTLY; a collective whose span holds no compute measures 0.0
    and — above the duration floor — is flagged serialized, flipping
    measured_overlap_ok."""
    ev = _meta_tpu() + [
        _step(0, 0.0),
        _op("all-reduce.1", 100.0, 200.0, tid=11),
        # concurrent compute on the other lane: covers [150, 250]
        _op("dot.1", 150.0, 100.0, tid=10),
        # serialized reduce-scatter: 150 us, nothing concurrent
        _op("reduce-scatter.2", 500.0, 150.0, tid=11),
        _op("fusion.3", 700.0, 100.0, tid=10),
    ]
    rep = timeline.analyze_trace({"traceEvents": ev})
    assert rep.device_type == "tpu" and rep.overlap_measurable
    by_name = {c.name: c for c in rep.collectives}
    ar = by_name["all-reduce.1"]
    assert ar.overlap_fraction == pytest.approx(0.5)
    assert not ar.serialized
    rs = by_name["reduce-scatter.2"]
    assert rs.overlap_fraction == 0.0
    assert rs.serialized  # 0.15 ms >= SERIALIZED_FLOOR_MS
    assert rep.measured_overlap_ok is False
    assert "MEASURED-SERIALIZED" in timeline.render_timeline_table(rep)
    # drop the serialized one -> the verdict goes green
    rep2 = timeline.analyze_trace(
        {"traceEvents": [e for e in ev
                         if e.get("name") != "reduce-scatter.2"]})
    assert rep2.measured_overlap_ok is True
    # a sub-floor serialized collective is latency noise, not flagged
    ev3 = [dict(e) for e in ev if e.get("name") != "reduce-scatter.2"]
    ev3.append(_op("reduce-scatter.9", 500.0, 20.0, tid=11))  # 0.02 ms
    rep3 = timeline.analyze_trace({"traceEvents": ev3})
    assert rep3.measured_overlap_ok is True


def test_host_gap_math_and_gapped_steps():
    """Gapped steps: wall − device-busy union == host gap, per step;
    overlapping device events never double-count in the union."""
    ev = _meta_tpu() + [
        _step(0, 0.0, wall=1000.0),
        _op("dot.1", 100.0, 250.0),
        _op("fusion.2", 600.0, 150.0),
        # step 1: two OVERLAPPING events [0+2000,100+2000] and
        # [2050, 2150] -> union 150 us busy, not 200
        _step(1, 2000.0, wall=1000.0),
        _op("dot.1", 2000.0, 100.0, tid=10),
        _op("fusion.2", 2050.0, 100.0, tid=11),
        # step 2: pure host stall, zero device events
        _step(2, 4000.0, wall=1000.0),
    ]
    rep = timeline.analyze_trace({"traceEvents": ev})
    s0, s1, s2 = rep.steps
    assert s0.device_busy_ms == pytest.approx(0.4)
    assert s0.device_busy_fraction == pytest.approx(0.4)
    assert s0.host_gap_ms == pytest.approx(0.6)
    assert s1.device_busy_ms == pytest.approx(0.15)  # union, merged
    assert s2.device_busy_ms == 0.0
    assert s2.host_gap_ms == pytest.approx(1.0)
    # aggregate busy = total busy / total wall
    assert rep.device_busy_fraction == pytest.approx(0.55 / 3.0)
    assert sum(rep.category_fractions.values()) == pytest.approx(1.0)
    # idle verdict fires below the floor, by name
    assert "DEVICE IDLE" in timeline.render_timeline_table(rep)


def test_device_pid_non_op_lanes_never_double_count():
    """TPU converters mirror the same wall time onto several device
    lanes ("XLA Modules" whole-module spans, "Steps", name-scope
    hierarchies) — only the "XLA Ops" lanes may feed the busy union,
    or every step reads ~100% busy regardless of reality."""
    ev = _meta_tpu() + [
        {"ph": "M", "pid": 1, "tid": 99, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        _step(0, 0.0, wall=1000.0),
        _op("dot.1", 100.0, 300.0, tid=10),
        # a module-level span covering the WHOLE step on a non-op lane
        {"ph": "X", "pid": 1, "tid": 99, "name": "jit_step",
         "ts": 0.0, "dur": 1000.0},
    ]
    rep = timeline.analyze_trace({"traceEvents": ev})
    assert rep.steps[0].device_busy_fraction == pytest.approx(0.3)
    assert rep.n_device_events == 1


def test_multi_device_pids_judged_per_device():
    """Review fix: on a multi-chip trace, device A's compute must
    never count as 'concurrent' with device B's collective (the
    serialized-TP condition ROADMAP 2 wants convicted would read
    green), and one busy device must not mask another's idle — busy
    is the per-device MEAN."""
    ev = _meta_tpu() + [
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "pid": 2, "tid": 20, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        _step(0, 0.0, wall=1000.0),
        # device 0: serialized all-reduce [100, 300], nothing else
        _op("all-reduce.1", 100.0, 200.0, pid=1, tid=10),
        # device 1: skewed gemm overlapping that wall-time span
        _op("dot.1", 150.0, 400.0, pid=2, tid=20),
        _op("all-reduce.1", 600.0, 200.0, pid=2, tid=20),
    ]
    rep = timeline.analyze_trace({"traceEvents": ev})
    ar = next(c for c in rep.collectives if c.name == "all-reduce.1")
    # both occurrences serialized ON THEIR OWN DEVICE: zero, not the
    # cross-device illusion
    assert ar.overlap_fraction == 0.0 and ar.serialized
    assert rep.measured_overlap_ok is False
    # busy: device 0 busy 200us, device 1 busy 600us -> mean 400us
    assert rep.steps[0].device_busy_ms == pytest.approx(0.4)
    assert rep.steps[0].host_gap_ms == pytest.approx(0.6)


def test_parse_malformed_metadata_row_costs_row_not_trace():
    obj = {"traceEvents": [
        {"ph": "M", "pid": "dev0", "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        _op("dot.1", 0.0, 10.0, pid=1, tid=2),
    ]}
    tr = timeline.parse_trace(obj)  # must not raise
    assert tr.process_names == {1: "/host:CPU"}
    assert len(tr.events) == 1


def test_crosscheck_name_match_wins_over_ordinal_fallback():
    """Review fix: an unmatched collective's kind-ordinal fallback
    must not steal the span a LATER collective matches by name —
    two rows judged against one measurement corrupts the table."""
    comms = _comms_dict([
        _cc("all-reduce.77", "all-reduce", overlap=0.9, expected=True),
        _cc("all-reduce.3", "all-reduce", overlap=0.9, expected=True),
    ])
    tl = _timeline_with([_span("all-reduce.3", "all-reduce", 0.95)])
    res = timeline.crosscheck_comms(tl, comms)
    by = {r["name"]: r for r in res["rows"]}
    assert by["all-reduce.3"]["verdict"] == "AGREE"
    assert by["all-reduce.3"]["measured_overlap_fraction"] == 0.95
    assert by["all-reduce.77"]["verdict"] == "UNMEASURED"


def test_classify_op_shared_heuristics():
    """Category heuristics share the comms parser's COLLECTIVE_KINDS
    spelling — the same op means the same thing in both planes."""
    assert timeline.classify_op("all-reduce.3") == "collective"
    assert timeline.classify_op("all-reduce-start.1") == "collective"
    assert timeline.classify_op("reduce-scatter.5") == "collective"
    assert timeline.classify_op("all-to-all") == "collective"
    assert timeline.classify_op("collective-permute.2") == "collective"
    assert timeline.classify_op("dot.7") == "gemm"
    assert timeline.classify_op("convolution.1") == "gemm"
    assert timeline.classify_op("fusion.9", "fusion.9") == "other"
    assert timeline.classify_op("fusion.2",
                                "fusion.2.matmul") == "gemm"
    assert timeline.classify_op("infeed.1") == "infeed_outfeed"
    assert timeline.classify_op("outfeed") == "infeed_outfeed"
    assert timeline.classify_op("reduce.8") == "other"
    # a dtype cast is NOT a convolution — the "conv" prefix must not
    # swallow convert ops into the gemm category (review fix)
    assert timeline.classify_op("convert.5") == "other"
    assert timeline.classify_op("convert") == "other"
    assert timeline.classify_op("convolution.1") == "gemm"
    # display name may be shortened; hlo_op wins
    assert timeline.classify_op("Eigen::matmul",
                                "all-gather.2") == "collective"


def test_cpu_trace_overlap_unmeasurable_never_faked():
    """A CPU-style trace (no /device: pids; hlo_op-tagged thunk events
    incl. a sync all-reduce): the anatomy is fully measured but the
    overlap plane is UNMEASURABLE — fraction None, verdict None, and
    the v11 record does NOT carry timeline_measured_overlap_ok."""
    ev = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        _step(0, 0.0),
        _op("dot.1", 100.0, 300.0, pid=7, tid=2),
        _op("all-reduce.1", 450.0, 200.0, pid=7, tid=2),
    ]
    rep = timeline.analyze_trace({"traceEvents": ev})
    assert rep.device_type == "cpu"
    assert rep.overlap_measurable is False
    assert rep.measured_overlap_ok is None
    assert rep.n_device_events == 2
    assert all(c.overlap_fraction is None for c in rep.collectives)
    assert not any(c.serialized for c in rep.collectives)
    rec = rep.timeline_record()
    assert "timeline_measured_overlap_ok" not in rec
    assert rec["timeline_collective_fraction"] == pytest.approx(0.4)
    assert "UNMEASURABLE" in timeline.render_timeline_table(rep)


def test_malformed_trace_named_error(tmp_path):
    """Truncated/corrupt traces raise TraceParseError — named, never a
    bare gzip/json crash escaping into the analysis pipeline."""
    good = tmp_path / "t.trace.json.gz"
    payload = json.dumps(
        {"traceEvents": _meta_tpu() + [_step(0, 0.0)]}).encode()
    good.write_bytes(gzip.compress(payload))
    timeline.analyze_trace(str(good))  # sanity: the intact file parses

    truncated = tmp_path / "cut.trace.json.gz"
    truncated.write_bytes(gzip.compress(payload)[:40])
    with pytest.raises(timeline.TraceParseError, match="cannot parse"):
        timeline.analyze_trace(str(truncated))
    garbage = tmp_path / "garbage.trace.json"
    garbage.write_text("{not json")
    with pytest.raises(timeline.TraceParseError):
        timeline.analyze_trace(str(garbage))
    notdict = tmp_path / "list.trace.json"
    notdict.write_text("[1, 2]")
    with pytest.raises(timeline.TraceParseError, match="trace-event"):
        timeline.analyze_trace(str(notdict))
    with pytest.raises(timeline.TraceParseError, match="traceEvents"):
        timeline.analyze_trace({"no": "events"})
    # the no-capture path: trace_path() None composes to a named error
    with pytest.raises(timeline.TraceParseError, match="no trace"):
        timeline.analyze_trace(None)
    # TraceParseError IS a ValueError (catchable at the schema layer)
    assert issubclass(timeline.TraceParseError, ValueError)


# --------------------------- comms crosscheck ---------------------------

def _comms_dict(collectives):
    """A minimal CommsReport-shaped dict for crosscheck input."""
    return {"collectives": collectives}


def _cc(name, kind, *, group_size=2, overlap=None, expected=False):
    return {"name": name, "kind": kind, "group_size": group_size,
            "overlap_fraction": overlap, "expected_overlap": expected}


def _timeline_with(spans):
    return {"collectives": spans, "overlap_measurable": True}


def _span(name, kind, frac, total_ms=1.0):
    return {"name": name, "kind": kind, "overlap_fraction": frac,
            "total_ms": total_ms, "n_events": 3,
            "concurrent_compute_ms": 0.0, "serialized": frac == 0.0}


def test_crosscheck_agreement_divergence_and_fallbacks():
    comms = _comms_dict([
        # exact-name agree
        _cc("all-reduce.3", "all-reduce", overlap=0.9, expected=True),
        # -start spelling strips to the trace's op name
        _cc("reduce-scatter-start.5", "reduce-scatter", overlap=0.8,
            expected=True),
        # kind-ordinal fallback (no name match)
        _cc("all-gather.99", "all-gather", overlap=0.7, expected=True),
        # sync on the AOT side, measured in the trace
        _cc("all-reduce.8", "all-reduce", overlap=None),
        # degenerate: not counted, no row
        _cc("all-reduce.0", "all-reduce", group_size=1, overlap=0.5),
    ])
    tl = _timeline_with([
        _span("all-reduce.3", "all-reduce", 0.95),
        _span("reduce-scatter.5", "reduce-scatter", 0.1),
        _span("all-gather.7", "all-gather", 0.75),
        _span("all-reduce.8", "all-reduce", 0.3),
    ])
    res = timeline.crosscheck_comms(tl, comms)
    assert len(res["rows"]) == 4  # degenerate skipped
    by = {r["name"]: r for r in res["rows"]}
    assert by["all-reduce.3"]["verdict"] == "AGREE"
    assert by["all-reduce.3"]["measured_overlap_fraction"] == 0.95
    # |0.8 - 0.1| > 0.25 — the AOT model and the schedule disagree
    assert by["reduce-scatter-start.5"]["verdict"] == "DIVERGES"
    assert by["all-gather.99"]["verdict"] == "AGREE"  # ordinal match
    assert by["all-gather.99"]["measured_overlap_fraction"] == 0.75
    assert by["all-reduce.8"]["verdict"] == "MEASURED-ONLY"
    assert res["n_expected_overlap"] == 3
    assert res["n_diverge"] == 1 and res["ok"] is False
    # every expected-overlap collective got a row — the acceptance
    # contract the probe asserts on the dp ZeRO-2 step
    assert all(any(r["name"] == c["name"] for r in res["rows"])
               for c in comms["collectives"] if c["expected_overlap"])
    text = timeline.render_crosscheck(res, label="t")
    assert "DIVERGES" in text and "AGREE" in text
    # an UNMEASURABLE timeline (CPU) is honest, not green-washed: rows
    # exist, measured side None, ok stays True (nothing DIVERGED)
    tl_cpu = {"collectives": [
        dict(s, overlap_fraction=None) for s in tl["collectives"]],
        "overlap_measurable": False}
    res2 = timeline.crosscheck_comms(tl_cpu, comms)
    assert len(res2["rows"]) == 4
    assert all(r["verdict"] == "UNMEASURED" for r in res2["rows"])
    assert res2["ok"] is True and res2["n_unmeasured"] == 4


def test_crosscheck_prefix_groups_beat_kind_ordinals_on_chunked():
    """ISSUE 18 regression: a chunked program spells one logical
    collective as chunk-count-many same-kind instructions
    ("all-gather-start.{1,2}") next to an unrelated SYNC same-kind
    collective.  When the trace renumbers instances (no exact-name
    match), raw kind-ordinal pairing judges the first overlapped
    chunk against the sync collective's 0%-overlap span — a spurious
    DIVERGES on both rows.  Name-prefix pools (".N" stripped,
    "-start" kept) keep chunk spans with their own logical
    collective."""
    comms = _comms_dict([
        _cc("all-gather-start.1", "all-gather", overlap=0.9,
            expected=True),
        _cc("all-gather-start.2", "all-gather", overlap=0.9,
            expected=True),
        _cc("all-gather.9", "all-gather", overlap=0.0),
    ])
    # trace order puts the sync span FIRST — the ordinal trap
    tl = _timeline_with([
        _span("all-gather.3", "all-gather", 0.0),
        _span("all-gather-start.4", "all-gather", 0.92),
        _span("all-gather-start.5", "all-gather", 0.88),
    ])
    res = timeline.crosscheck_comms(tl, comms)
    by = {r["name"]: r for r in res["rows"]}
    assert by["all-gather-start.1"]["measured_overlap_fraction"] == 0.92
    assert by["all-gather-start.2"]["measured_overlap_fraction"] == 0.88
    assert by["all-gather.9"]["measured_overlap_fraction"] == 0.0
    assert all(r["verdict"] == "AGREE" for r in res["rows"])
    assert res["ok"] is True and res["n_diverge"] == 0


def test_crosscheck_prefix_fallback_strips_start_spelling():
    """A trace that records async ops under their BASE name still
    pools with the comms side's "-start" spelling (the pass-1
    tolerance, extended to renumbered instances)."""
    comms = _comms_dict([
        _cc("reduce-scatter-start.1", "reduce-scatter", overlap=0.8,
            expected=True),
        _cc("reduce-scatter-start.2", "reduce-scatter", overlap=0.8,
            expected=True),
    ])
    tl = _timeline_with([
        _span("reduce-scatter.6", "reduce-scatter", 0.85),
        _span("reduce-scatter.7", "reduce-scatter", 0.75),
    ])
    res = timeline.crosscheck_comms(tl, comms)
    by = {r["name"]: r for r in res["rows"]}
    assert by["reduce-scatter-start.1"]["measured_overlap_fraction"] \
        == 0.85
    assert by["reduce-scatter-start.2"]["measured_overlap_fraction"] \
        == 0.75
    assert all(r["verdict"] == "AGREE" for r in res["rows"])


# ------------------------------ v11 schema ------------------------------

def _base_record():
    return {"monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
            "loss": 1.0, "grad_norm": 1.0, "param_norm": 1.0,
            "update_norm": 0.1, "loss_scale": 1.0, "overflow_count": 0,
            "skipped_steps": 0, "tokens_seen": 10.0,
            "step_time_ms": 1.0, "tokens_per_sec": 10.0, "mfu": 0.1}


def test_v11_timeline_stamp_validation():
    """SCHEMA v10->v11: the timeline_* optional fields are
    never-null-when-present, the overlap verdict is bool-typed, and
    the reserved-prefix scalar rule covers unknown timeline_ keys."""
    assert monitor.SCHEMA_VERSION >= 11
    base = _base_record()
    good = dict(base, timeline_device_busy_fraction=0.87,
                timeline_host_gap_ms=0.4,
                timeline_collective_fraction=0.09,
                timeline_measured_overlap_ok=True)
    monitor.validate_record(good)
    monitor.validate_record(json.loads(json.dumps(good)))
    # the verdict may be absent (CPU capture) but never null
    monitor.validate_record(dict(base,
                                 timeline_device_busy_fraction=0.5,
                                 timeline_host_gap_ms=1.0,
                                 timeline_collective_fraction=0.0))
    with pytest.raises(ValueError, match="timeline_measured_overlap_ok"):
        monitor.validate_record(
            dict(good, timeline_measured_overlap_ok=None))
    with pytest.raises(ValueError, match="timeline_device_busy_fraction"):
        monitor.validate_record(
            dict(good, timeline_device_busy_fraction=None))
    with pytest.raises(ValueError, match="timeline_measured_overlap_ok"):
        monitor.validate_record(
            dict(good, timeline_measured_overlap_ok=1.0))
    # prefix rule: unknown timeline_ keys must be JSON scalars
    monitor.validate_record(dict(good, timeline_note="ok"))
    with pytest.raises(ValueError, match="scalar"):
        monitor.validate_record(dict(good, timeline_note={"no": 1}))


def test_logger_stamps_timeline_record():
    """MetricsLogger(timeline=report) folds the v11 stamps into every
    record — and the report is late-assignable, the natural order for
    a capture that closes mid-run."""
    rep = timeline.analyze_trace({"traceEvents": _meta_tpu() + [
        _step(0, 0.0),
        _op("dot.1", 100.0, 600.0),
        _op("all-reduce.1", 200.0, 100.0, tid=11),
    ]})
    logger = monitor.MetricsLogger([], timeline=rep)
    rec = logger.log_step(monitor.init_metrics())
    assert rec["timeline_device_busy_fraction"] == pytest.approx(
        rep.device_busy_fraction)
    assert rec["timeline_measured_overlap_ok"] is True  # TPU-style
    late = monitor.MetricsLogger([])
    assert "timeline_host_gap_ms" not in late.log_step(
        monitor.init_metrics())
    late.timeline = rep
    assert "timeline_host_gap_ms" in late.log_step(
        monitor.init_metrics())


def test_schema_roundtrip_and_drift_detected():
    rep = timeline.analyze_trace({"traceEvents": _meta_tpu() + [
        _step(0, 0.0), _op("dot.1", 10.0, 100.0),
        _op("all-reduce.1", 200.0, 100.0, tid=11),
    ]})
    d = json.loads(json.dumps(rep.to_dict()))
    timeline.validate_timeline_report(d)
    with pytest.raises(ValueError, match="timeline_schema_version"):
        timeline.validate_timeline_report(
            dict(d, timeline_schema_version=99))
    with pytest.raises(ValueError, match="device_busy_fraction"):
        timeline.validate_timeline_report(
            {k: v for k, v in d.items() if k != "device_busy_fraction"})
    broken = json.loads(json.dumps(d))
    broken["collectives"][0]["kind"] = "psum"
    with pytest.raises(ValueError, match="unknown kind"):
        timeline.validate_timeline_report(broken)
    # the sum-to-~1 attribution contract is schema-enforced
    broken2 = json.loads(json.dumps(d))
    broken2["category_fractions"]["gemm"] += 0.5
    with pytest.raises(ValueError, match="sum"):
        timeline.validate_timeline_report(broken2)


# ----------------------------- CLI gates -----------------------------

def _run_script(path, *args, timeout=600, env_extra=None):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout, env=env)


def test_timeline_probe_selftest():
    """Tier-1 CI gate: the committed fixture validates + renders with
    its seeded MEASURED-SERIALIZED collective flagged, and the seeded
    idle-heavy trace trips the DEVICE IDLE verdict BY NAME."""
    r = _run_script(ROOT / "scripts" / "timeline_probe.py",
                    "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "timeline_probe --selftest: OK" in r.stdout
    assert "flagged DEVICE IDLE — OK" in r.stdout


def test_timeline_probe_flagship_cli():
    """Acceptance: the full probe passes on the flagship targets from
    tier-1 — structure asserts green on CPU (device events present,
    step count matches the window, fractions sum to ~1, schema
    round-trips), overlap honestly UNMEASURABLE, and crosscheck_comms
    rows cover every counted collective of the dp ZeRO-2 step."""
    r = _run_script(ROOT / "scripts" / "timeline_probe.py", "--json",
                    "gpt", "gpt_zero2")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    reports = [json.loads(l) for l in r.stdout.splitlines()
               if l.startswith("{")]
    assert {x["target"] for x in reports} == {"gpt", "gpt_zero2"}
    for x in reports:
        assert x["ok"], x["target"]
        rep = x["report"]
        assert rep["n_device_events"] > 0
        assert len(rep["steps"]) == 3
        assert sum(rep["category_fractions"].values()) == \
            pytest.approx(1.0)
        assert rep["overlap_measurable"] is False  # CPU: honest
        assert rep["measured_overlap_ok"] is None
        timeline.validate_timeline_report(rep)
    zero2 = next(x for x in reports if x["target"] == "gpt_zero2")
    xc = zero2["crosscheck"]
    assert xc is not None and xc["ok"]
    # a row for every counted collective — the per-bucket
    # reduce-scatters of the ZeRO-2 step included
    kinds = [r["kind"] for r in xc["rows"]]
    assert kinds.count("reduce-scatter") >= 4
    assert all(r["verdict"] == "UNMEASURED" for r in xc["rows"])


@pytest.mark.slow
def test_timeline_probe_tp_overlap_target():
    """ISSUE 18 acceptance: the measured probe passes on the
    chunked-TP flagship — structure asserts green, overlap honestly
    UNMEASURABLE on CPU while the crosscheck carries a row for every
    counted collective, the chunk-count-many ring ppermutes
    included."""
    r = _run_script(ROOT / "scripts" / "timeline_probe.py", "--json",
                    "gpt_tp_overlap")
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    reports = [json.loads(l) for l in r.stdout.splitlines()
               if l.startswith("{")]
    x = next(x for x in reports if x["target"] == "gpt_tp_overlap")
    assert x["ok"]
    assert x["report"]["overlap_measurable"] is False  # CPU: honest
    xc = x["crosscheck"]
    assert xc is not None and xc["ok"]
    kinds = [row["kind"] for row in xc["rows"]]
    # 2 rings x 2L col sites x (p-1) hops x chunks on the smoke config
    assert kinds.count("collective-permute") == 16
    assert all(row["verdict"] == "UNMEASURED" for row in xc["rows"])


def test_train_with_monitor_profile_steps(tmp_path):
    """ISSUE 15 satellite gate: the example's --profile-steps A:B path
    captures, parses, prints the timeline table, and stamps the v11
    timeline_* fields into the JSONL records logged after the window
    closed — on CPU, like the --flight-report path."""
    jsonl = tmp_path / "m.jsonl"
    r = _run_script(ROOT / "examples" / "train_with_monitor.py",
                    "--steps", "5", "--profile-steps", "1:4",
                    "--jsonl", str(jsonl), "--force-cpu-devices", "1")
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "=== timeline: steps 1:4 ===" in r.stdout
    assert "UNMEASURABLE" in r.stdout  # CPU honesty, printed
    recs = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    stamped = [x for x in recs
               if "timeline_device_busy_fraction" in x]
    assert stamped, "no record carries the v11 timeline stamps"
    monitor.validate_records([x for x in recs if "loss" in x])
    assert all("timeline_measured_overlap_ok" not in x
               for x in stamped)  # CPU: absent, never null
    # bad window spelling is a usage error, not a crash
    r2 = _run_script(ROOT / "examples" / "train_with_monitor.py",
                     "--steps", "2", "--profile-steps", "nope",
                     "--force-cpu-devices", "1")
    assert r2.returncode != 0
    assert "A:B" in (r2.stderr + r2.stdout)
