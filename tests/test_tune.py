"""Kernel autotuner (apex_tpu.tune) + head-packed flash attention.

ISSUE 3 coverage: cache round-trip, corrupt/missing cache → heuristic
fallback (deterministically), device-kind isolation, empty-cache
byte-identity, and head-packed flash parity vs the unpacked kernel
(bitwise) and the fp64 oracle across causal × bias × segment ids."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import tune
from apex_tpu.ops.flash_attention import (
    attention_reference,
    flash_attention,
)


@pytest.fixture
def tmp_cache(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    monkeypatch.setenv(tune.ENV_CACHE_PATH, str(path))
    tune.invalidate()
    tune.reset_stats()
    yield path
    tune.invalidate()


# ------------------------------- cache layer --------------------------------

def test_cache_roundtrip(tmp_cache):
    attrs = dict(b=2, h=4, sq=64, sk=64, d=16, dtype="float32",
                 causal=True, bias="none", seg=False)
    cfg = {"block_q": 32, "block_k": 32, "heads_per_step": 2}
    tune.record("flash_sdpa", attrs, cfg, meta={"ms": 1.0})
    # reload from disk (invalidate drops the memo)
    tune.invalidate()
    got = tune.tuned("flash_sdpa", attrs)
    assert got == cfg
    # the file itself is schema-stamped
    raw = json.loads(tmp_cache.read_text())
    assert raw["schema"] == tune.SCHEMA_VERSION
    assert tune.device_kind() in raw["entries"]


def test_missing_cache_is_deterministic_miss(tmp_cache):
    tune.reset_stats()
    assert tune.tuned("flash_sdpa", dict(b=1)) is None
    assert tune.tuned("flash_sdpa", dict(b=1)) is None
    s = tune.stats()
    assert s["hits"] == 0 and s["misses"] == 2


def test_corrupt_cache_falls_back(tmp_cache):
    tmp_cache.write_text("{ not json !!!")
    tune.invalidate()
    with pytest.warns(UserWarning, match="corrupt"):
        assert tune.tuned("flash_sdpa", dict(b=1)) is None
    # and a wrong-schema file is likewise ignored
    tmp_cache.write_text(json.dumps({"schema": 999, "entries": {}}))
    tune.invalidate()
    assert tune.tuned("flash_sdpa", dict(b=1)) is None


def test_device_kind_mismatch_ignored(tmp_cache):
    attrs = dict(rows=1024, hidden=128)
    tune.record("softmax_fwd", attrs, {"block_rows": 64}, kind="v5e")
    tune.invalidate()
    # current kind is "cpu" on the test host — the v5e entry must not
    # leak across device kinds
    assert tune.device_kind() != "v5e"
    assert tune.tuned("softmax_fwd", attrs) is None
    tune.record("softmax_fwd", attrs, {"block_rows": 64})
    tune.invalidate()
    assert tune.tuned("softmax_fwd", attrs) == {"block_rows": 64}


def test_disable_env(tmp_cache, monkeypatch):
    attrs = dict(rows=8, hidden=8)
    tune.record("softmax_fwd", attrs, {"block_rows": 8})
    monkeypatch.setenv(tune.ENV_DISABLE, "0")
    assert tune.tuned("softmax_fwd", attrs) is None
    monkeypatch.delenv(tune.ENV_DISABLE)
    assert tune.tuned("softmax_fwd", attrs) == {"block_rows": 8}


def test_fingerprint_tracks_content(tmp_cache):
    fp0 = tune.fingerprint()
    tune.record("opt_flat", dict(kernel="adam", rows=1024),
                {"block_rows": 256})
    fp1 = tune.fingerprint()
    assert fp0 != fp1
    assert tune.stats()["fingerprint"] == fp1


# ----------------------- empty-cache byte-identity --------------------------

def _qkv(b, h, s, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


def test_empty_cache_matches_explicit_heuristics(tmp_cache):
    """With no cache entry, the tuner-consulting default path must be
    byte-identical to the pre-tuner heuristics."""
    q, k, v = _qkv(1, 2, 64, 16)
    auto = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    explicit = flash_attention(q, k, v, causal=True,
                               use_pallas_override=True,
                               block_q=64, block_k=64, heads_per_step=1)
    assert np.array_equal(np.asarray(auto), np.asarray(explicit))


def test_tuned_flash_entry_is_picked_up(tmp_cache):
    """A recorded entry for the current (cpu) kind drives the default
    path — observable via the hit counter — and stays correct."""
    b, h, s, d = 1, 4, 64, 16
    q, k, v = _qkv(b, h, s, d)
    attrs = dict(b=b, h=h, sq=s, sk=s, d=d, dtype="float32",
                 causal=True, bias="none", seg=False)
    tune.record("flash_sdpa", attrs,
                {"block_q": 32, "block_k": 32, "heads_per_step": 2})
    tune.reset_stats()
    out = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    assert tune.stats()["hits"] >= 1
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_tuned_invalid_heads_per_step_degrades(tmp_cache):
    """A stale tuned hp that doesn't divide the head count must degrade
    to the unpacked kernel (warn once), not fail."""
    b, h, s, d = 1, 3, 64, 16
    q, k, v = _qkv(b, h, s, d, seed=5)
    attrs = dict(b=b, h=h, sq=s, sk=s, d=d, dtype="float32",
                 causal=False, bias="none", seg=False)
    tune.record("flash_sdpa", attrs,
                {"block_q": 64, "block_k": 64, "heads_per_step": 4})
    with pytest.warns(UserWarning, match="heads_per_step"):
        out = flash_attention(q, k, v, use_pallas_override=True)
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


# ----------------------- head-packed flash attention ------------------------

def _oracle64(q, k, v, **kw):
    """TRUE fp64 reference (the satellite's oracle) — the conftest
    disables x64 globally, so the cast must run under enable_x64 or it
    silently truncates to fp32."""
    from jax.experimental import enable_x64

    with enable_x64():
        out = attention_reference(q.astype(jnp.float64),
                                  k.astype(jnp.float64),
                                  v.astype(jnp.float64), **kw)
        return np.asarray(out, np.float64)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bias_kind", ["none", "sk", "full"])
@pytest.mark.parametrize("seg", [False, True])
def test_packed_matches_unpacked_and_oracle(causal, bias_kind, seg,
                                            tmp_cache):
    b, h, s, d = 2, 4, 64, 16
    q, k, v = _qkv(b, h, s, d, seed=7)
    ks = jax.random.split(jax.random.PRNGKey(11), 2)
    bias = None
    if bias_kind == "sk":
        bias = jax.random.normal(ks[0], (b, 1, 1, s))
    elif bias_kind == "full":
        bias = jax.random.normal(ks[0], (b, h, s, s))
    seg_ids = None
    if seg:
        seg_ids = (jnp.arange(s)[None, :] < s // 2).astype(
            jnp.int32) * jnp.ones((b, 1), jnp.int32)

    kw = dict(causal=causal, bias=bias, segment_ids=seg_ids,
              use_pallas_override=True, block_q=32, block_k=32)
    un = flash_attention(q, k, v, heads_per_step=1, **kw)
    pk = flash_attention(q, k, v, heads_per_step=2, **kw)
    # bit parity at identical blocks (acceptance criterion)
    assert np.array_equal(np.asarray(un), np.asarray(pk)), (
        "packed forward is not bit-identical to unpacked")
    want = _oracle64(q, k, v, causal=causal, bias=bias,
                     q_segment_ids=seg_ids, kv_segment_ids=seg_ids)
    assert np.abs(np.asarray(pk, np.float64) - want).max() < 1e-5

    # grads: packed vs unpacked bitwise, packed vs fp64 oracle loose
    def loss(f, hp):
        def inner(q, k, v):
            return jnp.sum(jnp.sin(f(q, k, v, heads_per_step=hp, **kw)))
        return inner

    g_un = jax.grad(loss(flash_attention, 1), argnums=(0, 1, 2))(q, k, v)
    g_pk = jax.grad(loss(flash_attention, 2), argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g_pk, g_un, "qkv"):
        assert np.array_equal(np.asarray(a), np.asarray(e)), (
            f"packed d{name} not bit-identical to unpacked")

    # oracle-grad cross-check on the simplest and the fullest combo
    # only (the bitwise identity above covers the rest; the unpacked
    # kernel's own oracle parity lives in test_flash_attention.py)
    if (causal, bias_kind, seg) in ((False, "none", False),
                                    (True, "full", True)):
        from jax.experimental import enable_x64

        with enable_x64():
            def loss64(q, k, v):
                out = attention_reference(q, k, v, causal=causal,
                                          bias=None if bias is None
                                          else bias.astype(jnp.float64),
                                          q_segment_ids=seg_ids,
                                          kv_segment_ids=seg_ids)
                return jnp.sum(jnp.sin(out))

            g_or = jax.grad(loss64, argnums=(0, 1, 2))(
                q.astype(jnp.float64), k.astype(jnp.float64),
                v.astype(jnp.float64))
            g_or = [np.asarray(g, np.float64) for g in g_or]
        for a, e, name in zip(g_pk, g_or, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a, np.float64), np.asarray(e),
                rtol=1e-4, atol=1e-4,
                err_msg=f"packed d{name} vs oracle")


def test_packed_bf16_vs_oracle(tmp_cache):
    """bf16 packed kernel ≤ 1e-2 max-abs vs the fp64 oracle (acceptance
    criterion tolerance)."""
    q, k, v = _qkv(1, 4, 128, 32, dtype=jnp.bfloat16, seed=9)
    pk = flash_attention(q, k, v, causal=True, use_pallas_override=True,
                         heads_per_step=4, block_q=64, block_k=64)
    want = _oracle64(q, k, v, causal=True)
    assert np.abs(np.asarray(pk, np.float64) - want).max() < 1e-2


def test_packed_dropout_bitwise(tmp_cache):
    """The in-kernel counter dropout hashes the FLAT batch*head index —
    packing must regenerate the identical mask."""
    q, k, v = _qkv(2, 4, 64, 16, seed=13)
    key = jax.random.PRNGKey(42)
    kw = dict(causal=True, dropout_rate=0.3, dropout_key=key,
              use_pallas_override=True, block_q=32, block_k=32)
    un = flash_attention(q, k, v, heads_per_step=1, **kw)
    pk = flash_attention(q, k, v, heads_per_step=2, **kw)
    assert np.array_equal(np.asarray(un), np.asarray(pk))


def test_packed_long_context_bwd_fallback(monkeypatch, tmp_cache):
    """When the packed (hp, sk, d) scratch exceeds the packed cap the
    backward silently drops to the unpacked kernels — same grads."""
    import apex_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "_FUSED_BWD_CAP_PACKED", 16)  # force
    q, k, v = _qkv(1, 2, 64, 16, seed=17)

    def loss(hp):
        def inner(q, k, v):
            return jnp.sum(jnp.sin(fa.flash_attention(
                q, k, v, causal=True, use_pallas_override=True,
                heads_per_step=hp, block_q=32, block_k=32)))
        return inner

    g1 = jax.grad(loss(1), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss(2), argnums=(0, 1, 2))(q, k, v)
    for a, e in zip(g2, g1):
        assert np.array_equal(np.asarray(a), np.asarray(e))


def test_block_fallback_warns_once_and_matches(tmp_cache):
    """Non-dividing tuned/explicit blocks degrade to the largest
    dividing block with a single warning (ISSUE 3 satellite)."""
    import apex_tpu.ops.flash_attention as fa

    fa._BLOCK_FALLBACK_WARNED.clear()
    q, k, v = _qkv(1, 2, 96, 16, seed=19)
    with pytest.warns(UserWarning, match="does not divide"):
        out = flash_attention(q, k, v, causal=True,
                              use_pallas_override=True,
                              block_q=64, block_k=64)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # warned once: a second identical call stays silent
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        flash_attention(q, k, v, causal=True, use_pallas_override=True,
                        block_q=64, block_k=64)


# ------------------------- row-block / optimizer hooks ----------------------

def test_tuned_row_block_softmax(tmp_cache):
    from apex_tpu.ops.softmax import (
        scaled_softmax,
        scaled_softmax_reference,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (96, 128))
    base = scaled_softmax(x, 2.0, use_pallas_override=True)
    tune.record("softmax_fwd",
                dict(rows=tune.pow2_bucket(96), hidden=128),
                {"block_rows": 16})
    tuned_out = scaled_softmax(x, 2.0, use_pallas_override=True)
    np.testing.assert_allclose(np.asarray(tuned_out), np.asarray(base),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(tuned_out), np.asarray(scaled_softmax_reference(x, 2.0)),
        rtol=1e-5, atol=1e-5)
    # an insane tuned value is rejected → heuristic
    tune.record("softmax_fwd",
                dict(rows=tune.pow2_bucket(96), hidden=128),
                {"block_rows": 7})
    out2 = scaled_softmax(x, 2.0, use_pallas_override=True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(base),
                               rtol=1e-6, atol=1e-6)


def test_tuned_row_block_layer_norm(tmp_cache):
    from apex_tpu.ops.layer_norm import (
        fused_layer_norm,
        layer_norm_reference,
    )

    x = jax.random.normal(jax.random.PRNGKey(1), (80, 64))
    w = jnp.ones((64,)) * 1.5
    b = jnp.zeros((64,)) + 0.1
    tune.record("layer_norm_fwd",
                dict(rows=tune.pow2_bucket(80), hidden=64),
                {"block_rows": 8})
    tune.record("layer_norm_bwd",
                dict(rows=tune.pow2_bucket(80), hidden=64),
                {"block_rows": 8})

    def f(x, w, b):
        return jnp.sum(fused_layer_norm(x, w, b,
                                        use_pallas_override=True) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(x, w, b)

    def fr(x, w, b):
        return jnp.sum(layer_norm_reference(x, w, b) ** 2)

    gr = jax.grad(fr, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-4, atol=1e-4)


def test_tuned_opt_block_rows(tmp_cache):
    from apex_tpu.ops import optimizer_kernels as K

    n = K.FLAT_TILE
    rows = n // K._LANES
    p = jnp.ones((n,), jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = jnp.full((n,), 1e-2, jnp.float32)
    base = K.adam_flat(p, m, v, g, lr=1e-3, step=1,
                       use_pallas_override=True)
    tune.record("opt_flat", dict(kernel="adam",
                                 rows=tune.pow2_bucket(rows)),
                {"block_rows": 128})
    tuned_out = K.adam_flat(p, m, v, g, lr=1e-3, step=1,
                            use_pallas_override=True)
    for a, e in zip(tuned_out, base):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-6, atol=1e-6)
    # non-dividing tuned value → heuristic (512), still exact
    tune.record("opt_flat", dict(kernel="adam",
                                 rows=tune.pow2_bucket(rows)),
                {"block_rows": 384})
    out2 = K.adam_flat(p, m, v, g, lr=1e-3, step=1,
                       use_pallas_override=True)
    for a, e in zip(out2, base):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-6, atol=1e-6)


def test_check_key_roundtrip_covers_all_committed_defaults():
    """tune --check derives sweep shapes from the committed keys
    themselves — every v5e default must round-trip through the parser
    to a sweepable (op, attrs)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from scripts.gpt_anatomy import _parse_key_attrs

    from apex_tpu.tune import defaults

    for kind, entries in defaults.DEFAULTS.items():
        for key in entries:
            op, attrs = _parse_key_attrs(key)
            if op == "flash_sdpa":
                # re-keying the parsed attrs must reproduce the key
                assert tune.make_key(op, attrs) == key
                assert attrs["sq"] == attrs["sk"]  # sweepable shape
                assert attrs["bias"] == "none"
            else:
                assert op == "opt_flat"
                assert tune.make_key(op, attrs) == key


# ------------------------------- search driver ------------------------------

@pytest.mark.slow
@pytest.mark.l1
def test_search_sweep_records_winner(tmp_cache):
    """Full (tiny-shape, interpret-mode) sweep: the driver times every
    candidate, records the winner, and the kernels then hit it."""
    from apex_tpu.tune import search

    best, results = search.tune_flash(
        1, 2, 128, 16, dtype=jnp.float32, causal=True, iters=1,
        use_pallas_override=True)
    assert results and best in [c for c, _ in results]
    tune.invalidate()
    attrs = search.flash_attrs(1, 2, 128, 16, jnp.float32, True)
    assert tune.tuned("flash_sdpa", attrs) == best


@pytest.mark.slow
@pytest.mark.l1
def test_search_opt_flat_sweep(tmp_cache):
    from apex_tpu.tune import search

    best, results = search.tune_opt_flat(2 * 512 * 128, iters=1,
                                         use_pallas_override=True)
    assert best["block_rows"] in (128, 256, 512)
