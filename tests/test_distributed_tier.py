"""Distributed-tier correctness tests.

≡ the reference's `tests/distributed/` tier (SURVEY §4):
  - DDP grad-sync correctness with analytically known gradients
    (tests/distributed/DDP/ddp_race_condition_test.py:28-62)
  - amp master-param consistency across ranks
    (tests/distributed/amp_master_params/amp_master_params.py)
  - SyncBN numerics vs single-device BN incl. uneven per-rank batch
    sizes and subgroups (tests/distributed/synced_batchnorm/*.py)

The reference launches real NCCL processes; here every "rank" is a
shard of the 8-device virtual CPU mesh and the same collectives compile
through shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax import shard_map

from apex_tpu.ops import welford
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M
from apex_tpu.parallel.sync_batchnorm import sync_batch_norm


class TestDDPAnalyticGrads:
    """≡ ddp_race_condition_test.py: loss = sum(a*x + b) with per-rank x;
    expected grads are known in closed form, so any sync/ordering bug
    shows as a numeric mismatch."""

    def test_grads_match_closed_form(self):
        mesh = M.initialize_model_parallel()  # dp=8
        dp = 8
        n = 4096
        a = jnp.full((n,), 2.0)
        b = jnp.zeros((n,))
        # per-rank input: x_r = (r+1) * ones
        x = jnp.stack([jnp.full((n,), r + 1.0) for r in range(dp)])

        def per_shard(params, xs):
            aa, bb = params
            grads = jax.grad(lambda p: jnp.sum(p[0] * xs[0] + p[1]))(
                (aa, bb))
            return ddp.sync_gradients(grads, "dp")

        # check_vma=False is the make_train_step convention: grads are
        # per-shard partials and sync_gradients performs the one pmean
        # (with vma tracking, AD would itself psum grads of replicated
        # params — see sync_gradients docstring).
        f = shard_map(per_shard, mesh=mesh,
                      in_specs=((P(), P()), P("dp")),
                      out_specs=(P(), P()), check_vma=False)
        ga, gb = f((a, b), x)
        # dL/da averaged over ranks = mean_r(x_r) = mean(1..8) = 4.5
        np.testing.assert_allclose(np.asarray(ga), 4.5 * np.ones(n),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gb), np.ones(n), rtol=1e-6)

    def test_bucketed_matches_plain(self):
        mesh = M.initialize_model_parallel()
        key = jax.random.PRNGKey(0)
        grads = {
            "w": jax.random.normal(key, (8, 37, 5)),
            "b": jax.random.normal(jax.random.PRNGKey(1), (8, 11)),
        }

        def plain(g):
            return ddp.sync_gradients(g, "dp")

        def bucketed(g):
            return ddp.sync_gradients_bucketed(g, "dp", num_buckets=3)

        specs = {"w": P("dp"), "b": P("dp")}
        out_p = shard_map(plain, mesh=mesh, in_specs=(specs,),
                          out_specs=specs)(grads)
        out_b = shard_map(bucketed, mesh=mesh, in_specs=(specs,),
                          out_specs=specs)(grads)
        for k in grads:
            np.testing.assert_allclose(np.asarray(out_p[k]),
                                       np.asarray(out_b[k]), rtol=1e-5)


class TestAmpMasterParams:
    """≡ amp_master_params.py: after synced steps every rank's master
    (fp32) and model (half) params must agree."""

    def test_replicated_update_identical_across_shards(self):
        mesh = M.initialize_model_parallel()
        dp = 8
        n = 1024
        master = jnp.linspace(-1.0, 1.0, n, dtype=jnp.float32)
        # per-rank different grads — sync must make updates identical
        grads = jnp.stack([
            jax.random.normal(jax.random.PRNGKey(r), (n,)) for r in range(dp)
        ])

        def per_shard(m, g):
            g = jax.lax.pmean(g[0], "dp")
            new_master = m - 0.1 * g
            model = new_master.astype(jnp.bfloat16)
            # return per-shard copies so we can compare across shards
            return (jax.lax.all_gather(new_master, "dp"),
                    jax.lax.all_gather(model, "dp"))

        f = shard_map(per_shard, mesh=mesh, in_specs=(P(), P("dp")),
                      out_specs=(P("dp"), P("dp")))
        masters, models = f(master, grads)
        masters = np.asarray(masters)
        models = np.asarray(models, dtype=np.float32)
        for r in range(1, dp):
            np.testing.assert_array_equal(masters[0], masters[r])
            np.testing.assert_array_equal(models[0], models[r])
        # master ≈ model within bf16 precision (amp_master_params compare.py)
        np.testing.assert_allclose(models[0], masters[0], rtol=1e-2,
                                   atol=1e-2)


class TestSyncBNDistributed:
    """≡ tests/distributed/synced_batchnorm: parity vs single-device BN,
    subgroup stats, and uneven per-rank batch sizes."""

    def _ref_bn(self, x, eps=1e-5):
        m = x.mean(axis=(0, 1, 2))
        v = x.var(axis=(0, 1, 2))
        return (x - m) / np.sqrt(v + eps)

    def test_syncbn_matches_global_bn(self):
        mesh = M.initialize_model_parallel()
        x = np.random.RandomState(0).randn(16, 4, 4, 6).astype(np.float32)
        scale = jnp.ones((6,))
        bias = jnp.zeros((6,))
        rm = jnp.zeros((6,))
        rv = jnp.ones((6,))

        def f(xs):
            y, _, _ = sync_batch_norm(xs, scale, bias, rm, rv,
                                      training=True, axis_name="dp")
            return y

        y = shard_map(f, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P("dp"))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(y), self._ref_bn(x),
                                   rtol=2e-4, atol=2e-4)

    def test_syncbn_subgroups(self):
        """Group BN over a 2-device sub-axis (≡ test_groups.py): mesh
        (g=4, m=2), stats merged only within each m-pair."""
        M.destroy_model_parallel()
        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = jax.sharding.Mesh(devs, ("g", "m"))
        x = np.random.RandomState(1).randn(8, 2, 2, 3).astype(np.float32)
        scale, bias = jnp.ones((3,)), jnp.zeros((3,))
        rm, rv = jnp.zeros((3,)), jnp.ones((3,))

        def f(xs):
            y, _, _ = sync_batch_norm(xs, scale, bias, rm, rv,
                                      training=True, axis_name="m")
            return y

        y = shard_map(f, mesh=mesh, in_specs=(P(("g", "m")),),
                      out_specs=P(("g", "m")))(jnp.asarray(x))
        y = np.asarray(y)
        # each group of 2 consecutive shards (1 sample each) normalizes
        # over its own pair only
        for g in range(4):
            pair = x[2 * g:2 * g + 2]
            np.testing.assert_allclose(y[2 * g:2 * g + 2],
                                       self._ref_bn(pair),
                                       rtol=2e-4, atol=2e-4)

    def test_uneven_counts_merge(self):
        """≡ two_gpu_unit_test.py uneven batch sizes: shards contribute
        different valid-row counts via masked local stats; the merged
        stats must equal stats over the concatenated valid rows."""
        mesh = M.initialize_model_parallel()
        rng = np.random.RandomState(2)
        C = 5
        # shard r has (r % 3 + 1) valid rows, padded to 3
        counts = np.array([r % 3 + 1 for r in range(8)])
        data = [rng.randn(c, C).astype(np.float32) for c in counts]
        padded = np.stack([
            np.concatenate([d, np.zeros((3 - len(d), C), np.float32)])
            for d in data])
        cnt = jnp.asarray(counts, jnp.float32)

        def f(xs, n):
            x2 = xs[0]  # (3, C) padded rows
            n = n[0][0]
            mask = (jnp.arange(3) < n)[:, None]
            s = jnp.sum(x2 * mask, axis=0)
            q = jnp.sum((x2 ** 2) * mask, axis=0)
            mean = s / n
            var = jnp.maximum(q / n - mean ** 2, 0.0)
            tm, tv, tn = welford.merge_stats(mean, var, n, "dp")
            return jnp.stack([tm, tv, jnp.full((C,), tn)])

        out = shard_map(f, mesh=mesh,
                        in_specs=(P("dp"), P("dp")),
                        out_specs=P())(jnp.asarray(padded),
                                       cnt.reshape(8, 1))
        allrows = np.concatenate(data)
        np.testing.assert_allclose(np.asarray(out[0]), allrows.mean(0),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[1]), allrows.var(0),
                                   rtol=1e-4, atol=1e-4)
        assert float(out[2][0]) == len(allrows)
