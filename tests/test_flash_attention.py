"""Flash attention parity ≡ apex/contrib/test/fmha/test_fmha.py and the
multihead_attn numerics tests: Pallas blockwise kernel vs plain softmax
attention, fwd + grads, causal and full, multiple shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import attention_reference, flash_attention


def _qkv(b, h, sq, sk, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, sq, d), dtype)
    k = jax.random.normal(ks[1], (b, h, sk, d), dtype)
    v = jax.random.normal(ks[2], (b, h, sk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 32, 32, 16), (2, 1, 64, 64, 8)])
def test_flash_forward(shape, causal):
    b, h, sq, sk, d = shape
    q, k, v = _qkv(b, h, sq, sk, d)
    got = flash_attention(q, k, v, causal=causal, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_cross_attention_shapes():
    # sq != sk (encdec ≡ fast_multihead_attn encdec variants)
    q, k, v = _qkv(1, 2, 32, 64, 16, seed=1)
    got = flash_attention(q, k, v, causal=False, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads(causal):
    q, k, v = _qkv(1, 2, 32, 32, 16, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, use_pallas_override=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_bf16():
    q, k, v = _qkv(1, 2, 64, 64, 32, jnp.bfloat16, seed=3)
    got = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_flash_long_seq_blocks():
    # multiple q/k blocks (seq 256 → blocks of 256? no: picks 256; use 160
    # to force 32-blocks... 160 % 32 == 0)
    q, k, v = _qkv(1, 1, 160, 160, 8, seed=4)
    got = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_dropout_fallback_api():
    """dropout on the non-kernel path: masks attention weights, scales
    by 1/keep, deterministic per key, E[out] tracks the no-dropout
    output (the in-kernel philox path is validated on hardware by
    examples/tpu_kernel_smoke.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from apex_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 64, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 64, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 64, 32))
    with pytest.raises(ValueError, match="dropout_key"):
        flash_attention(q, k, v, dropout_rate=0.1)
    key = jax.random.PRNGKey(3)
    o1 = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                         dropout_key=key)
    o2 = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                         dropout_key=key)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    base = np.asarray(flash_attention(q, k, v, causal=True))
    acc = np.zeros_like(base)
    n = 32
    for i in range(n):
        acc += np.asarray(flash_attention(
            q, k, v, causal=True, dropout_rate=0.3,
            dropout_key=jax.random.PRNGKey(50 + i)))
    rel = np.abs(acc / n - base).mean() / np.abs(base).mean()
    assert rel < 0.3, rel


# ---------------- masks / bias / varlen (round 2: VERDICT missing #1-2) -----

@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_mask_parity(causal):
    """Segment ids ≡ the reference's padding/attention masks
    (multihead_attn mask paths) and fmha varlen cu_seqlens."""
    b, h, s, d = 2, 2, 64, 16
    q, k, v = _qkv(b, h, s, s, d, seed=3)
    # two packed segments + a pad tail per row
    seg = jnp.stack([
        jnp.concatenate([jnp.zeros(24, jnp.int32), jnp.ones(24, jnp.int32),
                         jnp.full((16,), 7, jnp.int32)]),
        jnp.concatenate([jnp.zeros(40, jnp.int32),
                         jnp.full((24,), 3, jnp.int32)]),
    ])
    got = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                          use_pallas_override=True)
    want = attention_reference(q, k, v, causal=causal,
                               q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_segment_grads():
    b, h, s, d = 1, 2, 64, 16
    q, k, v = _qkv(b, h, s, s, d, seed=4)
    seg = jnp.concatenate([jnp.zeros(32, jnp.int32),
                           jnp.ones(32, jnp.int32)])[None, :]

    def lf(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, segment_ids=seg, use_pallas_override=True)))

    def lr(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(
            q, k, v, q_segment_ids=seg, kv_segment_ids=seg)))

    g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_varlen_packing_equivalence():
    """Two sequences packed into one row with distinct segment ids give
    the same outputs as attending to each separately — the capability
    fmha's cu_seqlens packing provides (fmha_api.cpp:18-160)."""
    h, d = 2, 16
    s1, s2 = 24, 40
    q, k, v = _qkv(1, h, s1 + s2, s1 + s2, d, seed=5)
    seg = jnp.concatenate([jnp.zeros(s1, jnp.int32),
                           jnp.ones(s2, jnp.int32)])[None, :]
    packed = flash_attention(q, k, v, causal=True, segment_ids=seg,
                             use_pallas_override=True)
    sep1 = attention_reference(q[:, :, :s1], k[:, :, :s1], v[:, :, :s1],
                               causal=True)
    sep2 = attention_reference(q[:, :, s1:], k[:, :, s1:], v[:, :, s1:],
                               causal=True)
    np.testing.assert_allclose(np.asarray(packed[:, :, :s1]),
                               np.asarray(sep1), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(packed[:, :, s1:]),
                               np.asarray(sep2), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("bias_shape", [(1, 1), (1, 2), (2, 1), (2, 2)])
def test_flash_additive_bias_parity(bias_shape):
    """Additive score bias ≡ the fused x*scale + mask softmax
    (multihead_attn/softmax.cuh:27-200); covers ALiBi/rel-pos masks."""
    b, h, s, d = 2, 2, 64, 16
    q, k, v = _qkv(b, h, s, s, d, seed=6)
    nb, nh = bias_shape
    bias = jax.random.normal(jax.random.PRNGKey(9), (nb, nh, s, s),
                             jnp.float32)
    got = flash_attention(q, k, v, bias=bias, use_pallas_override=True)
    want = attention_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_bias_grads_qkv():
    """q/k/v grads flow through a bias; bias_grad=False keeps the
    constant-bias zero-cotangent contract."""
    b, h, s, d = 1, 2, 32, 16
    q, k, v = _qkv(b, h, s, s, d, seed=7)
    bias = jax.random.normal(jax.random.PRNGKey(8), (1, h, s, s))

    def lf(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, bias=bias, use_pallas_override=True)))

    def lr(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, bias=bias)))

    g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")
    dbias = jax.grad(lambda bb: jnp.sum(flash_attention(
        q, k, v, bias=bb, bias_grad=False,
        use_pallas_override=True)))(bias)
    assert float(jnp.max(jnp.abs(dbias))) == 0.0


# ------------------ trainable bias (round 4: VERDICT missing #1) ------------

@pytest.mark.parametrize("bias_shape", [(1, 1), (1, 2), (2, 1), (2, 2)])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_dbias_full_parity(bias_shape, causal):
    """Trainable full (sq, sk) bias: kernel dbias ≡ dense AD, including
    the broadcast-dim reductions (≡ self_multihead_attn_bias.cu
    capability — bias trains end-to-end on the fast path)."""
    b, h, s, d = 2, 2, 32, 16
    q, k, v = _qkv(b, h, s, s, d, seed=13)
    nb, nh = bias_shape
    bias = 0.5 * jax.random.normal(jax.random.PRNGKey(14), (nb, nh, s, s))

    def lf(bb):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, bias=bb, causal=causal, use_pallas_override=True)))

    def lr(bb):
        return jnp.sum(jnp.sin(attention_reference(
            q, k, v, bias=bb, causal=causal)))

    got, want = jax.grad(lf)(bias), jax.grad(lr)(bias)
    assert got.shape == bias.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("bias_shape", [(1, 1), (2, 2)])
def test_flash_dbias_sk_compact_parity(bias_shape):
    """Trainable key-compact (.., 1, sk) bias (learned ALiBi / padding
    shape): the in-kernel q-sum dbias ≡ dense AD — and the forward
    never expands it to sq x sk."""
    b, h, s, d = 2, 2, 32, 16
    q, k, v = _qkv(b, h, s, s, d, seed=15)
    nb, nh = bias_shape
    bias = 0.5 * jax.random.normal(jax.random.PRNGKey(16), (nb, nh, 1, s))

    def lf(bb):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, bias=bb, use_pallas_override=True)))

    def lr(bb):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, bias=bb)))

    got, want = jax.grad(lf)(bias), jax.grad(lr)(bias)
    assert got.shape == bias.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)
    # forward parity through the native compact path too
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, bias=bias,
                                   use_pallas_override=True)),
        np.asarray(attention_reference(q, k, v, bias=bias)),
        rtol=1e-4, atol=1e-4)


def test_flash_dbias_query_compact_zero():
    """A (.., sq, 1) bias adds a per-query constant — softmax cancels
    it: gradient is EXACTLY zero (dense AD agrees to float eps)."""
    b, h, s, d = 1, 2, 32, 16
    q, k, v = _qkv(b, h, s, s, d, seed=17)
    bias = jax.random.normal(jax.random.PRNGKey(18), (1, h, s, 1))
    got = jax.grad(lambda bb: jnp.sum(jnp.sin(flash_attention(
        q, k, v, bias=bb, use_pallas_override=True))))(bias)
    assert float(jnp.max(jnp.abs(got))) == 0.0
    want = jax.grad(lambda bb: jnp.sum(jnp.sin(attention_reference(
        q, k, v, bias=bb))))(bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_flash_dbias_two_kernel_path(monkeypatch):
    """Force the long-context two-kernel backward (dq-kernel dbias
    blocks) by shrinking the fused-path cap."""
    from apex_tpu.ops import flash_attention as FA
    monkeypatch.setattr(FA, "_FUSED_BWD_CAP", 1)
    b, h, s, d = 1, 2, 64, 16
    q, k, v = _qkv(b, h, s, s, d, seed=19)
    bias = 0.5 * jax.random.normal(jax.random.PRNGKey(20), (1, h, s, s))

    def lf(q, k, v, bb):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, bias=bb, causal=True, use_pallas_override=True)))

    def lr(q, k, v, bb):
        return jnp.sum(jnp.sin(attention_reference(
            q, k, v, bias=bb, causal=True)))

    g1 = jax.grad(lf, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, e, name in zip(g1, g2, ("q", "k", "v", "bias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_bias_with_segments_and_causal():
    b, h, s, d = 1, 2, 64, 16
    q, k, v = _qkv(b, h, s, s, d, seed=10)
    bias = 0.1 * jax.random.normal(jax.random.PRNGKey(11), (1, 1, s, s))
    seg = jnp.concatenate([jnp.zeros(48, jnp.int32),
                           jnp.ones(16, jnp.int32)])[None, :]
    got = flash_attention(q, k, v, causal=True, bias=bias, segment_ids=seg,
                          use_pallas_override=True)
    want = attention_reference(q, k, v, causal=True, bias=bias,
                               q_segment_ids=seg, kv_segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_segment_api_validation():
    q, k, v = _qkv(1, 1, 32, 32, 8)
    seg = jnp.zeros((1, 32), jnp.int32)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, segment_ids=seg, q_segment_ids=seg,
                        kv_segment_ids=seg)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, q_segment_ids=seg)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, bias=jnp.zeros((3, 1, 32, 32)))


def test_flash_in_kernel_dropout_mask_consistency():
    """The in-kernel dropout mask is a pure coordinate hash, so
    interpret mode reproduces the TPU masks bit-for-bit and fwd/bwd
    must agree: with a fixed mask the output is LINEAR in v, making
    directional finite differences exact (this was unverifiable in CPU
    CI with the hardware PRNG — whose stream order even differed
    between the fwd and fused-bwd kernels)."""
    from apex_tpu.ops.flash_attention import _flash
    B, H, S, D = 1, 2, 128, 32
    qq = jax.random.normal(jax.random.PRNGKey(0), (B, H, S, D))
    kk = jax.random.normal(jax.random.PRNGKey(1), (B, H, S, D))
    vv = jax.random.normal(jax.random.PRNGKey(2), (B, H, S, D))
    cc = jax.random.normal(jax.random.PRNGKey(3), (B, H, S, D))
    seed = jnp.asarray([[777]], jnp.int32)
    # (bias, q_seg, kv_seg, scale, causal, rate, block_q, block_k,
    #  heads_per_step, bias_grad, seed)
    args = (None, None, None, 0.18, True, 0.2, None, None, 1, False,
            seed)
    o1 = np.asarray(_flash(qq, kk, vv, *args))
    o2 = np.asarray(_flash(qq, kk, vv, *args))
    np.testing.assert_array_equal(o1, o2)

    def f(v_):
        return jnp.vdot(_flash(qq, kk, v_, *args), cc)

    gv = jax.grad(f)(vv)
    dirv = jax.random.normal(jax.random.PRNGKey(4), vv.shape)
    fd = float(f(vv + 0.5 * dirv)) - float(f(vv - 0.5 * dirv))
    an = float(jnp.vdot(gv, dirv))
    assert abs(fd - an) < 1e-3 * abs(an) + 1e-4, (fd, an)

    # keep-rate statistic ~ 1 - rate
    p_nodrop = np.asarray(_flash(
        qq, kk, vv, None, None, None, 0.18, True, 0.0, None, None, 1,
        False, seed))
    assert not np.allclose(o1, p_nodrop)
