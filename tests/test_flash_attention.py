"""Flash attention parity ≡ apex/contrib/test/fmha/test_fmha.py and the
multihead_attn numerics tests: Pallas blockwise kernel vs plain softmax
attention, fwd + grads, causal and full, multiple shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import attention_reference, flash_attention


def _qkv(b, h, sq, sk, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, h, sq, d), dtype)
    k = jax.random.normal(ks[1], (b, h, sk, d), dtype)
    v = jax.random.normal(ks[2], (b, h, sk, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 2, 32, 32, 16), (2, 1, 64, 64, 8)])
def test_flash_forward(shape, causal):
    b, h, sq, sk, d = shape
    q, k, v = _qkv(b, h, sq, sk, d)
    got = flash_attention(q, k, v, causal=causal, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_cross_attention_shapes():
    # sq != sk (encdec ≡ fast_multihead_attn encdec variants)
    q, k, v = _qkv(1, 2, 32, 64, 16, seed=1)
    got = flash_attention(q, k, v, causal=False, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads(causal):
    q, k, v = _qkv(1, 2, 32, 32, 16, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, use_pallas_override=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, e, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-3, atol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_flash_bf16():
    q, k, v = _qkv(1, 2, 64, 64, 32, jnp.bfloat16, seed=3)
    got = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_flash_long_seq_blocks():
    # multiple q/k blocks (seq 256 → blocks of 256? no: picks 256; use 160
    # to force 32-blocks... 160 % 32 == 0)
    q, k, v = _qkv(1, 1, 160, 160, 8, seed=4)
    got = flash_attention(q, k, v, causal=True, use_pallas_override=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_flash_dropout_fallback_api():
    """dropout on the non-kernel path: masks attention weights, scales
    by 1/keep, deterministic per key, E[out] tracks the no-dropout
    output (the in-kernel philox path is validated on hardware by
    examples/tpu_kernel_smoke.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from apex_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 64, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 64, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 64, 32))
    with pytest.raises(ValueError, match="dropout_key"):
        flash_attention(q, k, v, dropout_rate=0.1)
    key = jax.random.PRNGKey(3)
    o1 = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                         dropout_key=key)
    o2 = flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                         dropout_key=key)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    base = np.asarray(flash_attention(q, k, v, causal=True))
    acc = np.zeros_like(base)
    n = 32
    for i in range(n):
        acc += np.asarray(flash_attention(
            q, k, v, causal=True, dropout_rate=0.3,
            dropout_key=jax.random.PRNGKey(50 + i)))
    rel = np.abs(acc / n - base).mean() / np.abs(base).mean()
    assert rel < 0.3, rel
