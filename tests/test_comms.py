"""Collective & overlap observatory tests (ISSUE 7): the
`monitor.comms` inventory on the real ZeRO-2 `ddp.make_train_step`
(per-bucket reduce-scatters found with correct bytes/dtype/axis on a
dp=2 CPU mesh), the async start/done overlap classification on a
seeded serialized-collective HLO fixture, the ICI roofline table
resolution + override, crash-dump attachment via
`analyze_step(..., comms=True)`, the SCHEMA v4 `comms_*` record
fields, the `comms_probe.py --selftest` / fixture gates (tier-1, like
`lint_step.py --selftest`), and the acceptance line: step numerics
bitwise identical with the observatory on vs off.

The HLO-text tests need no backend at all; the compiled-step tests run
tiny programs only — the file must stay cheap (the tier-1 window is a
dot budget and this file sorts early in the alphabet).
"""

import json
import os
import pathlib
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import monitor
from apex_tpu.monitor import comms
from apex_tpu.monitor import trace
from apex_tpu.monitor.comms import hlo as hlo_lib
from apex_tpu.monitor.comms import roofline
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M

ROOT = pathlib.Path(__file__).resolve().parent.parent


# ------------------- seeded HLO fixture (no backend) -------------------

# A hand-written optimized-module dump in XLA's post-scheduling syntax:
# one async all-reduce whose start->done window holds a dot (hidden),
# and one async reduce-scatter (spelled via the async-start wrapper
# form XLA also emits) whose window holds NOTHING — the seeded
# serialized collective the gate must flag.  Both move 4 MiB over
# replica group {0,1}.
_SEEDED_HLO = """\
HloModule jit_step, is_scheduled=true

%add_f32 (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

%rs_comp (param.1: f32[1048576]) -> f32[524288] {
  %param.1 = f32[1048576]{0} parameter(0)
  ROOT %rs = f32[524288]{0} reduce-scatter(f32[1048576]{0} %param.1), replica_groups={{0,1}}, dimensions={0}, to_apply=%add_f32
}

ENTRY %main (p0: f32[1048576], p1: f32[256,256], p2: f32[256,256]) -> (f32[1048576], f32[524288], f32[256,256]) {
  %p0 = f32[1048576]{0} parameter(0)
  %p1 = f32[256,256]{1,0} parameter(1)
  %p2 = f32[256,256]{1,0} parameter(2)
  %ar-start = f32[1048576]{0} all-reduce-start(f32[1048576]{0} %p0), replica_groups={{0,1}}, to_apply=%add_f32, metadata={op_name="jit(step)/psum"}
  %dot.1 = f32[256,256]{1,0} dot(f32[256,256]{1,0} %p1, f32[256,256]{1,0} %p2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %ar-done = f32[1048576]{0} all-reduce-done(f32[1048576]{0} %ar-start)
  %rs-start = ((f32[1048576]{0}), f32[524288]{0}) async-start(f32[1048576]{0} %p0), calls=%rs_comp
  %rs-done = f32[524288]{0} async-done(((f32[1048576]{0}), f32[524288]{0}) %rs-start), calls=%rs_comp
  ROOT %tup = (f32[1048576]{0}, f32[524288]{0}, f32[256,256]{1,0}) tuple(f32[1048576]{0} %ar-done, f32[524288]{0} %rs-done, f32[256,256]{1,0} %dot.1)
}
"""


def test_seeded_serialized_collective_flagged():
    """The gate's reason to exist: an async reduce-scatter whose
    start->done window holds zero dot flops is SERIALIZED; the async
    all-reduce with a dot inside its window is not."""
    rep = comms.comms_report(hlo_text=_SEEDED_HLO,
                             mesh_axis_names=("dp",),
                             mesh_axis_sizes=(2,),
                             device_kind="TPU v5e")
    assert rep.async_supported is True
    by_kind = {c.kind: c for c in rep.collectives}
    assert set(by_kind) == {"all-reduce", "reduce-scatter"}

    ar = by_kind["all-reduce"]
    assert ar.async_pair and ar.operand_bytes == 4 * 2 ** 20
    assert ar.axes == ("dp",) and ar.group_size == 2
    assert ar.overlapped_flops == 2.0 * 256 * 256 * 256
    assert ar.overlap_fraction > 0 and not ar.serialized

    rs = by_kind["reduce-scatter"]
    assert rs.async_pair and rs.operand_bytes == 4 * 2 ** 20
    assert rs.output_bytes == 2 * 2 ** 20  # this rank's scattered half
    assert rs.axes == ("dp",) and rs.group_size == 2
    assert rs.expected_overlap and rs.overlap_fraction == 0.0
    assert rs.serialized, "the seeded serialized collective was missed"

    assert rep.overlap_ok is False
    assert rep.serialized_comm_bytes == 4 * 2 ** 20
    ser = comms.serialized_collectives(rep)
    assert [f["name"] for f in ser] == ["rs-start"]
    text = comms.render_comms_table(rep, label="seeded")
    assert "**SER**" in text and "SERIALIZED collective(s)" in text
    # the to_dict form is schema-valid and JSON round-trips
    d = json.loads(json.dumps(rep.to_dict()))
    comms.validate_comms_report(d)


def test_small_collectives_not_held_to_overlap():
    """A sub-floor async collective (scalar loss pmean, found_inf OR)
    is never expected to overlap: noise, not a lever."""
    tiny = _SEEDED_HLO.replace("1048576", "64").replace("524288", "32")
    rep = comms.comms_report(hlo_text=tiny, mesh_axis_names=("dp",),
                             mesh_axis_sizes=(2,))
    assert all(not c.expected_overlap and not c.serialized
               for c in rep.collectives)
    assert rep.overlap_ok is True


def test_async_update_chain_pairs_start_done():
    """XLA may thread start -> async-update -> done; the done's
    operand then names the UPDATE, not the start.  The pairing must
    follow the chain — else the window runs to the end of the
    computation and the gate goes blind to exactly the serialized
    collective it exists to catch."""
    old = ("  %rs-done = f32[524288]{0} async-done(((f32[1048576]{0}),"
           " f32[524288]{0}) %rs-start), calls=%rs_comp\n")
    new = ("  %rs-upd = ((f32[1048576]{0}), f32[524288]{0}) "
           "async-update(((f32[1048576]{0}), f32[524288]{0}) "
           "%rs-start), calls=%rs_comp\n"
           "  %rs-done = f32[524288]{0} async-done(((f32[1048576]{0}),"
           " f32[524288]{0}) %rs-upd), calls=%rs_comp\n"
           # a dot AFTER the done: an unpaired done would stretch the
           # window over it and launder the serialization as overlap
           "  %dot.2 = f32[256,256]{1,0} dot(f32[256,256]{1,0} %p1, "
           "f32[256,256]{1,0} %p2), lhs_contracting_dims={1}, "
           "rhs_contracting_dims={0}\n")
    assert old in _SEEDED_HLO  # fixture drift guard for the replace
    rep = comms.comms_report(hlo_text=_SEEDED_HLO.replace(old, new),
                             mesh_axis_names=("dp",),
                             mesh_axis_sizes=(2,))
    rs = next(c for c in rep.collectives if c.kind == "reduce-scatter")
    assert rs.async_pair, "done never paired through the update chain"
    assert rs.serialized and rs.overlap_fraction == 0.0, rs
    assert rep.overlap_ok is False


def test_while_body_collective_inventoried():
    """A collective inside a while/scan body must not vanish: the loop
    carry is ONE tuple-typed parameter whose nested parens the
    computation-header parse must span — a header regex stopping at
    the first `)` drops every loop body, collectives included, and
    the probe would pass vacuously green on pipelined/scanned steps."""
    from jax import shard_map
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])

    def run(x):
        def body(_, c):
            return jax.lax.psum(c, "dp") * 0.5
        return jax.lax.fori_loop(0, 3, body, x)

    f = jax.jit(shard_map(run, mesh=mesh, in_specs=(P("dp"),),
                          out_specs=P("dp"), check_vma=False))
    rep = comms.comms_report(f, (jnp.ones((2, 8), jnp.float32),),
                             mesh=mesh)
    ars = [c for c in rep.collectives if c.kind == "all-reduce"]
    assert ars, "loop-resident all-reduce vanished from the inventory"
    assert all(c.axes == ("dp",) and c.group_size == 2 for c in ars)
    M.destroy_model_parallel()


def test_comms_report_compiled_preopt_contradiction():
    """compiled= carries only the OPTIMIZED module, so asking it for
    the pre-optimization view must be an error, not a silent
    optimized-module answer under a pre-opt contract."""
    with pytest.raises(ValueError, match="optimized=False"):
        comms.comms_report(None, (), compiled=object(), optimized=False)


def test_iota_replica_groups_and_axis_mapping():
    """The `[G,S]<=[n](T(p))` iota form XLA prints on larger meshes
    parses to explicit groups, and groups map to the mesh axes whose
    coordinates vary within a group."""
    assert hlo_lib._parse_replica_groups(
        "replica_groups=[2,2]<=[4]") == [[0, 1], [2, 3]]
    assert hlo_lib._parse_replica_groups(
        "replica_groups=[2,2]<=[2,2]T(1,0)") == [[0, 2], [1, 3]]
    # (dp=2, tp=2) mesh: {0,1} varies tp only; {0,2} varies dp only
    from apex_tpu.monitor.comms.report import _axes_for_groups
    assert _axes_for_groups([[0, 1], [2, 3]], ("dp", "tp"),
                            (2, 2)) == ("tp",)
    assert _axes_for_groups([[0, 2], [1, 3]], ("dp", "tp"),
                            (2, 2)) == ("dp",)
    assert _axes_for_groups([[0, 1, 2, 3]], ("dp", "tp"),
                            (2, 2)) == ("dp", "tp")
    assert _axes_for_groups([[0]], ("dp",), (2,)) == ()
    assert _axes_for_groups([[0, 9]], ("dp",), (2,)) is None  # off-mesh


# ------------------------------ roofline ------------------------------

def test_ici_table_resolution_and_override():
    """Sibling contract of flops.DEVICE_BF16_PEAKS: per-generation
    resolution, v5e fallback for unknown kinds (CPU), override wins."""
    assert roofline.device_link_bandwidth("TPU v4") == 300e9
    assert roofline.device_link_bandwidth("TPU v5 lite") == 200e9
    assert roofline.device_link_bandwidth("TPU v5p") == 600e9
    assert roofline.device_link_bandwidth("TPU v6 lite") == 448e9
    assert roofline.device_link_bandwidth("cpu") == \
        roofline.V5E_ICI_BYTES_PER_S
    assert roofline.device_link_bandwidth("TPU v4", override=42e9) == 42e9


def test_collective_cost_formulas():
    """The ring-algorithm formulas the predictions are built from."""
    bw, d = 100e9, 8 * 2 ** 20
    assert roofline.collective_seconds("all-reduce", d, 4, bw) == \
        pytest.approx(2 * 0.75 * d / bw)
    assert roofline.collective_seconds("reduce-scatter", d, 4, bw) == \
        pytest.approx(0.75 * d / bw)
    assert roofline.collective_seconds("all-gather", d, 4, bw) == \
        pytest.approx(3 * d / bw)
    assert roofline.collective_seconds("collective-permute", d, 4, bw) \
        == pytest.approx(d / bw)
    # degenerate groups cost nothing (XLA compiles most of them away)
    assert roofline.collective_seconds("all-reduce", d, 1, bw) == 0.0


def test_report_bandwidth_resolution_per_device_kind():
    """comms_report prices against the report's device kind (so a
    saved TPU report re-renders with TPU numbers on any host), and
    bandwidth_override threads through to the predictions."""
    r5e = comms.comms_report(hlo_text=_SEEDED_HLO,
                             device_kind="TPU v5e")
    assert r5e.link_bandwidth == 200e9
    assert r5e.bandwidth_source == "table:v5e"
    r4 = comms.comms_report(hlo_text=_SEEDED_HLO, device_kind="TPU v4")
    assert r4.link_bandwidth == 300e9
    assert r4.predicted_comm_s == pytest.approx(
        r5e.predicted_comm_s * 200 / 300)
    ovr = comms.comms_report(hlo_text=_SEEDED_HLO, device_kind="TPU v4",
                             bandwidth_override=50e9)
    assert ovr.bandwidth_source == "override"
    assert ovr.link_bandwidth == 50e9


def test_rank_timing_crosscheck():
    """The runtime loop-closer: measured allreduce medians vs the AOT
    prediction (TIMING_FIELDS column 1 = allreduce_duration_s)."""
    rep = comms.comms_report(hlo_text=_SEEDED_HLO,
                             device_kind="TPU v5e")
    timings = np.array([[1e-3, 2e-3], [1e-3, 4e-3]])  # (ranks, fields)
    got = comms.crosscheck_rank_timing(rep, timings)
    assert got["measured_s"] == pytest.approx(3e-3)
    assert got["n_ranks"] == 2
    assert got["ratio"] == pytest.approx(
        3e-3 / rep.predicted_comm_s)


# --------------------- the real ZeRO-2 train step ---------------------

def _zero2_linear_step(mesh, n_buckets=2):
    """The real `ddp.make_train_step` ZeRO-2 path (DistributedFusedAdam
    auto-detected, per-bucket psum_scatter) on a dp=2 slice of the CPU
    mesh — the miniature of the flagship gpt_zero2 gate target."""
    from jax import shard_map

    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )

    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    Y = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
    params = {"w1": jnp.zeros((16, 64)), "w2": jnp.zeros((64, 1))}

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((jnp.tanh(x @ p["w1"]) @ p["w2"] - y) ** 2)

    opt = DistributedFusedAdam(num_shards=2, lr=1e-2, use_pallas=False,
                               n_buckets=n_buckets)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")))
    return step, state, (X, Y)


def test_zero2_step_inventory_dp2():
    """Acceptance: the inventory on the real ZeRO-2 step finds the
    per-bucket reduce-scatters with correct bytes/dtype/axis, mapped
    through the builder-attached mesh metadata (no mesh= passed)."""
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    step, state, batch = _zero2_linear_step(mesh, n_buckets=2)
    assert step.mesh_axis_names == ("pp", "dp", "tp")
    assert step.mesh_axis_sizes == (1, 2, 1)
    rep = comms.comms_report(step, (state, None, batch))
    assert rep.mesh_axis_names == ("pp", "dp", "tp")

    rs = [c for c in rep.collectives if c.kind == "reduce-scatter"]
    assert len(rs) >= 2, f"per-bucket reduce-scatters not found: {rep}"
    for c in rs:
        assert c.axes == ("dp",), c
        assert c.group_size == 2 and c.dtype == "f32", c
    # the buckets partition the padded flat grad buffer: operand
    # bytes sum to the full (unscattered) master-length buffer
    full_elems = int(state.params_shard.shape[0])
    assert sum(c.operand_bytes for c in rs) == full_elems * 4
    # ZeRO-2 tail: the updated param shards all-gather back, same axis
    ags = [c for c in rep.collectives if c.kind == "all-gather"]
    assert ags and all(c.axes == ("dp",) for c in ags)
    # aggregates count the dp collectives only (degenerate excluded)
    assert rep.counts.get("reduce-scatter") == len(rs)
    assert rep.total_comm_bytes == sum(
        c.operand_bytes for c in rep.collectives if c.group_size > 1)
    # CPU backend: sync collectives only — measured as unmeasurable
    assert rep.async_supported is False
    assert rep.overlap_ok is True
    assert all(c.overlap_fraction is None for c in rep.collectives)
    M.destroy_model_parallel()


def test_zero2_numerics_bitwise_identical_with_observatory():
    """Acceptance: training is bitwise identical whether or not the
    comms observatory (comms_report + analyze_step(comms=True)) ran
    against the step."""
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    plain, s_plain, batch = _zero2_linear_step(mesh)
    for _ in range(3):
        s_plain, _, _ = plain(s_plain, None, batch)

    audited, s_aud, _ = _zero2_linear_step(mesh)
    rep = comms.comms_report(audited, (s_aud, None, batch))
    assert rep.counts  # the audit actually saw the program
    full = monitor.analyze_step(audited, (s_aud, None, batch),
                                comms=True)
    assert full.comms is not None
    for _ in range(3):
        s_aud, _, _ = audited(s_aud, None, batch)
    a = np.asarray(jax.device_get(s_plain.params_shard))
    b = np.asarray(jax.device_get(s_aud.params_shard))
    assert a.tobytes() == b.tobytes(), "comms observatory changed bits"
    M.destroy_model_parallel()


def test_preopt_inventory_keeps_authored_dtype():
    """optimized=False reads the pre-optimization module: CPU XLA's
    float-normalization rewrites bf16 collectives to f32 in the
    OPTIMIZED module (backend artifact — TPU keeps bf16), so authored-
    dtype claims (the ported test_distributed_optimizers probes) must
    look pre-opt."""
    from jax import shard_map
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    f = jax.jit(shard_map(
        lambda x: jax.lax.all_gather(x, "dp", tiled=True), mesh=mesh,
        in_specs=(P("dp"),), out_specs=P(), check_vma=False))
    x = jnp.ones((8, 4), jnp.bfloat16)
    pre = comms.comms_report(f, (x,), mesh=mesh, optimized=False)
    (ag,) = [c for c in pre.collectives if c.kind == "all-gather"]
    assert ag.dtype == "bf16" and ag.axes == ("dp",)
    assert ag.operand_bytes == 4 * 4 * 2  # this rank's bf16 shard
    opt = comms.comms_report(f, (x,), mesh=mesh)
    (ag_o,) = [c for c in opt.collectives if c.kind == "all-gather"]
    assert ag_o.dtype == "f32"  # the CPU normalization artifact
    M.destroy_model_parallel()


def test_collective_only_program_is_comm_bound():
    """cost_analysis flops == 0.0 is a real answer (a program that only
    talks is 100% comm-bound), not a missing cost analysis — the falsy
    check `if xla_flops:` used to drop the verdict entirely."""
    from jax import shard_map
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:2])
    f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
                          in_specs=(P("dp"),), out_specs=P(),
                          check_vma=False))
    x = jnp.ones((2, 1024), jnp.float32)
    rep = comms.comms_report(f, (x,), mesh=mesh)
    assert rep.counts.get("all-reduce", 0) >= 1
    assert rep.compute_s is not None  # flops=0.0 kept, not dropped
    assert rep.comm_fraction is not None and rep.comm_fraction > 0.99
    assert rep.comm_bound is True
    assert "COMM-BOUND" in comms.render_comms_table(
        rep.to_dict(), label="psum-only")
    M.destroy_model_parallel()


# ------------------- attachment, schema, rendering -------------------

def test_analyze_step_attaches_comms_and_crash_dump_carries_it(tmp_path):
    """analyze_step(..., comms=True) reuses the SAME executable, the
    report rides the flight-recorder crash dump with no recorder
    schema change, and render_budget_table prints the verdict line."""
    f = jax.jit(lambda a, b: (a @ b).sum())
    a = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    rep = monitor.analyze_step(f, (a, a), comms=True)
    assert rep.comms is not None
    comms.validate_comms_report(rep.comms)
    assert "comms:" in monitor.render_budget_table(rep)
    # comms=False (default) carries None and renders without the line
    assert monitor.analyze_step(f, (a, a)).comms is None

    path = tmp_path / "flight.json"
    rec = trace.FlightRecorder(path, capacity=4)
    rec.attach_compile_report(rep)
    with pytest.raises(RuntimeError):
        with rec.guard():
            raise RuntimeError("boom")
    data = json.loads(path.read_text())
    trace.validate_report(data)
    comms.validate_comms_report(data["compile_report"]["comms"])


def test_validate_record_v4_comms_fields_roundtrip(tmp_path):
    """SCHEMA_VERSION 3->4: the comms_* optional fields are null-legal
    exactly where the backend withholds the plane (roofline/overlap),
    never for the inventory totals, and survive a JSONLSink round
    trip under the prefix-scalar rule."""
    # fields introduced in v4 stay valid in every later version
    assert monitor.SCHEMA_VERSION >= 4
    base = {"monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
            "loss": 1.0, "grad_norm": 0.1, "param_norm": 1.0,
            "update_norm": 0.0, "loss_scale": 1.0, "overflow_count": 0,
            "skipped_steps": 0, "tokens_seen": 0.0, "step_time_ms": 1.0,
            "tokens_per_sec": 1.0, "mfu": 0.0}
    good = dict(base, comms_n_collectives=8, comms_bytes=3 * 2 ** 20,
                comms_predicted_comm_s=1.5e-4, comms_comm_fraction=0.25,
                comms_overlap_ok=True)
    monitor.validate_record(good)
    # null-legal: the CPU stamps (no cost analysis, no async plane)
    monitor.validate_record(dict(base, comms_comm_fraction=None,
                                 comms_overlap_ok=None,
                                 comms_predicted_comm_s=None))
    # the inventory totals must carry a value when present
    with pytest.raises(ValueError, match="comms_n_collectives"):
        monitor.validate_record(dict(base, comms_n_collectives=None))
    with pytest.raises(ValueError, match="comms_bytes"):
        monitor.validate_record(dict(base, comms_bytes=1.5))
    # prefix-scalar rule: unknown comms_ keys must be JSON scalars
    monitor.validate_record(dict(base, comms_custom="ok"))
    with pytest.raises(ValueError, match="scalar"):
        monitor.validate_record(dict(base, comms_custom={"no": 1}))
    # JSON round trip (0.25 stays float, ints stay ints)
    monitor.validate_record(json.loads(json.dumps(good)))


def test_allowlist_parse_and_apply():
    """lint_allowlist-style `KIND location-glob` lines; the committed
    file starts EMPTY."""
    entries = comms.parse_allowlist(
        "# comment\n"
        "reduce-scatter gpt_zero2:rs-start*  # deliberate\n"
        "all-gather *\n")
    assert entries == [("reduce-scatter", "gpt_zero2:rs-start*"),
                       ("all-gather", "*")]
    with pytest.raises(ValueError, match="unknown collective kind"):
        comms.parse_allowlist("psum foo")
    findings = [{"kind": "reduce-scatter", "name": "rs-start.1"},
                {"kind": "reduce-scatter", "name": "other"}]
    new, allowed = comms.apply_allowlist(findings, entries, "gpt_zero2")
    assert [f["name"] for f in allowed] == ["rs-start.1"]
    assert [f["name"] for f in new] == ["other"]
    # the committed allowlist is empty
    committed = (ROOT / "scripts" / "comms_allowlist.txt").read_text()
    assert comms.parse_allowlist(committed) == []


def test_comms_schema_drift_detected():
    """validate_comms_report fails loudly on version or field drift —
    what --selftest turns into a CI exit code."""
    rep = comms.comms_report(hlo_text=_SEEDED_HLO).to_dict()
    comms.validate_comms_report(rep)
    with pytest.raises(ValueError, match="comms_schema_version"):
        comms.validate_comms_report(dict(rep, comms_schema_version=99))
    with pytest.raises(ValueError, match="overlap_ok"):
        comms.validate_comms_report(
            {k: v for k, v in rep.items() if k != "overlap_ok"})
    broken = json.loads(json.dumps(rep))
    broken["collectives"][0]["kind"] = "psum"
    with pytest.raises(ValueError, match="unknown kind"):
        comms.validate_comms_report(broken)


# ----------------------------- CLI gates -----------------------------

def _run_script(path, *args, timeout=600):
    return subprocess.run(
        [sys.executable, str(path), *args], capture_output=True,
        text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_comms_probe_selftest():
    """Tier-1 CI gate (mirrors lint_step.py --selftest): the committed
    fixture validates, renders with its load-bearing markers, and its
    seeded serialized collective is still flagged."""
    r = _run_script(ROOT / "scripts" / "comms_probe.py", "--selftest")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "comms_probe --selftest: OK" in r.stdout


def test_comms_probe_cli_flagships_clean():
    """Acceptance: `scripts/comms_probe.py` exits 0 on the flagship
    steps (ZeRO-2 dp step + GPT smoke) with the EMPTY committed
    allowlist, and its inventory finds the per-bucket
    reduce-scatters.  (The chunked-TP flagship has its own slow-marked
    test below — it builds the model TWICE for the inventory pin,
    which would stretch this tier-1 gate.)"""
    r = _run_script(ROOT / "scripts" / "comms_probe.py", "--json",
                    "gpt_zero2", "gpt", "serve", "moe")
    assert r.returncode == 0, r.stdout + r.stderr
    reports = [json.loads(line) for line in r.stdout.splitlines()
               if line.startswith("{")]
    zero2 = next(x for x in reports if x["target"] == "gpt_zero2")
    rs = [c for c in zero2["report"]["collectives"]
          if c["kind"] == "reduce-scatter"]
    assert len(rs) >= 4 and all(c["axes"] == ["dp"] for c in rs)
    # the serve decode step (ISSUE 8) is the standing negative
    # control: single-chip serving must emit ZERO collectives
    serve = next(x for x in reports if x["target"] == "serve")
    assert serve["report"]["collectives"] == []
    assert serve["new"] == []


@pytest.mark.slow
def test_comms_probe_tp_overlap_target():
    """ISSUE 18 acceptance: the chunked-TP flagship passes the comms
    gate with the EMPTY committed allowlist, and the inventory pin
    holds — chunk-count-many equal-payload ring ppermutes whose bytes
    equal twice the displaced all-gather traffic, reduce-scatter and
    dp all-reduce planes conserved, monolithic (chunks=1) spelling
    ppermute-free."""
    r = _run_script(ROOT / "scripts" / "comms_probe.py", "--json",
                    "gpt_tp_overlap")
    assert r.returncode == 0, r.stdout + r.stderr
    reports = [json.loads(line) for line in r.stdout.splitlines()
               if line.startswith("{")]
    main = next(x for x in reports if x["target"] == "gpt_tp_overlap")
    cp = [c for c in main["report"]["collectives"]
          if c["kind"] == "collective-permute"]
    # 2 rings (fwd + wgrad) x 2L col sites x (p-1) hops x chunks
    assert len(cp) == 16 and all(c["axes"] == ["tp"] for c in cp)
    assert len({c["operand_bytes"] for c in cp}) == 1
    pin = next(x for x in reports
               if x["target"] == "gpt_tp_overlap_inventory_pin")
    assert pin["ok"], pin["fails"]
    assert pin["n_ring_hops"] == pin["expected_ring_hops"] == 16
    assert pin["ring_bytes"] == 2 * pin["displaced_all_gather_bytes"]


def test_comms_probe_gates_serialized_report():
    """Acceptance: --report on the committed fixture (which seeds a
    serialized reduce-scatter) exits NONZERO — the gate's negative
    control — and the allowlist path accepts it back."""
    fixture = ROOT / "scripts" / "comms_fixture.json"
    r = _run_script(ROOT / "scripts" / "comms_probe.py",
                    "--report", str(fixture))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FAIL" in r.stdout and "serialized" in r.stdout
    # an allowlist naming the seeded collective turns the gate green
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        # both seeded serialized entries: the ZeRO-2 reduce-scatter
        # and the ISSUE 18 serialized ring chunk
        f.write("reduce-scatter *reduce-scatter-start*\n"
                "collective-permute *collective-permute-start*\n")
        allowpath = f.name
    try:
        r2 = _run_script(ROOT / "scripts" / "comms_probe.py",
                         "--report", str(fixture),
                         "--allowlist", allowpath)
        assert r2.returncode == 0, r2.stdout + r2.stderr
        assert "allowlisted" in r2.stdout
    finally:
        os.unlink(allowpath)
