"""fp32 main-grad accumulation in the microbatch hot paths (round 6,
VERDICT r5 next-round #2 + integration gap (b)).

The Apex reference makes fp32 main grads a hard guarantee: the wgrad
GEMM accumulates into a persistent fp32 `main_grad` buffer regardless
of param/compute dtype (transformer/tensor_parallel/layers.py:415-428,
fused_weight_gradient_mlp_cuda).  Here the capability existed as a
utility (`ops/fused_dense.wgrad_accum`) but the hot paths accumulated
microbatch cotangents in the PARAM dtype — with bf16 params, 32
microbatch adds each round to 8 mantissa bits.

These tests pin the integrated behavior:
  * the 32-microbatch drift test — bf16-accum vs fp32-accum against an
    fp64 oracle over the IDENTICAL per-microbatch grads; fp32 must
    track the oracle ≥ 10× tighter (it measures ~1000× in practice)
  * main_grad_dtype=float32 is a numerical no-op for fp32 params
  * ddp.make_train_step(num_microbatches=k, main_grad_dtype=float32)
    matches the single-shot full-batch step on fp32 params
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import flat as F
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
)

N_MICRO = 32


def _loss_fn(p, mb):
    x, y = mb
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    pred = h @ p["w2"]
    return jnp.mean((pred - y) ** 2).astype(jnp.float32)


def _bf16_problem():
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(size=(16, 32)) * 0.3, jnp.bfloat16),
        "b1": jnp.asarray(rng.normal(size=(32,)) * 0.1, jnp.bfloat16),
        "w2": jnp.asarray(rng.normal(size=(32, 4)) * 0.3, jnp.bfloat16),
    }
    # heterogeneous microbatch magnitudes — accumulation-order error is
    # invisible when every partial grad has the same scale
    scale = (1.0 + np.arange(N_MICRO) / 4.0)[:, None, None]
    x = jnp.asarray(rng.normal(size=(N_MICRO, 8, 16)) * scale,
                    jnp.bfloat16)
    y = jnp.asarray(rng.normal(size=(N_MICRO, 8, 4)), jnp.bfloat16)
    return params, (x, y)


def _rel_err(tree, oracle):
    num = den = 0.0
    for got, want in zip(jax.tree_util.tree_leaves(tree),
                         jax.tree_util.tree_leaves(oracle)):
        d = np.asarray(got, np.float64) - want
        num += float((d * d).sum())
        den += float((want * want).sum())
    return np.sqrt(num / den)


def test_main_grad_fp32_tracks_fp64_oracle_10x_tighter():
    params, batch = _bf16_problem()

    # fp64 oracle: the SAME per-microbatch grads (one jitted grad call
    # per slice — the identical jaxpr the scan body traces), accumulated
    # in numpy float64.  The arms differ ONLY in accumulator dtype.
    grad_one = jax.jit(jax.grad(_loss_fn))
    acc = None
    for i in range(N_MICRO):
        g = grad_one(params, jax.tree_util.tree_map(lambda a: a[i], batch))
        g64 = jax.tree_util.tree_map(
            lambda l: np.asarray(l, np.float64), g)
        acc = g64 if acc is None else jax.tree_util.tree_map(
            np.add, acc, g64)
    oracle = jax.tree_util.tree_map(lambda a: a / N_MICRO, acc)

    _, g_bf16 = forward_backward_no_pipelining(
        _loss_fn, batch, params, num_microbatches=N_MICRO,
        main_grad_dtype=jnp.bfloat16)
    _, g_f32 = forward_backward_no_pipelining(
        _loss_fn, batch, params, num_microbatches=N_MICRO,
        main_grad_dtype=jnp.float32)
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(g_f32))

    err_bf16 = _rel_err(g_bf16, oracle)
    err_f32 = _rel_err(g_f32, oracle)
    # the acceptance bar is 10x; measured ratio is ~3 orders of magnitude
    assert err_f32 < err_bf16 / 10.0, (err_f32, err_bf16)
    # and the default (dtype-of-param) path really is the bf16-drift arm
    _, g_default = forward_backward_no_pipelining(
        _loss_fn, batch, params, num_microbatches=N_MICRO)
    assert _rel_err(g_default, oracle) > err_f32 * 10.0


def test_main_grad_fp32_is_noop_for_fp32_params():
    rng = np.random.default_rng(1)
    params = {"w1": jnp.asarray(rng.normal(size=(8, 8)) * 0.3,
                                jnp.float32),
              "b1": jnp.zeros((8,), jnp.float32),
              "w2": jnp.asarray(rng.normal(size=(8, 2)) * 0.3,
                                jnp.float32)}
    x = jnp.asarray(rng.normal(size=(4, 3, 8)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(4, 3, 2)), jnp.float32)

    loss_a, g_a = forward_backward_no_pipelining(
        _loss_fn, (x, y), params, num_microbatches=4)
    loss_b, g_b = forward_backward_no_pipelining(
        _loss_fn, (x, y), params, num_microbatches=4,
        main_grad_dtype=jnp.float32)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), g_a, g_b)


def test_make_train_step_microbatched_main_grad_matches_full_batch():
    mesh = M.initialize_model_parallel()  # dp=8
    rng = np.random.default_rng(2)
    w_true = jnp.array([[2.0], [-3.0]])
    X = jnp.asarray(rng.normal(size=(32, 2)), jnp.float32)
    Y = X @ w_true

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    params0 = {"w": jnp.zeros((2, 1))}

    def train(num_microbatches, main_grad_dtype):
        opt = FusedSGD(lr=0.1, use_pallas=False)
        state = opt.init(params0)
        step = ddp.make_train_step(
            loss_fn, opt, mesh, batch_spec=(P("dp"), P("dp")),
            num_microbatches=num_microbatches,
            main_grad_dtype=main_grad_dtype)
        for _ in range(5):
            state, _, loss = step(state, None, (X, Y))
        return np.asarray(state.params), float(loss)

    p_ref, loss_ref = train(1, None)
    p_mb, loss_mb = train(2, jnp.float32)
    np.testing.assert_allclose(p_mb, p_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(loss_mb, loss_ref, rtol=1e-4)


def test_make_train_step_main_grad_fp32_with_bf16_params():
    """bf16 param/compute + fp32 main grads end-to-end through the
    fused optimizer (the integration the reference guarantees)."""
    mesh = M.initialize_model_parallel()
    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.normal(size=(32, 4)), jnp.bfloat16)
    Y = jnp.asarray(rng.normal(size=(32, 1)), jnp.bfloat16)

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y).astype(jnp.float32) ** 2)

    params0 = {"w": jnp.zeros((4, 1), jnp.bfloat16)}
    opt = FusedSGD(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")),
                               num_microbatches=4,
                               main_grad_dtype=jnp.float32)
    losses = []
    for _ in range(8):
        state, _, loss = step(state, None, (X, Y))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # master params actually moved
    w = F.unflatten(state.params, opt.spec)["w"]
    assert float(jnp.abs(w).sum()) > 0
