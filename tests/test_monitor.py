"""apex_tpu.monitor tests (ISSUE 2): the metrics pytree, sinks/logger
schema, FLOP accounting, profiler capture, and — the acceptance
criterion — that enabling `metrics=` in the hot paths changes NO
training numerics (bitwise-equal params)."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, monitor
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


# ------------------------------ metrics pytree ------------------------------

def test_update_metrics_accumulates():
    m = monitor.init_metrics()
    g = {"w": jnp.full((4,), 3.0), "b": jnp.full((9,), 4.0)}
    m = monitor.update_metrics(m, loss=2.5, grads=g, tokens=128,
                               loss_scale=8.0,
                               found_inf=jnp.zeros((), bool))
    # sqrt(4*9 + 9*16) = sqrt(180)
    np.testing.assert_allclose(float(m.grad_norm), math.sqrt(180), rtol=1e-6)
    assert (int(m.step), float(m.loss), float(m.loss_scale)) == (1, 2.5, 8.0)
    assert int(m.overflow_count) == 0
    m = monitor.update_metrics(m, loss=2.0, grads=g, tokens=128,
                               found_inf=jnp.ones((), bool))
    assert int(m.step) == 2
    assert int(m.overflow_count) == 1 and int(m.skipped_steps) == 1
    assert float(m.tokens_seen) == 256.0


def test_update_metrics_scaled_grads_and_flat_norms():
    m = monitor.init_metrics()
    p0 = jnp.asarray([3.0, 4.0])
    p1 = jnp.asarray([3.0, 4.0 + 2.0])
    m = monitor.update_metrics(m, grads={"w": jnp.full((4,), 8.0)},
                               inv_scale=0.25, params_flat=p0,
                               new_params_flat=p1)
    np.testing.assert_allclose(float(m.grad_norm), 2.0 * 2.0)  # 8*0.25 * 2
    np.testing.assert_allclose(float(m.param_norm), 5.0)
    np.testing.assert_allclose(float(m.update_norm), 2.0)


def test_infer_tokens_per_step():
    tok = jnp.zeros((4, 16), jnp.int32)
    img = jnp.zeros((4, 8, 8, 3), jnp.float32)
    assert monitor.infer_tokens_per_step((tok, tok)) == 64
    assert monitor.infer_tokens_per_step((img, tok)) == 4
    # microbatch-stacked (m, mb, ...) variants
    assert monitor.infer_tokens_per_step(
        jnp.zeros((2, 4, 16), jnp.int32), microbatch_dims=1) == 128
    assert monitor.infer_tokens_per_step(
        jnp.zeros((2, 4, 8, 8, 3)), microbatch_dims=1) == 8
    assert monitor.infer_tokens_per_step({}) == 0


# ------------------------------ sinks + logger ------------------------------

def _fake_metrics(step=1, tokens=256.0):
    m = monitor.init_metrics()
    return m._replace(step=jnp.asarray(step, jnp.int32),
                      loss=jnp.asarray(1.25, jnp.float32),
                      grad_norm=jnp.asarray(0.5, jnp.float32),
                      tokens_seen=jnp.asarray(tokens, jnp.float32))


def test_logger_jsonl_schema_roundtrip(tmp_path):
    path = tmp_path / "m.jsonl"
    logger = monitor.MetricsLogger([monitor.JSONLSink(path)],
                                   flops_per_step=1e9)
    for s in (1, 2, 3):
        logger.log_step(_fake_metrics(step=s, tokens=256.0 * s))
    logger.close()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(records) == 3
    monitor.validate_records(records)
    assert records[0]["monitor_schema_version"] == monitor.SCHEMA_VERSION
    assert records[1]["tokens_per_sec"] > 0
    assert records[1]["mfu"] > 0
    assert records[1]["step_time_ms"] > 0


def test_reset_timer_resyncs_baselines():
    """After counted-but-unlogged warmup steps, reset_timer(metrics)
    must resync the step/token baselines — otherwise the first window
    divides by the warmup's extra steps (review finding: 3x-inflated
    tokens_per_sec in the demo)."""
    logger = monitor.MetricsLogger([])
    warm = _fake_metrics(step=2, tokens=512.0)  # 2 warmup steps counted
    logger.reset_timer(warm)
    rec = logger.log_step(_fake_metrics(step=3, tokens=768.0))
    # window covers exactly ONE step / 256 tokens
    assert rec["step_time_ms"] * 1e-3 == pytest.approx(
        256.0 / rec["tokens_per_sec"], rel=1e-6)


def test_jsonl_sink_truncates_by_default(tmp_path):
    """A re-run against the default path must not append onto the old
    trajectory (appended steps restart at 1 → validate_records would
    reject the file)."""
    path = tmp_path / "m.jsonl"
    for _ in range(2):
        logger = monitor.MetricsLogger([monitor.JSONLSink(path)])
        logger.log_step(_fake_metrics(step=1))
        logger.close()
    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(records) == 1
    monitor.validate_records(records)
    with pytest.raises(ValueError, match="mode"):
        monitor.JSONLSink(path, mode="x")


def test_jsonl_sink_serializes_nonfinite_as_valid_json(tmp_path):
    """ISSUE 4 satellite regression: json.dumps defaults to
    allow_nan=True, so a NaN/Inf loss used to emit a bare `NaN` token —
    invalid JSON that broke every schema-validating reader.  Non-finite
    floats must land as null + a "<key>_nonfinite" marker."""
    path = tmp_path / "m.jsonl"
    sink = monitor.JSONLSink(path)
    sink.write({"step": 1, "loss": float("nan"),
                "grad_norm": float("inf"),
                "update_norm": float("-inf"), "param_norm": 2.0,
                "nested": {"absmax": float("inf")},
                "row": [1.0, float("nan")]})
    sink.close()
    (line,) = path.read_text().splitlines()
    assert "NaN" not in line and "Infinity" not in line
    rec = json.loads(line)  # valid JSON — would raise on bare NaN
    assert rec["loss"] is None and rec["loss_nonfinite"] == "nan"
    assert rec["grad_norm"] is None and rec["grad_norm_nonfinite"] == "inf"
    assert rec["update_norm_nonfinite"] == "-inf"
    assert rec["param_norm"] == 2.0
    assert rec["nested"]["absmax_nonfinite"] == "inf"
    assert rec["row"] == [1.0, "nan"]


def test_validate_record_accepts_nonfinite_markers():
    """A JSONL round-trip of an overflow window (grad_norm null +
    marker) must validate; a null LOSS must still fail (finiteness is
    required there)."""
    logger = monitor.MetricsLogger([])
    rec = logger.log_step(_fake_metrics())
    ok = dict(rec, grad_norm=None, grad_norm_nonfinite="inf",
              overflowed_this_window=True)
    monitor.validate_record(ok)
    with pytest.raises(ValueError, match="non-finite"):
        monitor.validate_record(dict(rec, loss=None,
                                     loss_nonfinite="nan"))


def test_summary_writer_sink_skips_bools_and_autosteps():
    """ISSUE 4 satellites: bool fields must not land as 0/1 scalar
    curves (isinstance(True, int) is true), and records without a
    "step" must fall back to an internal monotonic step, not pile onto
    tag 0."""
    calls = []

    class W:
        def add_scalar(self, tag, value, step):
            calls.append((tag, value, step))

    sink = monitor.SummaryWriterSink(W())
    sink.write({"step": 4, "loss": 1.0, "overflowed_this_window": True})
    assert calls == [("train/loss", 1.0, 4)]
    calls.clear()
    sink.write({"loss": 2.0})   # no step: 4 -> 5
    sink.write({"loss": 3.0})   # -> 6
    assert calls == [("train/loss", 2.0, 5), ("train/loss", 3.0, 6)]


def test_validate_record_rejects_bad_records():
    logger = monitor.MetricsLogger([])
    rec = logger.log_step(_fake_metrics())
    with pytest.raises(ValueError, match="missing field"):
        monitor.validate_record({k: v for k, v in rec.items()
                                 if k != "grad_norm"})
    bad = dict(rec, loss=float("nan"))
    with pytest.raises(ValueError, match="non-finite"):
        monitor.validate_record(bad)
    with pytest.raises(ValueError, match="non-monotonic"):
        monitor.validate_records([rec, rec])
    with pytest.raises(ValueError, match="monitor_schema_version"):
        monitor.validate_record(dict(rec, monitor_schema_version=999))


def test_console_sink_formats_line():
    lines = []
    sink = monitor.ConsoleSink(print_fn=lines.append)
    monitor.MetricsLogger([sink]).log_step(_fake_metrics())
    assert len(lines) == 1 and "loss 1.2500" in lines[0]
    # step-only records (ScalarWriter tags) stay silent
    sink.write({"step": 3, "fwd-time": 0.1})
    assert len(lines) == 1


def test_scalar_writer_is_summary_writer_compatible(tmp_path):
    """Timers.write targets anything with add_scalar — including the
    monitor stack (the ISSUE 2 adapter requirement)."""
    from apex_tpu.utils.timers import Timers

    path = tmp_path / "t.jsonl"
    writer = monitor.ScalarWriter(monitor.JSONLSink(path))
    t = Timers()
    t("fwd").start()
    t("fwd").stop()
    t.write(["fwd"], writer, iteration=7)
    writer.close()
    (rec,) = [json.loads(l) for l in path.read_text().splitlines()]
    assert rec["step"] == 7 and rec["fwd-time"] >= 0.0


def test_summary_writer_sink_forwards_and_validates():
    calls = []

    class W:
        def add_scalar(self, tag, value, step):
            calls.append((tag, value, step))

    sink = monitor.SummaryWriterSink(W())
    sink.write({"step": 4, "loss": 1.0, "note": "str ignored"})
    assert calls == [("train/loss", 1.0, 4)]
    with pytest.raises(TypeError, match="add_scalar"):
        monitor.SummaryWriterSink(object())


# ------------------------------ flops ------------------------------

def test_transformer_flops_matches_anatomy_formula():
    """Same numbers as scripts/gpt_anatomy.py's per-sublayer accounting
    (attn proj + full-square sdpa + mlp, x3 fwd+bwd, + head)."""
    b, s, h, l, heads, v = 2, 64, 32, 2, 4, 128
    d = h // heads
    attn = (2 * b * s * h * 4 * h + 2 * b * heads * s * s * d * 2) * 3
    mlp = 2 * b * s * h * 8 * h * 3
    head = 2 * b * s * h * v * 3
    want = (attn + mlp) * l + head
    got = monitor.transformer_step_flops(
        hidden=h, num_layers=l, num_heads=heads, vocab_size=v, batch=b,
        seq=s)
    assert got == want

    from apex_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=v, seq_len=s, hidden=h, num_layers=l,
                    num_heads=heads)
    assert monitor.gpt_step_flops(cfg, batch=b) == want


def test_mfu():
    assert monitor.mfu(1e12, 1.0, peak_flops=2e12) == 0.5
    assert monitor.mfu(1e12, 0.0) == 0.0


# ------------------------------ profiler capture ------------------------------

def test_profile_capture_window(tmp_path):
    logdir = str(tmp_path / "trace")
    cap = monitor.profile_capture(range(1, 3), logdir=logdir)
    seen_active = []
    for i in range(5):
        with cap.step(i):
            seen_active.append(cap.active)
            jnp.ones((4, 4)).sum().block_until_ready()
    assert seen_active == [False, True, True, False, False]
    assert not cap.active
    files = [f for _, _, fs in os.walk(logdir) for f in fs]
    assert files, "profiler trace produced no files"
    cap.close()  # idempotent


def test_profile_capture_rejects_gapped_ranges(tmp_path):
    """ISSUE 4 satellite: {3, 10} used to silently capture its [3, 10]
    hull; a capture is ONE contiguous trace window, so gaps now raise
    (two windows = two ProfileCapture objects)."""
    with pytest.raises(ValueError, match="contiguous"):
        monitor.profile_capture({3, 10}, logdir=str(tmp_path))
    with pytest.raises(ValueError, match="contiguous"):
        monitor.ProfileCapture([0, 2, 3], logdir=str(tmp_path))
    # contiguous (in any order, duplicates ok) and empty remain fine
    monitor.ProfileCapture([2, 1, 3, 2], logdir=str(tmp_path))
    monitor.ProfileCapture((), logdir=str(tmp_path))


def test_profile_capture_close_is_safety_net(tmp_path):
    cap = monitor.profile_capture([0, 1], logdir=str(tmp_path / "t"))
    with cap.step(0):
        pass
    assert cap.active  # window still open (last step not reached)
    cap.close()
    assert not cap.active
    cap.close()  # idempotent — the hardening contract (ISSUE 15)
    assert not cap.active


def test_profile_capture_trace_path(tmp_path):
    """ISSUE 15 satellite: trace_path() is None until a window fired,
    then resolves the newest trace.json.gz the profiler wrote — the
    handle `monitor.analyze_trace` composes with."""
    logdir = str(tmp_path / "trace")
    cap = monitor.profile_capture(range(1, 3), logdir=logdir)
    assert cap.trace_path() is None  # nothing armed yet
    for i in range(4):
        with cap.step(i):
            jnp.ones((4, 4)).sum().block_until_ready()
    assert not cap.active
    path = cap.trace_path()
    assert path is not None and path.endswith(".trace.json.gz")
    assert path.startswith(logdir)
    rep = monitor.analyze_trace(path)  # the composed workflow parses
    assert rep.n_events > 0
    # a capture whose window the loop never reached stays None
    cap2 = monitor.profile_capture(range(50, 52),
                                   logdir=str(tmp_path / "t2"))
    for i in range(3):
        with cap2.step(i):
            pass
    cap2.close()
    assert cap2.trace_path() is None


def test_profile_capture_step_reentry_raises(tmp_path):
    """ISSUE 15 satellite: re-entering step() while a trace window is
    open raises the NAMED error (nested scopes would make every trace
    "step" the hull of its children); outside a window the nesting is
    inert and stays permitted."""
    cap = monitor.profile_capture([0, 1], logdir=str(tmp_path / "t"))
    with pytest.raises(monitor.ProfileStepReentryError,
                       match="still open"):
        with cap.step(0):
            with cap.step(1):
                pass
    cap.close()
    # no window armed -> nesting emits no annotations, no error
    inert = monitor.ProfileCapture(())
    with inert.step(0):
        with inert.step(1):
            pass
    # review fix: a nested scope entered BEFORE the window opens is
    # inert too — it must neither arm the trace nested nor reset the
    # guard for its still-open outer scope
    cap2 = monitor.profile_capture(range(1, 3),
                                   logdir=str(tmp_path / "t3"))
    with cap2.step(0):            # pre-window outer scope
        with cap2.step(1):        # in-window but NESTED: stays inert
            pass
        assert not cap2.active    # the window did not open nested
    # ...and the inert nesting did not defeat the guard: at top level
    # the same step DOES arm the trace, and re-entry then raises
    with cap2.step(1):
        assert cap2.active
        with pytest.raises(monitor.ProfileStepReentryError):
            with cap2.step(2):
                pass
    cap2.close()


# ------------------------------ hot-path wiring ------------------------------

def _linear_problem():
    X = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)),
                    jnp.float32)
    Y = X @ jnp.asarray([[1.0], [-2.0], [0.5], [3.0]])

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] - y) ** 2)

    return loss_fn, {"w": jnp.zeros((4, 1))}, (X, Y)


def _train(mesh, metrics_on, steps=5):
    loss_fn, params0, batch = _linear_problem()
    amp_state = amp.initialize(opt_level="O0", loss_scale="dynamic")
    scaler = amp_state.loss_scalers[0]
    opt = FusedAdam(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               metrics=True if metrics_on else None)
    metrics = monitor.init_metrics()
    loss = None
    for _ in range(steps):
        if metrics_on:
            state, scaler, loss, metrics = step(state, scaler, batch,
                                                metrics)
        else:
            state, scaler, loss = step(state, scaler, batch)
    return state, float(loss), metrics


def test_make_train_step_metrics_bitwise_identical_numerics():
    """ISSUE 2 acceptance: metrics= must not perturb training — params
    after 5 steps are BITWISE equal with metrics on vs off."""
    mesh = M.initialize_model_parallel()
    state_off, loss_off, _ = _train(mesh, metrics_on=False)
    state_on, loss_on, _ = _train(mesh, metrics_on=True)
    a = np.asarray(jax.device_get(state_off.params))
    b = np.asarray(jax.device_get(state_on.params))
    assert a.tobytes() == b.tobytes(), "metrics= changed training numerics"
    assert loss_off == loss_on


def test_make_train_step_metrics_values():
    mesh = M.initialize_model_parallel()
    _, loss, m = _train(mesh, metrics_on=True, steps=3)
    assert int(m.step) == 3
    # m.loss is the GLOBAL dp-mean; the step's loss output is one
    # shard's local value — same ballpark, not equal (see below test)
    assert math.isfinite(float(m.loss)) and float(m.loss) > 0
    assert float(m.grad_norm) > 0 and math.isfinite(float(m.grad_norm))
    assert float(m.param_norm) > 0
    assert float(m.update_norm) > 0
    assert float(m.loss_scale) == 65536.0
    assert int(m.overflow_count) == 0
    # float X (samples-counting heuristic): 32 global samples x 3 steps
    assert float(m.tokens_seen) == 96.0


def test_metrics_loss_is_global_dp_mean():
    """The recorded loss must be the FULL-batch mean, not one shard's
    local loss (the raw loss output's P() out-spec takes shard 0's)."""
    mesh = M.initialize_model_parallel()
    loss_fn, params0, (X, Y) = _linear_problem()
    opt = FusedAdam(lr=0.05, use_pallas=False)
    state = opt.init(params0)
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")),
                               metrics=True)
    m = monitor.init_metrics()
    _, _, _, m = step(state, None, (X, Y), m)
    # step 1 runs with params0 = zeros: full-batch MSE = mean(Y^2)
    np.testing.assert_allclose(float(m.loss),
                               float(jnp.mean(Y ** 2)), rtol=1e-5)


def test_forward_backward_no_pipelining_metrics():
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining)

    w = {"w": jnp.asarray([[2.0], [1.0]])}
    batch = jnp.asarray(np.random.default_rng(1).normal(size=(4, 8, 2)),
                        jnp.float32)

    def fwd(p, mb):
        return jnp.mean((mb @ p["w"]) ** 2)

    # legacy shape untouched
    loss0, grads0 = forward_backward_no_pipelining(
        fwd, batch, w, num_microbatches=4)
    m0 = monitor.init_metrics()
    loss, grads, m = jax.jit(
        lambda b, mm: forward_backward_no_pipelining(
            fwd, b, w, num_microbatches=4, metrics=mm))(batch, m0)
    np.testing.assert_allclose(float(loss), float(loss0), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6), grads0, grads)
    assert int(m.step) == 1
    np.testing.assert_allclose(float(m.loss), float(loss0), rtol=1e-6)
    np.testing.assert_allclose(float(m.grad_norm),
                               float(monitor.global_norm(grads0)),
                               rtol=1e-6)
    assert float(m.tokens_seen) == 32.0  # 4 microbatches x 8 samples
    # main_grad_dtype path threads metrics too
    _, _, m2 = forward_backward_no_pipelining(
        fwd, batch, w, num_microbatches=4, metrics=m,
        main_grad_dtype=jnp.float32)
    assert int(m2.step) == 2 and float(m2.tokens_seen) == 64.0


def test_fp16_optimizer_metrics_overflow_accounting():
    from apex_tpu.amp.fp16_optimizer import FP16_Optimizer

    params = {"w": jnp.ones((4,))}
    fp16 = FP16_Optimizer(FusedAdam(lr=0.1, use_pallas=False),
                          dynamic_loss_scale=True)
    state = fp16.init(params)
    m = monitor.init_metrics()

    scale0 = fp16.loss_scale
    good = {"w": jnp.full((4,), 0.5) * scale0}
    params1, state, m = fp16.step(state, good, metrics=m)
    assert int(m.overflow_count) == 0
    np.testing.assert_allclose(float(m.grad_norm), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(m.loss_scale), scale0)
    assert float(m.update_norm) > 0

    bad = {"w": jnp.asarray([1.0, jnp.inf, 1.0, 1.0])}
    params2, state, m = fp16.step(state, bad, metrics=m)
    assert int(m.overflow_count) == 1 and int(m.skipped_steps) == 1
    # the skipped step must not move params
    np.testing.assert_array_equal(np.asarray(params1["w"]),
                                  np.asarray(params2["w"]))
    # grad_norm records the PRE-clip norm (a clipped norm pins at the
    # threshold and can never show the spike)
    scale1 = fp16.loss_scale
    big = {"w": jnp.full((4,), 100.0) * scale1}
    _, state, m = fp16.step(state, big, max_grad_norm=1.0, metrics=m)
    np.testing.assert_allclose(float(m.grad_norm), 200.0, rtol=1e-4)

    # metrics_count_step=False: fields update, step doesn't advance
    # (for composition with a loss-side hook in the same iteration)
    before = int(m.step)
    good2 = {"w": jnp.full((4,), 0.5) * fp16.loss_scale}
    _, state, m = fp16.step(state, good2, metrics=m,
                            metrics_count_step=False)
    assert int(m.step) == before
    np.testing.assert_allclose(float(m.grad_norm), 1.0, rtol=1e-5)

    # legacy 2-tuple return preserved without metrics
    out = fp16.step(state, good)
    assert len(out) == 2
