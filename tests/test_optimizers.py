"""Fused optimizer parity ≡ tests/L0/run_optimizers (test_adam.py,
test_fused_optimizer.py, test_lamb.py): fused flat-buffer kernels vs
independent references (optax for Adam/AdamW, analytic math for SGD),
plus overflow-skip semantics (≡ amp skip_step)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad
from apex_tpu.optimizers.fused_sgd import FusedSGD


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w1": jax.random.normal(k1, (17, 9)),
        "b1": jax.random.normal(k2, (9,)),
        "w2": jax.random.normal(k3, (9, 4)),
    }


def _grads(key, params):
    ks = jax.random.split(key, len(jax.tree_util.tree_leaves(params)))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return jax.tree_util.tree_unflatten(
        treedef, [jax.random.normal(k, l.shape) for k, l in zip(ks, leaves)])


def _assert_tree_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


@pytest.mark.parametrize("weight_decay", [0.0, 0.01])
def test_fused_adam_vs_optax_adamw(weight_decay):
    params = _params(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-2, weight_decay=weight_decay, adam_w_mode=True,
                    use_pallas=True)
    state = opt.init(params)

    ref = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                      weight_decay=weight_decay)
    ref_state = ref.init(params)
    ref_params = params

    for i in range(5):
        grads = _grads(jax.random.PRNGKey(10 + i), params)
        new_params, state = opt.step(state, grads)
        updates, ref_state = ref.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        _assert_tree_close(new_params, ref_params, rtol=1e-5, atol=1e-6)


def test_fused_adam_l2_mode_vs_optax():
    params = _params(jax.random.PRNGKey(1))
    opt = FusedAdam(lr=1e-2, weight_decay=0.1, adam_w_mode=False,
                    use_pallas=True)
    state = opt.init(params)
    ref = optax.chain(optax.add_decayed_weights(0.1),
                      optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
                      optax.scale(-1e-2))
    ref_state = ref.init(params)
    ref_params = params
    for i in range(3):
        grads = _grads(jax.random.PRNGKey(20 + i), params)
        new_params, state = opt.step(state, grads)
        updates, ref_state = ref.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        _assert_tree_close(new_params, ref_params, rtol=1e-5, atol=1e-6)


def test_adam_overflow_skip():
    params = _params(jax.random.PRNGKey(2))
    opt = FusedAdam(lr=1e-2, use_pallas=True)
    state = opt.init(params)
    grads = _grads(jax.random.PRNGKey(3), params)
    new_params, new_state = opt.step(state, grads, found_inf=True)
    _assert_tree_close(new_params, params)
    assert int(new_state.step) == 0
    # and inv_scale is applied when not skipped
    p1, _ = opt.step(state, grads, inv_scale=0.5)
    p2, _ = opt.step(state, jax.tree_util.tree_map(lambda g: 0.5 * g, grads))
    _assert_tree_close(p1, p2)


def test_fused_sgd_analytic():
    params = {"w": jnp.array([1.0, -2.0, 3.0])}
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=0.01, use_pallas=True)
    state = opt.init(params)
    # manual torch-SGD math
    p = np.array([1.0, -2.0, 3.0])
    buf = None
    for i in range(4):
        g = np.array([0.5, 0.1, -0.2]) * (i + 1)
        grads = {"w": jnp.asarray(g, jnp.float32)}
        new_params, state = opt.step(state, grads)
        d = g + 0.01 * p
        buf = d.copy() if buf is None else 0.9 * buf + d
        p = p - 0.1 * buf
        np.testing.assert_allclose(np.asarray(new_params["w"]), p,
                                   rtol=1e-5, atol=1e-6)
        params = new_params


def test_fused_sgd_no_momentum():
    params = {"w": jnp.arange(4.0)}
    opt = FusedSGD(lr=0.5, use_pallas=True)
    state = opt.init(params)
    new_params, _ = opt.step(state, {"w": jnp.ones(4)})
    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.arange(4.0) - 0.5, rtol=1e-6)


def test_fused_adagrad_vs_optax():
    params = _params(jax.random.PRNGKey(4))
    opt = FusedAdagrad(lr=0.05, eps=1e-10, use_pallas=True)
    state = opt.init(params)
    ref = optax.adagrad(0.05, initial_accumulator_value=0.0, eps=1e-10)
    ref_state = ref.init(params)
    ref_params = params
    for i in range(3):
        grads = _grads(jax.random.PRNGKey(30 + i), params)
        new_params, state = opt.step(state, grads)
        updates, ref_state = ref.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        _assert_tree_close(new_params, ref_params, rtol=1e-4, atol=1e-5)


def test_fused_lamb_properties():
    """LAMB lacks a drop-in optax twin with apex semantics; check the
    defining properties instead: trust-ratio-scaled direction equals the
    Adam-style update direction per tensor, and grad-norm clipping."""
    params = _params(jax.random.PRNGKey(5))
    opt = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=1e9,
                    use_pallas=True)
    state = opt.init(params)
    grads = _grads(jax.random.PRNGKey(6), params)
    new_params, state2 = opt.step(state, grads)

    # per-tensor: delta ∝ u with factor lr * ||w|| / ||u||
    for key in params:
        w = np.asarray(params[key], np.float64)
        delta = np.asarray(new_params[key], np.float64) - w
        g = np.asarray(grads[key], np.float64)
        m = 0.1 * g          # (1-b1)*g, b1=0.9
        v = 0.001 * g * g
        mhat = m / (1 - 0.9)
        vhat = v / (1 - 0.999)
        u = mhat / (np.sqrt(vhat) + 1e-6)
        wn = np.linalg.norm(w.ravel())
        un = np.linalg.norm(u.ravel())
        expect = -1e-2 * (wn / un) * u
        np.testing.assert_allclose(delta, expect, rtol=1e-3, atol=1e-6)


def test_fused_lamb_clipping():
    params = {"w": jnp.ones((4,))}
    opt = FusedLAMB(lr=1e-2, weight_decay=0.0, max_grad_norm=0.1,
                    use_pallas=True)
    state = opt.init(params)
    big = {"w": jnp.full((4,), 100.0)}
    small = {"w": jnp.full((4,), 100.0) * (0.1 / 200.0)}  # norm 0.1 dir same
    p1, _ = opt.step(state, big)
    state2 = opt.init(params)
    p2, _ = opt.step(state2, small)
    # clipped big grad ≡ grad with norm exactly max_grad_norm
    _assert_tree_close(p1, p2, rtol=1e-4, atol=1e-6)


def test_fused_novograd_smoke():
    params = _params(jax.random.PRNGKey(7))
    opt = FusedNovoGrad(lr=1e-2, betas=(0.95, 0.98), weight_decay=0.01,
                        use_pallas=True)
    state = opt.init(params)
    loss0 = None
    # v is per-tensor: shape == number of leaves
    assert state.exp_avg_sq.shape == (3,)
    for i in range(3):
        grads = _grads(jax.random.PRNGKey(40 + i), params)
        params, state = opt.step(state, grads)
    assert int(state.step) == 3
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(params))


def test_fused_lamb_grad_averaging_off():
    """grad_averaging=False must use beta3=1 in the m update
    (≡ the beta3 coefficient of multi_tensor_lamb.cu): with beta1=0.9
    the first-step momentum is 10x larger than the averaged variant."""
    params = _params(jax.random.PRNGKey(9))
    grads = _grads(jax.random.PRNGKey(10), params)
    kw = dict(lr=1e-3, betas=(0.9, 0.999), max_grad_norm=0.0,
              use_pallas=True)
    opt_avg = FusedLAMB(grad_averaging=True, **kw)
    opt_raw = FusedLAMB(grad_averaging=False, **kw)
    s_avg = opt_avg.init(params)
    s_raw = opt_raw.init(params)
    _, s_avg = opt_avg.step(s_avg, grads)
    _, s_raw = opt_raw.step(s_raw, grads)
    n = opt_avg.spec.total
    np.testing.assert_allclose(np.asarray(s_raw.exp_avg[:n]),
                               np.asarray(s_avg.exp_avg[:n]) * 10.0,
                               rtol=1e-5)


def test_master_dtype_bf16_trains():
    """O3-style pure-bf16 optimizer state (master_dtype=bfloat16): state
    buffers are bf16 (6 B/param for Adam) and training still converges
    on a least-squares problem; the update math stays fp32 in-kernel."""
    import jax.numpy as jnp
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
    y = x @ jax.random.normal(jax.random.PRNGKey(2), (16, 4))

    def loss_fn(p):
        return jnp.mean((x @ p["w"] - y) ** 2)

    params = {"w": w}
    opt = FusedAdam(lr=5e-2, master_dtype=jnp.bfloat16, use_pallas=True)
    state = opt.init(params)
    assert state.params.dtype == jnp.bfloat16
    assert state.exp_avg.dtype == jnp.bfloat16
    l0 = float(loss_fn(params))
    p = params
    for _ in range(60):
        g = jax.grad(loss_fn)(p)
        p, state = opt.step(state, g)
    assert float(loss_fn(p)) < l0 * 0.2

    opt2 = FusedSGD(lr=1e-2, momentum=0.9, master_dtype=jnp.bfloat16,
                    use_pallas=True)
    s2 = opt2.init(params)
    assert s2.params.dtype == jnp.bfloat16
    p = params
    for _ in range(60):
        g = jax.grad(loss_fn)(p)
        p, s2 = opt2.step(s2, g)
    assert float(loss_fn(p)) < l0 * 0.5


def test_fused_lamb_bf16_master_tracks_fp32():
    """bf16-state LAMB (master_dtype) must track the fp32-state update
    to bf16 resolution — the BERT-Large HBM-traffic dial (round 4)."""
    import jax
    from apex_tpu.optimizers.fused_lamb import FusedLAMB
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (256, 128)),
              "b": jnp.zeros((128,))}
    grads = jax.tree_util.tree_map(
        lambda x: 0.01 * jax.random.normal(jax.random.PRNGKey(1),
                                           x.shape), params)

    def run(dt):
        opt = FusedLAMB(lr=1e-2, weight_decay=0.01, master_dtype=dt,
                        use_pallas=False)
        state = opt.init(params)
        p = None
        for _ in range(5):
            p, state = opt.step(state, grads)
        return p

    p32 = run(jnp.float32)
    p16 = run(jnp.bfloat16)
    for a, e in zip(jax.tree_util.tree_leaves(p16),
                    jax.tree_util.tree_leaves(p32)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(e, np.float32),
                                   rtol=2e-2, atol=2e-2)


# ------------- per-leaf hyperparameters (param-group parity) ----------------
# ≡ the reference's param_groups with distinct lr/weight_decay
# (apex/optimizers/fused_adam.py:156-303) and the no-decay-for-bias/LN
# groups of _get_params_for_weight_decay_optimization
# (apex/transformer/pipeline_parallel/schedules/common.py:162-196).


def test_fused_adam_wd_mask_vs_optax_masked():
    """FusedAdam(wd_mask=...) over one flat buffer must match optax
    adamw with the same mask (the standard two-group BERT/GPT recipe)."""
    from apex_tpu.transformer.pipeline_parallel.common import (
        get_params_for_weight_decay_optimization,
    )

    params = _params(jax.random.PRNGKey(0))
    mask = get_params_for_weight_decay_optimization(params)
    assert jax.tree_util.tree_leaves(mask).count(False) >= 1  # b1 no-decay
    opt = FusedAdam(lr=1e-2, weight_decay=0.1, wd_mask=mask,
                    use_pallas=True)
    state = opt.init(params)

    ref = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8,
                      weight_decay=0.1, mask=mask)
    ref_state = ref.init(params)
    ref_params = params

    for i in range(5):
        grads = _grads(jax.random.PRNGKey(30 + i), params)
        new_params, state = opt.step(state, grads)
        updates, ref_state = ref.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        _assert_tree_close(new_params, ref_params, rtol=1e-5, atol=1e-6)


def test_fused_adam_lr_scales_per_leaf_reference():
    """Per-leaf lr multipliers: each leaf must track an independent
    single-leaf FusedAdam run at lr * scale (leaves are uncoupled in
    Adam, so the per-leaf runs are an exact oracle)."""
    params = _params(jax.random.PRNGKey(1))
    scales = {"w1": 1.0, "b1": 0.25, "w2": 2.0}
    mask = {"w1": True, "b1": False, "w2": True}
    opt = FusedAdam(lr=1e-2, weight_decay=0.05, wd_mask=mask,
                    lr_scales=scales, use_pallas=True)
    state = opt.init(params)

    refs = {}
    for name in params:
        r = FusedAdam(lr=1e-2 * scales[name],
                      weight_decay=0.05 if mask[name] else 0.0,
                      use_pallas=False)
        refs[name] = (r, r.init({name: params[name]}))

    cur = params
    for i in range(4):
        grads = _grads(jax.random.PRNGKey(50 + i), params)
        cur, state = opt.step(state, grads)
        for name in params:
            r, rs = refs[name]
            rp, rs = r.step(rs, {name: grads[name]})
            refs[name] = (r, rs)
            np.testing.assert_allclose(
                np.asarray(cur[name]), np.asarray(rp[name]),
                rtol=1e-5, atol=1e-6, err_msg=name)


def test_fused_adam_seg_l2_mode():
    """L2 (non-AdamW) mode routes the per-leaf decay through the
    gradient; parity vs optax add_decayed_weights masked."""
    params = _params(jax.random.PRNGKey(2))
    mask = {"w1": True, "b1": False, "w2": True}
    opt = FusedAdam(lr=1e-2, weight_decay=0.1, adam_w_mode=False,
                    wd_mask=mask, use_pallas=True)
    state = opt.init(params)
    ref = optax.chain(
        optax.masked(optax.add_decayed_weights(0.1), mask),
        optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
        optax.scale(-1e-2))
    ref_state = ref.init(params)
    ref_params = params
    for i in range(4):
        grads = _grads(jax.random.PRNGKey(70 + i), params)
        new_params, state = opt.step(state, grads)
        updates, ref_state = ref.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        _assert_tree_close(new_params, ref_params, rtol=1e-5, atol=1e-6)


def test_fused_adam_seg_pallas_matches_jnp():
    """Interpret-mode seg kernel ≡ the jnp per-element fallback."""
    params = _params(jax.random.PRNGKey(3))
    mask = {"w1": True, "b1": False, "w2": True}
    scales = {"w1": 0.5, "b1": 1.0, "w2": 1.5}

    def run(up):
        opt = FusedAdam(lr=1e-2, weight_decay=0.1, wd_mask=mask,
                        lr_scales=scales, use_pallas=up)
        state = opt.init(params)
        p = None
        for i in range(3):
            p, state = opt.step(state,
                                _grads(jax.random.PRNGKey(90 + i), params))
        return p

    _assert_tree_close(run(True), run(False), rtol=1e-6, atol=1e-7)


def test_fused_lamb_wd_mask_per_leaf_reference():
    """LAMB with a no-decay mask: with clipping off, leaves are
    uncoupled, so each must track a single-leaf FusedLAMB at its own
    weight decay (trust ratio is per-tensor already)."""
    params = _params(jax.random.PRNGKey(4))
    mask = {"w1": True, "b1": False, "w2": True}
    scales = {"w1": 1.0, "b1": 2.0, "w2": 0.5}
    opt = FusedLAMB(lr=1e-2, weight_decay=0.1, max_grad_norm=0.0,
                    wd_mask=mask, lr_scales=scales, use_pallas=True)
    state = opt.init(params)

    refs = {}
    for name in params:
        r = FusedLAMB(lr=1e-2 * scales[name],
                      weight_decay=0.1 if mask[name] else 0.0,
                      max_grad_norm=0.0, use_pallas=False)
        refs[name] = (r, r.init({name: params[name]}))

    cur = params
    for i in range(4):
        grads = _grads(jax.random.PRNGKey(110 + i), params)
        cur, state = opt.step(state, grads)
        for name in params:
            r, rs = refs[name]
            rp, rs = r.step(rs, {name: grads[name]})
            refs[name] = (r, rs)
            np.testing.assert_allclose(
                np.asarray(cur[name]), np.asarray(rp[name]),
                rtol=1e-5, atol=1e-6, err_msg=name)


def test_per_leaf_tree_mismatch_raises():
    params = _params(jax.random.PRNGKey(5))
    opt = FusedAdam(lr=1e-2, weight_decay=0.1,
                    wd_mask={"w1": True, "b1": False})  # missing w2
    with pytest.raises(ValueError, match="leaves"):
        opt.init(params)
