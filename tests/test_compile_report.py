"""Compile & HBM observatory tests (ISSUE 5): `analyze_step` ->
`CompileReport` under a CPU backend (optional backend fields None, no
crash), donation verification (a deliberately un-donated buffer is
flagged), the flops-accounting cross-check (a seeded divergence is
flagged), the recompile sentry (an induced shape-change retrace is
caught), crash-dump attachment of the report, and the acceptance line:
`ddp.make_train_step` numerics are bitwise identical with the
observatory on vs off.

Everything here runs tiny jits — the whole file must stay cheap (the
tier-1 window is a dot budget; this file sorts early in the alphabet).
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import monitor
from apex_tpu.monitor import compile as obs
from apex_tpu.monitor import trace
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.parallel import ddp
from apex_tpu.parallel import mesh as M


# ----------------------------- analyze_step -----------------------------

def _donating_fn():
    return jax.jit(lambda s, x: (s + x, (s * x).sum()),
                   donate_argnums=(0,))


def test_analyze_step_populated_on_cpu():
    """Acceptance: a populated CompileReport under JAX_PLATFORMS=cpu —
    backend fields that CPU XLA does report are ints, device memory is
    None, nothing crashes, and the dict form is JSON-serializable."""
    f = _donating_fn()
    s = jnp.ones((64, 64))
    rep = obs.analyze_step(f, (s, s), donated=(0,),
                           arg_names=("opt_state", "batch"))
    assert rep.backend == "cpu"
    assert isinstance(rep.argument_bytes, int) and rep.argument_bytes > 0
    assert isinstance(rep.flops, float) and rep.flops > 0
    assert rep.arg_bytes == {"opt_state": 64 * 64 * 4,
                             "batch": 64 * 64 * 4}
    # CPU allocator does not report: watermark fields None, no crash
    assert obs.device_memory_stats() is None
    wm = obs.hbm_watermarks()
    assert wm == {"hbm_bytes_in_use": None,
                  "hbm_peak_bytes_in_use": None,
                  "hbm_bytes_limit": None}
    json.dumps(rep.to_dict())  # the crash-dump attachment form
    text = obs.render_budget_table(rep)
    assert "HBM budget" in text


def test_analyze_step_accepts_shape_structs():
    """The audit never needs device buffers: ShapeDtypeStructs lower
    and compile the same program."""
    f = _donating_fn()
    sds = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    rep = obs.analyze_step(f, (sds, sds), donated=(0,))
    assert rep.donated_bytes == 32 * 32 * 4
    assert rep.donation_ok is True


def test_donation_verification_flags_undonated():
    """The 'second state copy alive' failure: claiming donation on a
    jit that does NOT donate must flag — donated bytes never show up
    as output aliasing."""
    f_nodonate = jax.jit(lambda s, x: (s + x, (s * x).sum()))
    s = jnp.ones((64, 64))
    rep = obs.analyze_step(f_nodonate, (s, s), donated=(0,))
    assert rep.donation_ok is False
    assert rep.undonated_bytes == rep.donated_bytes > 0
    assert "DONATION FAILED" in obs.render_budget_table(rep)
    # and the donating twin of the same program verifies clean
    ok = obs.analyze_step(_donating_fn(), (s, s), donated=(0,))
    assert ok.donation_ok is True and ok.undonated_bytes == 0


def test_flops_crosscheck_flags_seeded_divergence():
    """A matmul whose analytic count is correct passes; the same
    program scored against a 3x-wrong analytic count is flagged —
    the gate that validates every published MFU number."""
    m = k = n = 128
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((m, k))
    b = jnp.ones((k, n))
    good = obs.analyze_step(f, (a, b), analytic_flops=2 * m * k * n)
    assert good.flops_ok is True
    assert good.flops_divergence < 0.10
    bad = obs.analyze_step(f, (a, b), analytic_flops=6 * m * k * n)
    assert bad.flops_ok is False
    assert "FLOPS ACCOUNTING DIVERGES" in obs.render_budget_table(bad)


def test_analyze_step_rejects_unloweable():
    with pytest.raises(TypeError, match="lower"):
        obs.analyze_step(lambda x: x, (jnp.ones(3),))


# --------------------------- recompile sentry ---------------------------

def test_sentry_catches_induced_retrace():
    """Acceptance: an induced shape-change retrace is caught, its
    signature recorded, and — after mark_steady — warned once and
    counted as a steady-state recompile."""
    sent = obs.RecompileSentry(jax.jit(lambda x: x * 2), name="t")
    sent(jnp.ones(4))
    sent(jnp.ones(4))                       # cache hit: no new compile
    assert sent.n_compiles == 1 and sent.calls == 2
    sent.mark_steady()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        sent(jnp.ones(8))                   # the induced retrace
        sent(jnp.ones(16))                  # second one: no new warning
    assert sent.n_compiles == 3
    assert sent.steady_recompiles == 2
    assert len([x for x in w if issubclass(x.category,
                                           RuntimeWarning)]) == 1
    ev = sent.events[-1]
    assert ev["steady_state"] and "(16,)" in ev["signature"]
    assert sent.summary()["n_compiles"] == 3


def test_sentry_events_land_in_flight_ring(tmp_path):
    rec = trace.FlightRecorder(tmp_path / "f.json", capacity=2)
    sent = obs.RecompileSentry(jax.jit(lambda x: x + 1), recorder=rec,
                               warn=False)
    sent(jnp.ones(4))
    rep = rec.report()
    assert len(rep["compile_events"]) == 1
    assert rep["compile_events"][0]["call"] == 1
    trace.validate_report(rep)


# ----------------------- ddp train-step integration -----------------------

def _linear_step(mesh, metrics=None):
    X = jnp.asarray(np.random.default_rng(0).normal(size=(32, 4)),
                    jnp.float32)
    Y = X @ jnp.asarray([[1.0], [-2.0], [0.5], [3.0]])

    def loss_fn(p, b):
        x, y = b
        return jnp.mean((x @ p["w"] - y) ** 2)

    opt = FusedAdam(lr=0.05, use_pallas=False)
    state = opt.init({"w": jnp.zeros((4, 1))})
    step = ddp.make_train_step(loss_fn, opt, mesh,
                               batch_spec=(P("dp"), P("dp")),
                               metrics=metrics)
    return step, state, (X, Y)


def test_ddp_step_audits_and_stays_bitwise_identical():
    """Acceptance: analyze_step on the make_train_step handles works
    (budget classified by arg name, donation verified) AND training
    is bitwise identical whether or not the observatory ran."""
    mesh = M.initialize_model_parallel()
    step, state, batch = _linear_step(mesh)
    assert step.arg_names == ("opt_state", "scaler_state", "batch")
    assert step.donate_argnums == (0,)
    rep = obs.analyze_step(step, (state, None, batch))
    assert rep.budget["params"] > 0
    assert rep.budget["optimizer_state"] > rep.budget["params"]
    assert rep.donation_ok is True

    # plain run vs audited + sentry-wrapped run: same bits out.  The
    # audit above only LOWERED (no execution) — `state` is untouched
    # and safe to train from; `plain` is a separately-built twin with
    # its own identically-initialized state.
    plain, s_plain, _ = _linear_step(mesh)
    for _ in range(3):
        s_plain, _, _ = plain(s_plain, None, batch)
    sent = obs.RecompileSentry(step, warn=False)
    s_obs = state
    for _ in range(3):
        s_obs, _, _ = sent(s_obs, None, batch)
    a = np.asarray(jax.device_get(s_plain.params))
    b = np.asarray(jax.device_get(s_obs.params))
    assert a.tobytes() == b.tobytes(), "observatory changed numerics"
    assert sent.n_compiles >= 1 and sent.steady_recompiles == 0


def test_logger_stamps_observatory_fields(tmp_path):
    """MetricsLogger(sentry=, memory=True): n_compiles + null hbm_*
    fields in the record, schema-valid (v3 optional fields)."""
    sent = obs.RecompileSentry(jax.jit(lambda x: x), warn=False)
    sent(jnp.ones(2))
    path = tmp_path / "m.jsonl"
    logger = monitor.MetricsLogger([monitor.JSONLSink(path)],
                                   sentry=sent, memory=True)
    m = monitor.init_metrics()._replace(step=jnp.asarray(1, jnp.int32))
    rec = logger.log_step(m)
    logger.close()
    assert rec["n_compiles"] == 1
    assert rec["hbm_bytes_in_use"] is None  # CPU: null, schema-legal
    (line,) = path.read_text().splitlines()
    monitor.validate_record(json.loads(line))


def test_validate_record_rejects_bad_observatory_fields():
    base = {"monitor_schema_version": monitor.SCHEMA_VERSION, "step": 1,
            "loss": 1.0, "grad_norm": 0.1, "param_norm": 1.0,
            "update_norm": 0.0, "loss_scale": 1.0, "overflow_count": 0,
            "skipped_steps": 0, "tokens_seen": 0.0, "step_time_ms": 1.0,
            "tokens_per_sec": 1.0, "mfu": 0.0}
    monitor.validate_record(dict(base, n_compiles=2,
                                 hbm_bytes_in_use=None))
    with pytest.raises(ValueError, match="n_compiles"):
        monitor.validate_record(dict(base, n_compiles=None))
    with pytest.raises(ValueError, match="hbm_bytes_in_use"):
        monitor.validate_record(dict(base, hbm_bytes_in_use=1.5))
    with pytest.raises(ValueError, match="scalar"):
        monitor.validate_record(dict(base, hbm_custom={"nested": 1}))


# --------------------------- crash-dump forensics ---------------------------

def test_crash_dump_attaches_report_and_classifies_oom(tmp_path):
    """Acceptance: guard() on a RESOURCE_EXHAUSTED death dumps with
    oom=true, the attached CompileReport, and the budget table renders
    from the artifact."""
    f = _donating_fn()
    s = jnp.ones((16, 16))
    rep = obs.analyze_step(f, (s, s), donated=(0,))
    path = tmp_path / "flight.json"
    rec = trace.FlightRecorder(path, capacity=4)
    rec.attach_compile_report(rep)
    rec.record(0, metrics={"step": 0, "loss": 1.0})
    with pytest.raises(RuntimeError):
        with rec.guard():
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 12GB")
    data = json.loads(path.read_text())
    trace.validate_report(data)
    assert data["oom"] is True
    assert data["compile_report"]["donation_ok"] is True
    text = trace.render_report(data)
    assert "OOM" in text and "HBM budget" in text
    # a non-OOM death stays oom=false
    with pytest.raises(ValueError):
        with rec.guard():
            raise ValueError("not an oom")
    assert json.loads(path.read_text())["oom"] is False


def test_validate_report_requires_observatory_fields(tmp_path):
    rec = trace.FlightRecorder(tmp_path / "r.json", capacity=2)
    rep = rec.report()
    trace.validate_report(rep)
    for missing in ("oom", "compile_report", "compile_events", "memory"):
        with pytest.raises(ValueError, match="missing report field"):
            trace.validate_report(
                {k: v for k, v in rep.items() if k != missing})


# ------------------------------ peak table ------------------------------

def test_device_peak_flops_table_and_fallback():
    assert monitor.device_peak_flops("TPU v4") == 275e12
    assert monitor.device_peak_flops("TPU v5 lite") == 197e12
    assert monitor.device_peak_flops("TPU v5e") == 197e12
    assert monitor.device_peak_flops("TPU v5p") == 459e12
    assert monitor.device_peak_flops("TPU v6 lite") == 918e12
    # the documented fallback: unknown kinds (cpu) -> v5e peak, so
    # existing numbers don't move
    assert monitor.device_peak_flops("cpu") == monitor.V5E_BF16_PEAK
    assert monitor.device_peak_flops() == monitor.V5E_BF16_PEAK
    # explicit override wins outright
    assert monitor.device_peak_flops("TPU v4", override=1e12) == 1e12
    # mfu resolves the same table when peak_flops is omitted
    assert monitor.mfu(monitor.V5E_BF16_PEAK, 1.0) == pytest.approx(1.0)


# ------------------------------ watermarks ------------------------------

def test_hbm_watermarks_tolerates_fake_stats_shapes():
    """PR 5 NOTE hardening: the TPU runtime's memory_stats() key set is
    an assumption — missing keys become None, extra integer keys pass
    through under the hbm_ prefix, and non-coercible values cost the
    FIELD, never the record."""
    from apex_tpu.monitor.compile import watermarks as wm

    # the assumed canonical shape
    full = {"bytes_in_use": 7, "peak_bytes_in_use": 9, "bytes_limit": 11}
    assert wm.hbm_watermarks(stats=full) == {
        "hbm_bytes_in_use": 7, "hbm_peak_bytes_in_use": 9,
        "hbm_bytes_limit": 11}

    # missing + extra + garbage, all at once
    weird = {"bytes_in_use": 3.0,            # float: coerces
             "bytes_limit": "16GiB",         # garbage: None
             "bytes_reserved": 42,           # unknown int: passthrough
             "allocator": "bfc",             # unknown str: dropped
             "oom": True,                    # bool is not a byte count
             7: 99}                          # non-str key: dropped
    got = wm.hbm_watermarks(stats=weird)
    assert got == {"hbm_bytes_in_use": 3,
                   "hbm_peak_bytes_in_use": None,
                   "hbm_bytes_limit": None,
                   "hbm_bytes_reserved": 42}

    # the three canonical fields are ALWAYS present (empty stats too),
    # and every emitted value is schema-legal (int or None)
    empty = wm.hbm_watermarks(stats={})
    assert set(empty) == {f"hbm_{k}" for k in wm.WATERMARK_FIELDS}
    assert all(v is None for v in empty.values())


def test_budget_classifies_kv_cache_row():
    """ISSUE 8 satellite: args named `*kv_cache*`/`*page*` land in the
    budget's `kv_cache` class (the serve report must price the pool
    separately from weights — it scales with concurrent users, not
    model size), a bare `params` arg lands in `params`, and training
    steps without a pool keep a zero row that the renderer hides."""

    def step(params, kv_cache, page_table, state, batch):
        o = (params["w"] * kv_cache["k_pages"].sum()
             + page_table.sum() + state.sum() + batch.sum())
        return o.sum()

    jitted = jax.jit(step)
    args = (
        {"w": jnp.ones((8, 8))},                       # 256 B
        {"k_pages": jnp.zeros((4, 16, 8), jnp.float32),  # 2048 B
         "v_pages": jnp.zeros((4, 16, 8), jnp.float32)},  # 2048 B
        jnp.zeros((16,), jnp.int32),                   # 64 B (page arg)
        jnp.zeros((32,), jnp.float32),
        jnp.zeros((4, 4), jnp.float32),
    )
    rep = monitor.analyze_step(
        jitted, args, donated=(),
        arg_names=("params", "kv_cache", "page_table", "state", "batch"))
    assert rep.budget["kv_cache"] == 4096 + 64
    assert rep.budget["params"] == 256
    assert rep.budget["inputs"] == 32 * 4 + 16 * 4
    table = monitor.render_budget_table(rep)
    assert "kv cache (pages)" in table

    # a pool-free program keeps kv_cache == 0 and the renderer drops
    # the row (training tables unchanged)
    rep2 = monitor.analyze_step(
        jitted, args, donated=(),
        arg_names=("a", "b", "c", "d", "e"))
    assert rep2.budget["kv_cache"] == 0
    assert "kv cache" not in monitor.render_budget_table(rep2)


def test_serve_decode_step_budget_prices_pool():
    """End-to-end: the flagship serve engine's decode step audits with
    the pool priced in the kv_cache row — exactly the engine's own
    pool bytes — and donation of cache + state verified."""
    from apex_tpu.serve import build_flagship_engine

    eng = build_flagship_engine(False, n_slots=4)
    rep = monitor.analyze_step(eng.decode_step,
                               (eng.params, eng.kv, eng.state))
    assert rep.budget["kv_cache"] == eng.kv_config.pool_bytes()
    assert rep.budget["params"] > 0
    assert rep.donation_ok is True
